"""Run-level goodput aggregation: every second of a (possibly much-
restarted) run accounted for.

Three artifact kinds live in the run dir, written by different parties:

* ``.progress_rank{k}.json`` — per-rank BEACON, overwritten every
  optimizer step by the trainer: current step, wall-clock, and the
  in-attempt :class:`~..utils.perf.GoodputTracker` summary so far. A
  SIGKILLed attempt's last beacon is its flight recorder.
* ``goodput_attempt{A:03d}.json`` — rank 0's final goodput record for a
  CLEANLY exited attempt (written at ``run_loop`` exit).
* ``attempts.jsonl`` — the LAUNCHER's structured per-attempt log:
  attempt index, exit code, spawn/exit wall-clock, step progress
  (from the beacons), downtime before the attempt, resume overhead, and
  a post-mortem snapshot of rank 0's beacon.

:func:`aggregate_run` folds all three into one decomposition::

    wall ≈ useful + startup + restore + compile + save + data_stall
           + recompute + hang + lost + downtime

with ``goodput = useful / wall`` — the bench's acceptance metric.
``hang`` is LAUNCHER-attributed (the attempt record's ``hang_s``): the
window between an attempt's last observed progress and the hang
watchdog killing it — time a silently wedged worker burned while still
"alive". Without the watchdog that window is unbounded; with it, it is
measured and bounded by ``--hang_timeout_s``.

The SERVING half (ISSUE 11) mirrors the same discipline for a replica
fleet. A fleet dir holds one ``replica_{i}`` run dir per replica (each
supervised by its own launcher ring, so ``attempts.jsonl`` + beacons come
for free) plus the router's durable request ``journal.jsonl``; replica
workers write ``serving_attempt{A:03d}.json`` sidecars (clean exit) and a
``serving`` snapshot inside their beacons (the kill flight recorder).
:func:`aggregate_serving` folds the whole fleet into::

    serving wall == serving + drain + replay + paid_idle + swap
                    + downtime + lost

with ``accounted_frac == 1.0`` by construction — ``replay`` is the
serving-shaped time whose output was thrown away (work a killed replica
did on requests that later re-ran on a sibling, measured by the router
into the journal), ``drain``/``swap`` are the hot-swap windows, and
``lost`` is attempt wall covered by no snapshot.

Import-light (no jax): the launcher reads and writes these artifacts
before/after worker processes exist. The fleet-dir layout constants live
HERE (not in serving/) for the same reason the beacon naming does: the
launcher-adjacent readers must not pay a jax import to find a file.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Any, Dict, List, Optional

__all__ = [
    "beacon_path", "read_beacons", "beacon_max_step", "beacon_mtimes",
    "attempts_path", "append_attempt", "read_attempts",
    "goodput_record_path", "read_goodput_records", "aggregate_run",
    "replica_dir", "replica_id", "list_replica_dirs",
    "stage_dir", "stage_id", "list_stage_dirs",
    "serving_journal_path",
    "read_journal", "serving_record_path", "read_serving_records",
    "aggregate_serving",
]

_BEACON_RE = re.compile(r"\.progress_rank(\d+)\.json$")

# Goodput categories summed across attempts (mirrors
# perf.GoodputTracker.CATEGORIES + the data_stall merged at summary time;
# link_wait is the MPMD stages' send/recv-blocked time — mpmd/link.py —
# booked by the jax-free HostGoodput, zero for single-program attempts).
_CATEGORIES = ("startup_s", "setup_s", "restore_s", "compile_s", "save_s",
               "data_stall_s", "recompute_s", "link_wait_s")


def beacon_path(run_dir: str, rank: int) -> str:
    return os.path.join(run_dir, f".progress_rank{rank}.json")


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None  # torn mid-replace read / dead file: skip


def read_beacons(run_dir: str) -> Dict[int, dict]:
    out: Dict[int, dict] = {}
    for path in glob.glob(os.path.join(run_dir, ".progress_rank*.json")):
        m = _BEACON_RE.search(path)
        payload = _read_json(path) if m else None
        if m and isinstance(payload, dict):
            out[int(m.group(1))] = payload
    return out


def beacon_mtimes(run_dir: str) -> Dict[str, float]:
    """mtime per beacon file — the launcher hang watchdog's liveness
    signal (the trainer atomically replaces each rank's beacon every
    step, so a frozen newest-mtime means NO rank is advancing). Lives
    here so the beacon naming has exactly one owner; a beacon caught
    mid-replace is skipped and picked up next poll."""
    out: Dict[str, float] = {}
    for path in glob.glob(os.path.join(run_dir, ".progress_rank*.json")):
        try:
            out[path] = os.stat(path).st_mtime
        except OSError:
            pass
    return out


def beacon_max_step(run_dir: str) -> int:
    """Highest step ANY rank's beacon ever reported — the resume boundary
    for recompute accounting (steps at or below it were already paid for
    by an earlier attempt)."""
    return max((int(b.get("step", 0)) for b in read_beacons(run_dir).values()),
               default=0)


def attempts_path(run_dir: str) -> str:
    return os.path.join(run_dir, "attempts.jsonl")


def append_attempt(run_dir: str, record: dict) -> None:
    with open(attempts_path(run_dir), "a") as f:
        f.write(json.dumps(record) + "\n")


def read_attempts(run_dir: str) -> List[dict]:
    path = attempts_path(run_dir)
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    pass  # torn tail line from a killed writer
    return out


def goodput_record_path(run_dir: str, attempt: int) -> str:
    return os.path.join(run_dir, f"goodput_attempt{attempt:03d}.json")


def read_goodput_records(run_dir: str) -> Dict[int, dict]:
    out: Dict[int, dict] = {}
    for path in glob.glob(os.path.join(run_dir, "goodput_attempt*.json")):
        payload = _read_json(path)
        if isinstance(payload, dict):
            out[int(payload.get("attempt", 0))] = payload
    return out


# ------------------------------------------------------- serving artifacts

_REPLICA_RE = re.compile(r"replica_(\d+)$")
_SERVING_RECORD_RE = re.compile(r"serving_attempt(\d+)\.json$")


def replica_dir(fleet_dir: str, rid: int) -> str:
    """One replica's run dir inside a fleet dir — the dir its supervising
    launcher ring writes ``attempts.jsonl``/beacons into and its worker
    writes serving sidecars into. Owned here so the fleet writer
    (serving/fleet.py) and the import-light readers agree on the layout
    without serving/ imports."""
    return os.path.join(fleet_dir, f"replica_{rid}")


def list_replica_dirs(fleet_dir: str) -> List[str]:
    out = []
    for path in glob.glob(os.path.join(fleet_dir, "replica_*")):
        if _REPLICA_RE.search(path) and os.path.isdir(path):
            out.append(path)
    return sorted(out, key=replica_id)


def replica_id(replica_dir_path: str) -> int:
    """Replica index encoded in a replica dir path — the one parser for
    the naming :func:`replica_dir` writes (import-light readers must not
    each grow their own slice/regex of it)."""
    m = _REPLICA_RE.search(replica_dir_path)
    if m is None:
        raise ValueError(f"not a replica dir: {replica_dir_path!r}")
    return int(m.group(1))


_STAGE_RE = re.compile(r"stage_(\d+)$")


def stage_dir(run_dir: str, stage: int) -> str:
    """One MPMD pipeline stage's run dir inside a pipeline run dir — the
    dir its supervising launcher ring writes ``attempts.jsonl``/beacons
    into and its worker writes goodput sidecars into (the stage-side twin
    of :func:`replica_dir`, owned here for the same import-light
    reason)."""
    return os.path.join(run_dir, f"stage_{stage}")


def list_stage_dirs(run_dir: str) -> List[str]:
    out = []
    for path in glob.glob(os.path.join(run_dir, "stage_*")):
        if _STAGE_RE.search(path) and os.path.isdir(path):
            out.append(path)
    return sorted(out, key=stage_id)


def stage_id(stage_dir_path: str) -> int:
    m = _STAGE_RE.search(stage_dir_path)
    if m is None:
        raise ValueError(f"not a stage dir: {stage_dir_path!r}")
    return int(m.group(1))


def serving_journal_path(fleet_dir: str) -> str:
    """The router's durable request journal (append-only JSONL)."""
    return os.path.join(fleet_dir, "journal.jsonl")


def read_journal(path: str) -> List[dict]:
    """Journal events, torn-tail tolerant (same contract as
    :func:`read_attempts` — a killed router's last line may be partial)."""
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    pass
    return out


def serving_record_path(run_dir: str, attempt: int) -> str:
    return os.path.join(run_dir, f"serving_attempt{attempt:03d}.json")


def read_serving_records(run_dir: str) -> Dict[int, dict]:
    """Clean-exit serving sidecars per attempt (the serving counterpart of
    :func:`read_goodput_records`; a distinct filename prefix so training
    consumers never misparse one)."""
    out: Dict[int, dict] = {}
    for path in glob.glob(os.path.join(run_dir, "serving_attempt*.json")):
        payload = _read_json(path)
        if _SERVING_RECORD_RE.search(path) and isinstance(payload, dict):
            out[int(payload.get("attempt", 0))] = payload
    return out


def _fnum(x: Any, default: float = 0.0) -> float:
    """Defensive number coercion for fields read off disk: a killed
    attempt's artifacts may carry ``null`` (a beacon snapshotted mid-
    build, a record harvested with no beacon at all) or garbage from a
    torn write — the fold must degrade that attempt, never raise."""
    try:
        if isinstance(x, bool) or x is None:
            return default
        return float(x)
    except (TypeError, ValueError):
        return default


def aggregate_run(run_dir: str) -> Dict[str, Any]:
    """Fold a run's attempts into one goodput decomposition.

    Per attempt, the in-attempt record is the clean-exit sidecar when one
    exists, else the launcher's post-mortem beacon snapshot (a killed
    attempt's flight recorder). Attempt wall not covered by either —
    including whole attempts that died before their first beacon — lands
    in ``lost_s``: genuinely thrown-away time, EXCEPT the watchdog-
    measured ``hang_s`` window, which gets its own category (a wedge the
    watchdog bounded is a different failure than unaccounted loss).
    ``downtime_s`` is the launcher-observed gap between attempts
    (teardown + backoff + spawn).

    Degrades, never raises: a hard-killed attempt with a missing or
    zero-byte sidecar/beacon, or one whose snapshot carries nulls, folds
    as ``lost`` time — ``accounted_frac`` stays 1.0 by construction.
    SERVING attempts in a mixed run dir (a replica dir fed to the
    training fold, or a dir where both halves ran) degrade the same way:
    their artifacts carry a ``serving`` snapshot / ``serving_attempt*``
    sidecar and NO training goodput, so their wall folds to ``lost`` and
    they are counted in ``serving_attempts`` — use
    :func:`aggregate_serving` for the serving-side decomposition.
    """
    attempts = read_attempts(run_dir)
    sidecars = read_goodput_records(run_dir)
    if not attempts and not sidecars:
        stages = list_stage_dirs(run_dir)
        if stages:
            return _aggregate_pipeline(stages)
    serving_recs = read_serving_records(run_dir)
    cats = {c: 0.0 for c in _CATEGORIES}
    useful = lost = downtime = hang = 0.0
    serving_attempts = 0
    per_attempt: List[dict] = []

    def _fold(idx: int, duration_s: Optional[float], gp: Optional[dict],
              hang_s: float = 0.0):
        nonlocal useful, lost, hang
        hang += hang_s
        if not isinstance(gp, dict):
            gp = None  # a non-dict snapshot (torn write) is no snapshot
        if gp:
            for c in _CATEGORIES:
                cats[c] += _fnum(gp.get(c))
            useful += _fnum(gp.get("useful_step_s"))
            if duration_s is not None:
                lost += max(0.0, duration_s - _fnum(gp.get("wall_s"))
                            - hang_s)
        elif duration_s is not None:
            lost += max(0.0, duration_s - hang_s)

    if attempts:
        for rec in attempts:
            idx = int(_fnum(rec.get("attempt")))
            gp = sidecars.get(idx) or rec.get("goodput") or None
            # A serving attempt (replica worker under the same launcher)
            # has serving artifacts and no training goodput: its wall
            # degrades to lost here instead of raising or misparsing.
            is_serving = (idx in serving_recs
                          or isinstance(rec.get("serving"), dict))
            if is_serving and not isinstance(gp, dict):
                serving_attempts += 1
            downtime += _fnum(rec.get("downtime_s"))
            dur = rec.get("duration_s")
            if isinstance(dur, bool) or not isinstance(dur, (int, float)):
                # null/garbled duration (torn record): re-derive from the
                # spawn/exit stamps so the attempt's wall degrades to
                # lost instead of silently vanishing from the fold
                dur = max(0.0, _fnum(rec.get("t_exit"))
                          - _fnum(rec.get("t_spawn")))
            _fold(idx, float(dur), gp, hang_s=_fnum(rec.get("hang_s")))
            per_attempt.append({**rec,
                                "goodput_source": ("sidecar" if idx in sidecars
                                                   else "beacon"
                                                   if isinstance(gp, dict)
                                                   else "serving"
                                                   if is_serving else None)})
        wall = (_fnum(attempts[-1].get("t_exit"))
                - _fnum(attempts[0].get("t_spawn")))
    else:
        # Launcher-less run (single process): the sidecars are all there is.
        for idx in sorted(sidecars):
            _fold(idx, None, sidecars[idx])
            per_attempt.append({"attempt": idx, "goodput_source": "sidecar"})
        wall = sum(_fnum(s.get("wall_s")) for s in sidecars.values())
    wall = max(wall, 1e-9)
    accounted = useful + sum(cats.values()) + hang + lost + downtime
    return {
        "wall_s": wall,
        "useful_step_s": useful,
        "goodput": useful / wall,
        **cats,
        "hang_s": hang,
        "lost_s": lost,
        "downtime_s": downtime,
        "accounted_s": accounted,
        "accounted_frac": accounted / wall,
        "attempts": len(per_attempt),
        "serving_attempts": serving_attempts,
        "per_attempt": per_attempt,
    }


def _aggregate_pipeline(stage_dirs: List[str]) -> Dict[str, Any]:
    """Fold an MPMD pipeline run dir (one ``stage_{k}`` launcher-ring dir
    per stage, no root-level attempts) into ONE decomposition: every
    numeric field sums across the per-stage folds, so ``wall_s`` is
    summed STAGE wall (an S-stage run's wall is ~S x the clock time —
    every stage-second accounted, the same contract as
    :func:`aggregate_serving`'s summed replica wall) and
    ``accounted_frac`` stays 1.0 iff it held per stage. ``per_stage``
    keeps each stage's own fold (minus its per_attempt detail) so a
    restart on stage k is attributable to stage k alone."""
    cats = {c: 0.0 for c in _CATEGORIES}
    useful = lost = downtime = hang = wall = accounted = 0.0
    n_attempts = 0
    serving_attempts = 0
    per_stage: List[dict] = []
    for sd in stage_dirs:
        agg = aggregate_run(sd)
        wall += _fnum(agg.get("wall_s"))
        useful += _fnum(agg.get("useful_step_s"))
        for c in _CATEGORIES:
            cats[c] += _fnum(agg.get(c))
        hang += _fnum(agg.get("hang_s"))
        lost += _fnum(agg.get("lost_s"))
        downtime += _fnum(agg.get("downtime_s"))
        accounted += _fnum(agg.get("accounted_s"))
        n_attempts += int(_fnum(agg.get("attempts")))
        serving_attempts += int(_fnum(agg.get("serving_attempts")))
        per_stage.append({"stage": stage_id(sd),
                          **{k: v for k, v in agg.items()
                             if k != "per_attempt"}})
    wall = max(wall, 1e-9)
    return {
        "wall_s": wall,
        "useful_step_s": useful,
        "goodput": useful / wall,
        **cats,
        "hang_s": hang,
        "lost_s": lost,
        "downtime_s": downtime,
        "accounted_s": accounted,
        "accounted_frac": accounted / wall,
        "attempts": n_attempts,
        "serving_attempts": serving_attempts,
        "stages": len(per_stage),
        "per_stage": per_stage,
        "per_attempt": [],
    }


def aggregate_serving(fleet_dir: str) -> Dict[str, Any]:
    """Fold a serving fleet's artifacts into one ledger::

        wall == serving + drain + replay + paid_idle + swap
                + downtime + lost

    ``wall`` is summed REPLICA wall (each replica's first-spawn ->
    last-exit span, which the launcher's attempt records decompose into
    durations + downtime exactly), so an N-replica fleet's wall is ~N x
    the fleet's clock time — every replica-second is accounted, the same
    contract as the training fold. Per attempt, the in-attempt snapshot
    is the clean-exit ``serving_attempt*`` sidecar when one exists, else
    the launcher's post-mortem ``serving`` beacon snapshot; attempt wall
    covered by neither folds to ``lost``. ``replay`` — work a dead or
    wedged replica did on requests that later re-ran on a sibling — is
    ROUTER-attributed (the journal's ``replay`` events carry the wasted
    window) and re-booked out of ``serving``, clamped so the identity
    stays exact — note the windows are PER REQUEST and may overlap the
    same wall period (N requests in flight on one killed replica each
    book their own assign->death window), so under heavy replay the
    clamp can consume all of ``serving``. ``paid_idle`` — the
    autoscaler's journaled unneeded-capacity seconds — is re-booked out
    of ``serving`` with the same clamp discipline (zero when no
    autoscaler ran). Degrades, never raises, like :func:`aggregate_run`.
    """
    serving = drain = swap = lost = downtime = wall = 0.0
    per_replica: List[dict] = []
    n_attempts = 0
    for rd in list_replica_dirs(fleet_dir):
        attempts = read_attempts(rd)
        sidecars = read_serving_records(rd)
        r = {"replica": int(_REPLICA_RE.search(rd).group(1)),
             "attempts": len(attempts), "serving_s": 0.0, "lost_s": 0.0}
        for rec in attempts:
            n_attempts += 1
            idx = int(_fnum(rec.get("attempt")))
            snap = sidecars.get(idx) or rec.get("serving") or None
            if not isinstance(snap, dict):
                snap = None
            downtime += _fnum(rec.get("downtime_s"))
            wall += _fnum(rec.get("downtime_s"))
            dur = rec.get("duration_s")
            if isinstance(dur, bool) or not isinstance(dur, (int, float)):
                dur = max(0.0, _fnum(rec.get("t_exit"))
                          - _fnum(rec.get("t_spawn")))
            dur = float(dur)
            wall += dur
            if snap:
                # the worker's tracker keeps wall == serving + drain +
                # swap identically (serving is the residual), so folding
                # the parts preserves the identity; the uncovered tail
                # (snapshot -> kill) is lost
                d = _fnum(snap.get("drain_s"))
                s = _fnum(snap.get("swap_s"))
                sv = _fnum(snap.get("serving_s"))
                drain += d
                swap += s
                serving += sv
                r["serving_s"] += sv
                att_lost = max(0.0, dur - _fnum(snap.get("wall_s")))
            else:
                att_lost = dur
            lost += att_lost
            r["lost_s"] += att_lost
        per_replica.append(r)
    # Router-attributed replay: serving-shaped time whose output was
    # discarded. Re-booked out of `serving`, clamped to keep the identity
    # exact even against a torn/overstated journal.
    replay_raw = sum(
        _fnum(ev.get("wasted_s"))
        for ev in read_journal(serving_journal_path(fleet_dir))
        if ev.get("ev") == "replay")
    replay = min(max(0.0, replay_raw), serving)
    serving -= replay
    # Autoscaler-attributed paid idle: replica-seconds that were up and
    # ready but UNNEEDED (idle beyond the scaler's floor with an empty
    # queue). Same re-booking discipline as replay: journal deltas
    # summed, clamped against what is left of `serving`, identity exact.
    paid_idle_raw = sum(
        _fnum(ev.get("idle_s"))
        for ev in read_journal(serving_journal_path(fleet_dir))
        if ev.get("ev") == "paid_idle")
    paid_idle = min(max(0.0, paid_idle_raw), serving)
    serving -= paid_idle
    wall = max(wall, 1e-9)
    accounted = (serving + drain + replay + paid_idle + swap + downtime
                 + lost)
    return {
        "wall_s": wall,
        "serving_s": serving,
        "drain_s": drain,
        "replay_s": replay,
        "paid_idle_s": paid_idle,
        "swap_s": swap,
        "downtime_s": downtime,
        "lost_s": lost,
        "accounted_s": accounted,
        "accounted_frac": accounted / wall,
        "replicas": len(per_replica),
        "attempts": n_attempts,
        "per_replica": per_replica,
    }
