"""ChaosPlan: a declarative schedule of faults to inject into a live run.

The plan is plain JSON so it travels every channel a config does —
``--chaos_plan`` on the CLI, a field in ``--config_json``, or the
``DPT_CHAOS_PLAN`` environment variable, which (like ``DPT_PREFETCH_DEPTH``)
is inherited by every worker of every restart attempt the launcher spawns:
the one channel that reaches a ``--config_json`` ring without minting a new
config file.

Schema::

    {"faults": [
        {"kind": "kill",               "step": 3, "rank": 1,
         "sig": "SIGKILL"},
        {"kind": "crash_in_save",      "step": 6, "rank": 0},
        {"kind": "stall_data",         "step": 2, "rank": 0,
         "seconds": 1.5},
        {"kind": "stall_step",         "step": 4, "rank": 0,
         "seconds": 600},
        {"kind": "slow_rank",          "step": 2, "rank": 0,
         "seconds": 0.2, "until_step": 6},
        {"kind": "corrupt_checkpoint", "step": 5, "rank": 0}
    ]}

Fault kinds (executed by :mod:`.inject`):

* ``kill`` — the targeted rank sends itself ``sig`` (SIGKILL/SIGTERM/...)
  at the top of optimizer step ``step`` (a worker dying mid-step);
* ``crash_in_save`` — the targeted rank SIGKILLs itself right after the
  checkpoint save at ``step`` is SCHEDULED, i.e. between the array write
  and finalize — leaving an unfinalized/torn checkpoint on disk;
* ``stall_data`` — the targeted rank's data iterator blocks ``seconds``
  before yielding the batch at ``step`` (a wedged input pipeline);
* ``stall_step`` — the targeted rank WEDGES inside the step loop for
  ``seconds`` at the top of step ``step`` (a hung collective / network
  stall: the process stays alive but no rank advances — the failure the
  launcher's ``--hang_timeout_s`` watchdog exists to detect, since no
  exit code ever fires the restart machinery). Fires once per run (the
  marker makes the respawned attempt sail past the wedge step);
* ``slow_rank`` — a STRAGGLER, not a hang: the targeted rank sleeps
  ``seconds`` before EVERY step in ``[step, until_step]``. Progress
  continues (beacons keep advancing), so the hang watchdog must NOT
  fire — the negative control proving the watchdog keys on stalled
  progress, not on slowness;
* ``corrupt_checkpoint`` — garbles the payload of the newest FINALIZED
  checkpoint in the run dir at ``step`` (bit rot / torn replication: the
  directory still looks committed, but restore fails — the case the
  resume walk-back exists for).

Serving-fleet faults (ISSUE 11; ``rank`` targets a REPLICA id, ``step``
is an admitted-request threshold — serving has no optimizer steps):

* ``kill_replica`` — the targeted replica's worker sends itself ``sig``
  at the first scheduler tick where it has admitted >= ``step`` requests
  AND at least one is still in flight (a replica dying mid-request: the
  router must replay the in-flight requests on a sibling);
* ``stall_replica`` — same trigger, but the worker WEDGES alive for
  ``seconds`` (a stuck device / network stall): beacons freeze, so only
  the per-replica hang watchdog can end it — the serving twin of
  ``stall_step``;
* ``corrupt_swap_checkpoint`` — fired FLEET-side at the next checkpoint
  hot-swap: the swap target's payload is garbled before any replica
  loads it, so the canary replica's validation must fail and the swap
  must abort with every replica still serving the old weights (``step``
  and ``rank`` are ignored — the swap is a fleet-level event).

This module must stay import-light (no jax): the launcher and tests read
plans before any backend initializes.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List

__all__ = ["ChaosFault", "ChaosPlan", "CHAOS_PLAN_ENV"]

CHAOS_PLAN_ENV = "DPT_CHAOS_PLAN"

_KINDS = ("kill", "crash_in_save", "stall_data", "stall_step", "slow_rank",
          "corrupt_checkpoint",
          "kill_replica", "stall_replica", "corrupt_swap_checkpoint")


@dataclasses.dataclass(frozen=True)
class ChaosFault:
    """One scheduled fault. ``rank`` targets a single process (faults on
    other ranks no-op), so a plan can kill worker 1 mid-step while worker
    0 keeps serving the coordinator."""

    kind: str
    step: int
    rank: int = 0
    sig: str = "SIGKILL"      # kill only
    seconds: float = 1.0      # stall_data / stall_step / slow_rank
    until_step: int = -1      # slow_rank only: last straggled step
    #                           (defaults to ``step`` — one slow step)

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"unknown chaos fault kind {self.kind!r} "
                             f"(expected one of {_KINDS})")
        if self.step < 0:
            raise ValueError(f"chaos fault step must be >= 0, got {self.step}")
        if self.kind in ("stall_data", "stall_step", "slow_rank",
                         "stall_replica") and self.seconds <= 0:
            raise ValueError(f"{self.kind} fault needs seconds > 0")
        if self.kind == "slow_rank":
            if self.until_step < 0:
                object.__setattr__(self, "until_step", self.step)
            elif self.until_step < self.step:
                raise ValueError(
                    f"slow_rank until_step {self.until_step} precedes "
                    f"step {self.step}")


@dataclasses.dataclass(frozen=True)
class ChaosPlan:
    faults: tuple

    @classmethod
    def parse(cls, src: str) -> "ChaosPlan":
        """Build a plan from inline JSON, ``@/path/to/plan.json``, or a
        bare path to an existing file. Raises ValueError on anything
        malformed — a chaos run with a silently-empty plan would 'pass'
        without testing anything."""
        text = src.strip()
        if text.startswith("@"):
            with open(text[1:]) as f:
                text = f.read()
        elif not text.startswith("{") and os.path.exists(text):
            with open(text) as f:
                text = f.read()
        try:
            payload = json.loads(text)
        except json.JSONDecodeError as e:
            raise ValueError(f"chaos plan is not valid JSON: {e}") from e
        raw = payload.get("faults") if isinstance(payload, dict) else payload
        if not isinstance(raw, list) or not raw:
            raise ValueError("chaos plan must carry a non-empty 'faults' list")
        faults: List[ChaosFault] = []
        for i, f in enumerate(raw):
            if not isinstance(f, dict):
                raise ValueError(f"chaos fault #{i} must be an object")
            known = {k: f[k] for k in
                     ("kind", "step", "rank", "sig", "seconds",
                      "until_step") if k in f}
            if set(f) - set(known):
                raise ValueError(f"chaos fault #{i} has unknown keys "
                                 f"{sorted(set(f) - set(known))}")
            faults.append(ChaosFault(**known))
        return cls(faults=tuple(faults))

    def describe(self) -> str:
        return "; ".join(
            f"{f.kind}@step{f.step}/rank{f.rank}"
            + (f" {f.sig}" if f.kind in ("kill", "kill_replica") else "")
            + (f" {f.seconds}s" if f.kind in ("stall_data", "stall_step",
                                              "stall_replica")
               else "")
            + (f" {f.seconds}s/step thru {f.until_step}"
               if f.kind == "slow_rank" else "")
            for f in self.faults)

    def to_json(self) -> str:
        return json.dumps(
            {"faults": [dataclasses.asdict(f) for f in self.faults]})
