"""ChaosInjector: executes a :class:`~.plan.ChaosPlan` inside a live run.

The trainer calls three tiny hooks (``on_step`` at the top of every
optimizer step, ``on_data`` before pulling a batch, ``on_save`` right after
a checkpoint save is scheduled); each hook fires whatever faults the plan
schedules for the current step on this rank. Every fault fires AT MOST ONCE
PER RUN: a marker file in the run dir (written BEFORE the fault executes)
makes the respawned attempt sail past the step that killed its predecessor
— the same marker idiom the launcher restart tests pioneered, now owned by
the injector so every fault kind gets it for free.

Import-light on purpose: the launcher may import this package before jax
exists in the process; the checkpoint-corruption helper touches only the
filesystem.
"""

from __future__ import annotations

import os
import signal
import sys
import time
from typing import Optional

from .plan import ChaosFault, ChaosPlan

__all__ = ["ChaosInjector", "corrupt_newest_checkpoint",
           "corrupt_checkpoint_payload"]

# Payload bytes for checkpoint corruption: long enough to guarantee any
# parser/checksum downstream sees garbage, loud enough to grep in a hexdump.
_GARBAGE = b"\xde\xad\xbe\xef CHAOS-CORRUPTED " * 8

# orbax's commit marker — corruption must leave it intact so the torn
# checkpoint still LOOKS finalized and exercises the restore walk-back
# (deleting it would exercise the cheaper discovery-skip path instead).
# Public under COMMIT_MARKERS: the serving fleet's jax-free checkpoint
# discovery needs the same notion of "finalized".
_COMMIT_MARKERS = COMMIT_MARKERS = ("_CHECKPOINT_METADATA",
                                    "commit_success.txt")


def corrupt_newest_checkpoint(directory: str) -> Optional[str]:
    """Garble the payload of the newest finalized ``model_*`` checkpoint
    under ``directory`` (every file except the commit marker gets its head
    overwritten). Returns the corrupted path, or None when there is no
    finalized checkpoint to corrupt. Local-filesystem only — chaos runs
    are dev rings."""
    best: Optional[str] = None
    best_step = -1
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    for name in names:
        if not name.startswith("model_") or ".orbax-checkpoint-tmp" in name:
            continue
        digits = name[len("model_"):]
        if not digits.isdigit():
            continue
        path = os.path.join(directory, name)
        if not any(os.path.exists(os.path.join(path, m))
                   for m in _COMMIT_MARKERS):
            continue  # torn already — corrupt a checkpoint resume WOULD pick
        if int(digits) > best_step:
            best_step, best = int(digits), path
    if best is None:
        return None
    corrupt_checkpoint_payload(best)
    return best


def corrupt_checkpoint_payload(path: str) -> bool:
    """Garble the head of every payload file under ONE checkpoint dir,
    leaving the commit markers intact (the dir still looks finalized; any
    restore of it must fail). Returns whether anything was written —
    ``False`` means the dir had no payload to damage (missing/empty), so
    a caller injecting a swap fault can tell the fault went nowhere."""
    wrote = False
    for root, _, files in os.walk(path):
        for fname in files:
            if fname in _COMMIT_MARKERS:
                continue
            fpath = os.path.join(root, fname)
            try:
                with open(fpath, "r+b") as f:
                    f.write(_GARBAGE)
                wrote = True
            except OSError:
                pass  # a file we cannot open is already damage enough
    return wrote


class ChaosInjector:
    """Fires plan faults from the trainer's hook points.

    ``run_dir`` anchors the once-per-run markers; when the trainer passes
    no checkpoint dir (bench loops), markers degrade to in-process memory
    — enough for single-attempt use, while multi-attempt kill/restart
    scenarios always have a run dir by construction (that is where the
    checkpoint being resumed lives)."""

    def __init__(self, plan: ChaosPlan, rank: int = 0,
                 run_dir: str = "") -> None:
        self.plan = plan
        self.rank = rank
        self.run_dir = run_dir
        self._fired_mem: set = set()

    # ------------------------------------------------------------- markers

    def _marker(self, idx: int) -> str:
        return os.path.join(self.run_dir, f".chaos_fired_{idx:02d}")

    def _already_fired(self, idx: int) -> bool:
        if idx in self._fired_mem:
            return True
        return bool(self.run_dir) and os.path.exists(self._marker(idx))

    def _mark_fired(self, idx: int, fault: ChaosFault) -> None:
        # Marker lands BEFORE the fault executes: a SIGKILL leaves no
        # chance to write afterwards, and a re-fired kill every attempt
        # would be an unrecoverable crash loop, not an injected fault.
        self._fired_mem.add(idx)
        if self.run_dir:
            with open(self._marker(idx), "w") as f:
                f.write(f"{fault.kind} step={fault.step} rank={fault.rank} "
                        f"t={time.time():.3f}\n")

    # --------------------------------------------------------------- hooks

    def _due(self, step: int, kinds) -> list:
        return [(i, f) for i, f in enumerate(self.plan.faults)
                if f.kind in kinds and f.rank == self.rank
                and f.step == step and not self._already_fired(i)]

    def _fire_kill(self, fault: ChaosFault) -> None:
        sig = getattr(signal, fault.sig, None)
        if not isinstance(sig, signal.Signals):
            raise ValueError(f"chaos kill: unknown signal {fault.sig!r}")
        print(f"[chaos] rank {self.rank}: {fault.sig} self at step "
              f"{fault.step}", file=sys.stderr, flush=True)
        os.kill(os.getpid(), sig)
        # SIGTERM may be handled/deferred by the host loop; SIGKILL never
        # returns here. Either way the fault's job is done.

    def on_step(self, loop) -> None:
        """Top of ``run_step``: corrupt/kill/stall_step faults scheduled
        for the step ABOUT to run (plan order — corrupt-then-kill at the
        same step is the classic 'newest checkpoint is garbage AND the
        worker died'), plus the slow_rank straggler delay."""
        for idx, fault in self._due(loop.step,
                                    ("corrupt_checkpoint", "kill",
                                     "stall_step")):
            self._mark_fired(idx, fault)
            if fault.kind == "corrupt_checkpoint":
                victim = corrupt_newest_checkpoint(
                    self.run_dir or loop.checkpoint_dir)
                print(f"[chaos] rank {self.rank}: corrupted checkpoint "
                      f"{victim}", file=sys.stderr, flush=True)
            elif fault.kind == "stall_step":
                # The wedge the hang watchdog exists for: the process
                # stays ALIVE but stops advancing — no beacon write, no
                # exit code, nothing the restart machinery can see. The
                # marker landed first, so the attempt the watchdog
                # eventually kills is resumed past the wedge step.
                print(f"[chaos] rank {self.rank}: wedging step loop "
                      f"{fault.seconds}s at step {fault.step}",
                      file=sys.stderr, flush=True)
                time.sleep(fault.seconds)
            else:
                self._fire_kill(fault)
        # slow_rank: a straggler, not a hang — sleeps before EVERY step in
        # its [step, until_step] range, with no once-per-run marker (it
        # never kills; a respawned attempt re-straggles only the steps it
        # actually replays). Beacons keep advancing, so the hang watchdog
        # must ride through it.
        for fault in self.plan.faults:
            if (fault.kind == "slow_rank" and fault.rank == self.rank
                    and fault.step <= loop.step <= fault.until_step):
                time.sleep(fault.seconds)

    def on_data(self, loop) -> float:
        """Before pulling the batch for the NEXT step: stall faults.
        Returns the injected stall seconds (the caller attributes them to
        the data-wait gauge, so goodput accounting sees the stall as the
        input-pipeline time it simulates)."""
        stalled = 0.0
        for idx, fault in self._due(loop.step, ("stall_data",)):
            self._mark_fired(idx, fault)
            print(f"[chaos] rank {self.rank}: stalling data "
                  f"{fault.seconds}s at step {fault.step}",
                  file=sys.stderr, flush=True)
            time.sleep(fault.seconds)
            stalled += fault.seconds
        return stalled

    # ------------------------------------------------------- serving hooks

    def on_serve_tick(self, admitted: int, in_flight: int) -> None:
        """Serving replica hook, called once per scheduler tick with the
        replica's cumulative ADMITTED request count and its current
        in-flight count. ``kill_replica`` / ``stall_replica`` faults for
        this replica (``rank`` = replica id) fire at the first tick where
        ``admitted >= step`` AND something is in flight — "mid-request"
        by construction, whatever the traffic process did to the
        schedule. Threshold (not equality) because admitted counts can
        jump by a whole prefill batch in one tick. Marker-once like every
        fault: a respawned replica sails past."""
        if in_flight <= 0:
            return
        due = [(i, f) for i, f in enumerate(self.plan.faults)
               if f.kind in ("kill_replica", "stall_replica")
               and f.rank == self.rank and admitted >= f.step
               and not self._already_fired(i)]
        for idx, fault in due:
            self._mark_fired(idx, fault)
            if fault.kind == "stall_replica":
                # the serving wedge: alive, beacons frozen — only the
                # per-replica hang watchdog can end this
                print(f"[chaos] replica {self.rank}: wedging serve loop "
                      f"{fault.seconds}s ({in_flight} in flight)",
                      file=sys.stderr, flush=True)
                time.sleep(fault.seconds)
            else:
                self._fire_kill(fault)

    def on_swap(self, checkpoint_path: str) -> bool:
        """Fleet-side hook at the start of a checkpoint hot-swap:
        ``corrupt_swap_checkpoint`` garbles the swap TARGET before any
        replica loads it (``step``/``rank`` ignored — the swap is a
        fleet-level event, and this injector's run_dir is the fleet dir).
        Returns whether a fault fired, so the swap report can say the
        abort was injected rather than organic."""
        due = [(i, f) for i, f in enumerate(self.plan.faults)
               if f.kind == "corrupt_swap_checkpoint"
               and not self._already_fired(i)]
        fired = False
        for idx, fault in due:
            self._mark_fired(idx, fault)
            wrote = corrupt_checkpoint_payload(checkpoint_path)
            print(f"[chaos] fleet: corrupted swap checkpoint "
                  f"{checkpoint_path} (payload garbled: {wrote})",
                  file=sys.stderr, flush=True)
            fired = True
        return fired

    def on_save(self, loop) -> None:
        """Right after a checkpoint save is SCHEDULED (async write in
        flight, finalize not reached): crash_in_save faults — the kill
        lands between the array write and finalize, leaving an
        unfinalized/torn checkpoint behind."""
        for idx, fault in self._due(loop.step, ("crash_in_save",)):
            self._mark_fired(idx, fault)
            print(f"[chaos] rank {self.rank}: SIGKILL mid-save at step "
                  f"{fault.step}", file=sys.stderr, flush=True)
            os.kill(os.getpid(), signal.SIGKILL)
