"""Fault injection + goodput accounting for the elastic training stack.

The recovery machinery — the self-relaunching launcher, checkpoint
auto-resume, the persistent compile cache — is only as real as the failures
it has survived. This package supplies the failures (:class:`ChaosPlan` /
:class:`ChaosInjector`: scheduled kills, crashes mid-checkpoint-save, data
stalls, step-loop wedges, stragglers, corrupted checkpoints — and, for the
serving fleet, replica kills/wedges and corrupted swap checkpoints) and the
metric that proves survival was
cheap (:mod:`.goodput`: useful-step time / wall time, with every second of
a restarted run attributed to a category; :func:`~.goodput.aggregate_serving`
is the serving-side twin).

Import-light by design: the launcher imports this before jax exists in the
process.
"""

from .goodput import (
    aggregate_run,
    aggregate_serving,
    append_attempt,
    attempts_path,
    beacon_max_step,
    beacon_path,
    goodput_record_path,
    list_replica_dirs,
    read_attempts,
    read_beacons,
    read_goodput_records,
    read_journal,
    read_serving_records,
    replica_dir,
    replica_id,
    serving_journal_path,
    serving_record_path,
)
from .inject import (
    ChaosInjector,
    corrupt_checkpoint_payload,
    corrupt_newest_checkpoint,
)
from .plan import CHAOS_PLAN_ENV, ChaosFault, ChaosPlan

__all__ = [
    "ChaosFault", "ChaosPlan", "ChaosInjector", "CHAOS_PLAN_ENV",
    "corrupt_newest_checkpoint", "corrupt_checkpoint_payload",
    "aggregate_run", "append_attempt", "attempts_path", "beacon_max_step",
    "beacon_path", "goodput_record_path", "read_attempts", "read_beacons",
    "read_goodput_records",
    "aggregate_serving", "list_replica_dirs", "read_journal",
    "read_serving_records", "replica_dir", "replica_id",
    "serving_journal_path", "serving_record_path",
]
