"""Native (C++) runtime components, consumed via ``ctypes``.

The reference keeps all native capability in external libraries (SURVEY.md
§2.1); here the host-side data path gets its own native pieces:

* ``bpe_encoder.cpp`` — the BPE merge loop behind the exact contract of
  ``data/tokenizer.py:BPEVocab`` (~15x the Python throughput);
* ``jsonl_index.cpp`` — mmap'd random access over jsonl corpora (offset
  table instead of holding every line in Python memory; pages shared
  across loader processes by the page cache).

The library is built on first use with the toolchain baked into the image
(``g++``/``clang++``; no pybind11, so the binding is a plain C ABI +
ctypes) and cached next to the sources. Everything degrades gracefully:
no compiler, a failed build, or ``DPT_NATIVE=0`` simply leaves the
pure-Python paths in charge — the same degrade-to-portable contract the
distributed substrate follows (parallel/dist.py).
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import tempfile
import threading
from typing import Dict, List, Optional

__all__ = ["load_library", "NativeBPE", "NativeJsonlIndex",
           "native_enabled"]

_DIR = os.path.dirname(__file__)
_SRCS = [os.path.join(_DIR, "bpe_encoder.cpp"),
         os.path.join(_DIR, "jsonl_index.cpp")]
_BUILD_DIR = os.path.join(_DIR, "_build")
_SO = os.path.join(_BUILD_DIR, "libdpt_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False

_hash_fn = None


def _stable_hash_id():
    """The shared OOV hash from data.tokenizer (the parity contract),
    imported lazily once — tokenizer imports this package inside
    ``BPEVocab.__init__``, so a module-level import here would be a cycle
    hazard; per-call imports would tax OOV-heavy corpora."""
    global _hash_fn
    if _hash_fn is None:
        from ..data.tokenizer import stable_hash_id
        _hash_fn = stable_hash_id
    return _hash_fn


def native_enabled() -> bool:
    """False when the user opted out via ``DPT_NATIVE=0``."""
    return os.environ.get("DPT_NATIVE", "1") not in ("0", "false", "False")


def _build() -> bool:
    """Compile the shared library if missing or stale; True on success.

    Staleness is mtime-based so editing the .cpp during development
    rebuilds, with a SOURCE-HASH sidecar (``<so>.srchash``) as the semantic
    tie-breaker: a successful build records the sha256 of its sources, so
    when a later recompile fails the existing .so is reused only if its
    recorded hash still matches the current sources (mtime lied — e.g. a
    fresh checkout touched files). A genuinely semantically-stale library
    falls back to Python instead of silently breaking the 'identical with
    or without native' parity contract (r4 advisor), unless
    ``DPT_NATIVE_ALLOW_STALE=1`` opts in. The compile lands in a temp file
    first and is moved into place atomically — concurrent processes (e.g.
    a ``--nprocs`` dev ring) race benignly. Compiler: ``$CXX`` if set
    (same knob as the Makefile), else the first of g++/clang++ on PATH."""
    import hashlib
    import warnings

    def _src_hash() -> str:
        h = hashlib.sha256()
        for s in _SRCS:
            with open(s, "rb") as f:
                h.update(f.read())
        return h.hexdigest()

    try:
        have_srcs = all(os.path.exists(s) for s in _SRCS)
        if os.path.exists(_SO) and (
                not have_srcs  # prebuilt .so shipped without sources
                or os.path.getmtime(_SO) >= max(os.path.getmtime(s)
                                                for s in _SRCS)):
            return True
        if not have_srcs:
            return False
        os.makedirs(_BUILD_DIR, exist_ok=True)
        env_cxx = os.environ.get("CXX")
        compilers = [env_cxx] if env_cxx else ["g++", "clang++"]
        for cxx in compilers:
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
            os.close(fd)
            try:
                proc = subprocess.run(
                    [cxx, "-O2", "-std=c++17", "-Wall", "-Wextra",
                     "-shared", "-fPIC", "-o", tmp] + _SRCS,
                    capture_output=True, text=True, timeout=120)
                if proc.returncode == 0:
                    os.replace(tmp, _SO)
                    try:
                        with open(_SO + ".srchash", "w") as f:
                            f.write(_src_hash())
                    except OSError:
                        pass
                    return True
            except (OSError, subprocess.SubprocessError):
                continue
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        if os.path.exists(_SO):
            # Sources are newer but no compiler produced a fresh build.
            try:
                with open(_SO + ".srchash") as f:
                    same_sources = f.read().strip() == _src_hash()
            except OSError:
                same_sources = False
            if same_sources:
                return True  # mtime skew only; the .so matches the sources
            if os.environ.get("DPT_NATIVE_ALLOW_STALE") == "1":
                warnings.warn(
                    "distributed_pipeline_tpu.native: recompile failed; "
                    "DPT_NATIVE_ALLOW_STALE=1 -> using the SEMANTICALLY "
                    "STALE prebuilt library (sources differ from its "
                    "recorded build hash)")
                return True
            warnings.warn(
                "distributed_pipeline_tpu.native: recompile failed and the "
                "prebuilt library does not match the current sources — "
                "falling back to the Python implementations (set "
                "DPT_NATIVE_ALLOW_STALE=1 to use the stale .so anyway)")
            return False
        return False
    except OSError:
        if os.path.exists(_SO):
            import warnings
            warnings.warn(
                "distributed_pipeline_tpu.native: staleness check failed; "
                "using the existing prebuilt library as-is")
            return True
        return False


def load_library() -> Optional[ctypes.CDLL]:
    """The process-wide handle to the native library, building it on first
    use; None when native is disabled or unavailable (callers fall back to
    Python)."""
    global _lib, _lib_failed
    if not native_enabled():
        return None
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        if not _build():
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO)
            _wire_symbols(lib)
        # AttributeError: a stale .so accepted by _build() may predate a
        # symbol added to the wiring below — degrade to Python, don't crash
        except (OSError, AttributeError):
            _lib_failed = True
            return None
        _lib = lib
        return _lib


def _wire_symbols(lib: ctypes.CDLL) -> None:
    """Declare every exported symbol's ctypes signature (raises
    AttributeError if the library predates a symbol)."""
    lib.dpt_bpe_create.restype = ctypes.c_void_p
    lib.dpt_bpe_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.dpt_bpe_destroy.restype = None
    lib.dpt_bpe_destroy.argtypes = [ctypes.c_void_p]
    lib.dpt_bpe_encode.restype = ctypes.c_int64
    lib.dpt_bpe_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
    lib.dpt_bpe_oov_count.restype = ctypes.c_int64
    lib.dpt_bpe_oov_count.argtypes = [ctypes.c_void_p]
    lib.dpt_bpe_oov_get.restype = ctypes.c_int64
    lib.dpt_bpe_oov_get.argtypes = [
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
    lib.dpt_jsonl_open.restype = ctypes.c_void_p
    lib.dpt_jsonl_open.argtypes = [ctypes.c_char_p]
    lib.dpt_jsonl_count.restype = ctypes.c_int64
    lib.dpt_jsonl_count.argtypes = [ctypes.c_void_p]
    lib.dpt_jsonl_get.restype = ctypes.c_int64
    lib.dpt_jsonl_get.argtypes = [
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
    lib.dpt_jsonl_close.restype = None
    lib.dpt_jsonl_close.argtypes = [ctypes.c_void_p]


def _pack_tables(merges: List[List[str]], vocab: Dict[str, int]) -> bytes:
    """Serialize the BPE artifact into the C++ wire format (see
    bpe_encoder.cpp header): length-prefixed UTF-8 strings, no JSON parsing
    on the native side."""
    parts = [struct.pack("<II", 0x45504254, 1), struct.pack("<I", len(merges))]
    for a, b in merges:
        ab, bb = a.encode(), b.encode()
        parts.append(struct.pack("<I", len(ab)) + ab)
        parts.append(struct.pack("<I", len(bb)) + bb)
    parts.append(struct.pack("<I", len(vocab)))
    for s, i in vocab.items():
        sb = s.encode()
        parts.append(struct.pack("<I", len(sb)) + sb + struct.pack("<i", i))
    return b"".join(parts)


class NativeBPE:
    """ctypes wrapper around one C++ encoder instance.

    ``encode_words`` takes the words of one text (the caller keeps Python's
    ``str.split()`` Unicode-whitespace semantics) and returns ids identical
    to ``BPEVocab.encode``: vocab hits come from C++, out-of-alphabet
    symbols come back as sentinels and are resolved here with the same
    blake2s stable hash the Python path uses."""

    def __init__(self, merges: List[List[str]], vocab: Dict[str, int],
                 vocab_size: int, n_reserved: int):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native BPE library unavailable")
        blob = _pack_tables(merges, vocab)
        handle = lib.dpt_bpe_create(blob, len(blob))
        if not handle:
            raise RuntimeError("native BPE rejected the vocab tables")
        self._lib = lib
        self._handle = handle
        self._vocab_size = vocab_size
        self._n_reserved = n_reserved
        self._buf_cap = 4096
        self._buf = (ctypes.c_int32 * self._buf_cap)()
        self._lock = threading.Lock()

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.dpt_bpe_destroy(handle)
            self._handle = None

    def _resolve_oov(self, k: int) -> int:
        cap = 64
        while True:
            raw = (ctypes.c_uint8 * cap)()
            n = self._lib.dpt_bpe_oov_get(self._handle, k, raw, cap)
            if n < 0:
                raise RuntimeError(f"native BPE: bad OOV index {k}")
            if n <= cap:
                break
            cap = int(n)
        symbol = bytes(raw[:n]).decode()
        return _stable_hash_id()(symbol, self._vocab_size, self._n_reserved)

    def encode_words(self, words: List[str]) -> List[int]:
        if not words:
            return []
        text = "\n".join(words).encode()
        with self._lock:
            n = self._lib.dpt_bpe_encode(self._handle, text, len(text),
                                         self._buf, self._buf_cap)
            if n > self._buf_cap:
                self._buf_cap = int(n)
                self._buf = (ctypes.c_int32 * self._buf_cap)()
                n = self._lib.dpt_bpe_encode(self._handle, text, len(text),
                                             self._buf, self._buf_cap)
            # OOV sentinels must be resolved before the NEXT encode on this
            # handle (which may flush the C++ memo/OOV tables when the
            # bounded word cache overflows) — so resolve under the lock.
            return [i if i >= 0 else self._resolve_oov(-i - 1)
                    for i in self._buf[:n]]


class NativeJsonlIndex:
    """mmap'd random access over a jsonl corpus (jsonl_index.cpp).

    Replaces holding every line in a Python list: the offset table is the
    only per-process memory (16 bytes/line), the file's pages stream in on
    demand and are shared across loader processes by the page cache.
    ``line(i)`` returns the decoded non-blank line i — blank means
    ASCII-whitespace-only, the contract shared with the Python fallback in
    ``data/dataset.py``."""

    def __init__(self, path: str):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        handle = lib.dpt_jsonl_open(os.fspath(path).encode())
        if not handle:
            raise RuntimeError(f"native jsonl index failed to open {path!r}")
        self._lib = lib
        self._handle = handle
        self._len = int(lib.dpt_jsonl_count(handle))
        # line() runs once per __getitem__: keep one growable buffer
        # instead of allocating per call (NativeBPE does the same). Guarded
        # by a lock — loader worker threads share the dataset.
        self._buf_cap = 4096
        self._buf = (ctypes.c_uint8 * self._buf_cap)()
        self._buf_lock = threading.Lock()

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.dpt_jsonl_close(handle)
            self._handle = None

    def __len__(self) -> int:
        return self._len

    def line(self, i: int) -> str:
        with self._buf_lock:
            n = self._lib.dpt_jsonl_get(self._handle, i, self._buf,
                                        self._buf_cap)
            if n < 0:
                raise IndexError(i)
            if n > self._buf_cap:
                self._buf_cap = int(n)
                self._buf = (ctypes.c_uint8 * self._buf_cap)()
                n = self._lib.dpt_jsonl_get(self._handle, i, self._buf,
                                            self._buf_cap)
            return bytes(self._buf[:n]).decode()
