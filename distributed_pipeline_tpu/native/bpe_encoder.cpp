// Native BPE encoder: the host-side tokenization hot loop in C++.
//
// The reference delegates all native capability to libraries (SURVEY.md
// §2.1: torch c10d / cuDNN / blobfile); its data path is a user stub
// (/root/reference/data/dataset.py:5-15). This framework's jsonl data path
// tokenizes with a pure-Python BPE (data/tokenizer.py) whose per-word merge
// loop is the slowest host-side code in the input pipeline — on TPU the
// accelerator step is jitted end-to-end, so host tokenization is what
// competes with the prefetch budget. This file implements the exact same
// greedy lowest-rank merge procedure in C++ behind a C ABI consumed via
// ctypes (no pybind11 in the image).
//
// Parity contract with data/tokenizer.py:BPEVocab:
//   * the caller (Python) performs the Unicode whitespace split
//     (str.split()) and sends words joined by '\n' — C++ never re-implements
//     Python's whitespace semantics;
//   * a word is split into Unicode code points (not bytes) + the "</w>"
//     end-of-word marker, then adjacent pairs merge greedily by lowest
//     merge-table rank, ties broken by leftmost position — identical to
//     BPEVocab._bpe_word;
//   * symbols found in the vocab map to their id; out-of-alphabet symbols
//     are reported as -(k+1) sentinels referencing a persistent OOV table
//     the caller resolves with its own stable hash (blake2s) — so the
//     fallback contract stays byte-identical with the Python path.
//
// Table wire format (built by native/__init__.py, little-endian):
//   u32 magic 0x45504254 ("TBPE")  u32 version=1
//   u32 n_merges  then per merge:  u32 len_a, bytes a, u32 len_b, bytes b
//   u32 n_vocab   then per entry:  u32 len_s, bytes s, i32 id
//
// Build: g++ -O2 -std=c++17 -shared -fPIC (native/Makefile, or auto-built
// on first use by native/__init__.py).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x45504254u;  // "TBPE"
constexpr uint32_t kVersion = 1u;
const std::string kEOW = "</w>";

// Open-vocabulary corpora (IDs, numbers, typos) produce unbounded distinct
// words; the memo tables are flushed past this size so a multi-day run's
// host memory stays bounded (the flush happens between encode calls, when
// no OOV sentinel is outstanding — see dpt_bpe_encode).
constexpr size_t kWordCacheCap = 1u << 16;

struct Encoder {
  std::unordered_map<std::string, int32_t> ranks;  // key: len(a)|a|b
  std::unordered_map<std::string, int32_t> vocab;
  // word -> encoded ids (OOV entries already as -(k+1) sentinels into
  // oov_symbols; flushed together with the OOV tables).
  std::unordered_map<std::string, std::vector<int32_t>> word_cache;
  std::vector<std::string> oov_symbols;
  std::unordered_map<std::string, int64_t> oov_index;
  std::mutex mu;  // encode() may be called from several loader threads
};

// Unambiguous pair key: 4-byte little-endian length of `a`, then a, then b
// (symbols may in principle contain any byte, so a separator would be
// ambiguous).
std::string PairKey(const std::string& a, const std::string& b) {
  uint32_t la = static_cast<uint32_t>(a.size());
  std::string k;
  k.reserve(4 + a.size() + b.size());
  k.append(reinterpret_cast<const char*>(&la), 4);
  k += a;
  k += b;
  return k;
}

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  template <typename T>
  T Get() {
    T v{};
    if (p + sizeof(T) > end) {
      ok = false;
      return v;
    }
    std::memcpy(&v, p, sizeof(T));
    p += sizeof(T);
    return v;
  }

  std::string GetStr() {
    uint32_t n = Get<uint32_t>();
    if (!ok || p + n > end) {
      ok = false;
      return {};
    }
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    return s;
  }
};

// Split a UTF-8 word into code-point symbols (mirrors Python's list(word)).
// Input comes UTF-8-encoded from a valid Python str; a malformed lead byte
// is still handled (consumed as a single-byte symbol) so we can never run
// off the buffer.
void SplitCodepoints(const char* s, size_t n, std::vector<std::string>* out) {
  size_t i = 0;
  while (i < n) {
    unsigned char c = static_cast<unsigned char>(s[i]);
    size_t len = 1;
    if (c >= 0xF0) {
      len = 4;
    } else if (c >= 0xE0) {
      len = 3;
    } else if (c >= 0xC0) {
      len = 2;
    }
    if (i + len > n) len = 1;
    out->emplace_back(s + i, len);
    i += len;
  }
}

// Greedy merge identical to BPEVocab._bpe_word: repeatedly merge the
// adjacent pair with the lowest rank (leftmost on ties) until none remains.
void BpeWord(const Encoder& enc, std::vector<std::string>* seq) {
  while (seq->size() > 1) {
    int best = -1;
    int32_t best_rank = 0;
    for (size_t i = 0; i + 1 < seq->size(); ++i) {
      auto it = enc.ranks.find(PairKey((*seq)[i], (*seq)[i + 1]));
      if (it != enc.ranks.end() &&
          (best < 0 || it->second < best_rank)) {
        best = static_cast<int>(i);
        best_rank = it->second;
      }
    }
    if (best < 0) break;
    (*seq)[best] += (*seq)[best + 1];
    seq->erase(seq->begin() + best + 1);
  }
}

void EncodeWord(Encoder* enc, const char* s, size_t n,
                std::vector<int32_t>* out) {
  std::string word(s, n);
  auto cached = enc->word_cache.find(word);
  if (cached != enc->word_cache.end()) {
    out->insert(out->end(), cached->second.begin(), cached->second.end());
    return;
  }
  std::vector<std::string> seq;
  SplitCodepoints(s, n, &seq);
  seq.push_back(kEOW);
  BpeWord(*enc, &seq);
  std::vector<int32_t> ids;
  ids.reserve(seq.size());
  for (const auto& sym : seq) {
    auto it = enc->vocab.find(sym);
    if (it != enc->vocab.end()) {
      ids.push_back(it->second);
    } else {
      auto [oit, inserted] = enc->oov_index.try_emplace(
          sym, static_cast<int64_t>(enc->oov_symbols.size()));
      if (inserted) enc->oov_symbols.push_back(sym);
      ids.push_back(static_cast<int32_t>(-(oit->second + 1)));
    }
  }
  out->insert(out->end(), ids.begin(), ids.end());
  enc->word_cache.emplace(std::move(word), std::move(ids));
}

}  // namespace

extern "C" {

// Parse the wire-format table; returns nullptr on malformed input.
void* dpt_bpe_create(const uint8_t* blob, uint64_t len) {
  Reader r{blob, blob + len};
  if (r.Get<uint32_t>() != kMagic || r.Get<uint32_t>() != kVersion ||
      !r.ok) {
    return nullptr;
  }
  auto enc = new Encoder();
  uint32_t n_merges = r.Get<uint32_t>();
  for (uint32_t i = 0; i < n_merges && r.ok; ++i) {
    std::string a = r.GetStr();
    std::string b = r.GetStr();
    if (r.ok) enc->ranks.emplace(PairKey(a, b), static_cast<int32_t>(i));
  }
  uint32_t n_vocab = r.Get<uint32_t>();
  for (uint32_t i = 0; i < n_vocab && r.ok; ++i) {
    std::string s = r.GetStr();
    int32_t id = r.Get<int32_t>();
    if (r.ok) enc->vocab.emplace(std::move(s), id);
  }
  if (!r.ok || r.p != r.end) {
    delete enc;
    return nullptr;
  }
  return enc;
}

void dpt_bpe_destroy(void* h) { delete static_cast<Encoder*>(h); }

// Encode '\n'-separated words (already whitespace-split by the caller).
// Writes up to `cap` ids into `out`; RETURNS the total id count, which may
// exceed `cap` (caller retries with a larger buffer — nothing past `cap`
// is written). Ids >= 0 are vocab ids; id == -(k+1) refers to OOV symbol k
// (dpt_bpe_oov_get). Sentinels are only guaranteed resolvable until the
// NEXT encode call (which may flush the memo tables) — the caller must
// resolve them immediately, before encoding anything else on this handle.
int64_t dpt_bpe_encode(void* h, const uint8_t* text, uint64_t text_len,
                       int32_t* out, int64_t cap) {
  auto enc = static_cast<Encoder*>(h);
  std::lock_guard<std::mutex> lock(enc->mu);
  if (enc->word_cache.size() > kWordCacheCap) {
    enc->word_cache.clear();
    enc->oov_symbols.clear();
    enc->oov_index.clear();
  }
  std::vector<int32_t> ids;
  ids.reserve(text_len / 2 + 8);
  const char* s = reinterpret_cast<const char*>(text);
  size_t start = 0;
  size_t words = 0;
  for (size_t i = 0; i <= text_len; ++i) {
    if (i == text_len || s[i] == '\n') {
      if (i > start) {
        EncodeWord(enc, s + start, i - start, &ids);
        // Re-check the memo cap inside huge single-call texts too. Only
        // word_cache may flush mid-call: OOV sentinels already emitted
        // into `ids` THIS call reference oov_symbols, so those tables
        // flush only between calls (entry check above) — one call's OOV
        // growth is bounded by its distinct unknown words.
        if ((++words & 0xfff) == 0 &&
            enc->word_cache.size() > kWordCacheCap) {
          enc->word_cache.clear();
        }
      }
      start = i + 1;
    }
  }
  int64_t n = static_cast<int64_t>(ids.size());
  if (n > 0 && cap > 0) {
    std::memcpy(out, ids.data(),
                static_cast<size_t>(std::min(n, cap)) * sizeof(int32_t));
  }
  return n;
}

int64_t dpt_bpe_oov_count(void* h) {
  auto enc = static_cast<Encoder*>(h);
  std::lock_guard<std::mutex> lock(enc->mu);
  return static_cast<int64_t>(enc->oov_symbols.size());
}

// Copy OOV symbol k (UTF-8) into buf; returns its byte length (call with
// cap=0 to size the buffer), or -1 if k is out of range.
int64_t dpt_bpe_oov_get(void* h, int64_t k, uint8_t* buf, int64_t cap) {
  auto enc = static_cast<Encoder*>(h);
  std::lock_guard<std::mutex> lock(enc->mu);
  if (k < 0 || k >= static_cast<int64_t>(enc->oov_symbols.size())) {
    return -1;
  }
  const std::string& s = enc->oov_symbols[static_cast<size_t>(k)];
  int64_t n = static_cast<int64_t>(s.size());
  if (cap > 0) {
    std::memcpy(buf, s.data(),
                static_cast<size_t>(std::min(n, cap)));
  }
  return n;
}

}  // extern "C"
