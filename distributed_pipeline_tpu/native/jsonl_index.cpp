// Native jsonl corpus index: mmap + newline offset table.
//
// The Python JsonlSeq2SeqDataset (data/dataset.py) reads every line of the
// corpus into a Python list — O(corpus) host memory per process, paid again
// by every loader worker. This component replaces that with the classic
// native data-loader design (the role torch's C++ DataLoader internals play
// for the reference, SURVEY.md §2.1): the file is mmap'd read-only (pages
// stream in on demand, shared across processes by the page cache) and a
// single scan builds an offset table of non-blank lines. Random access is
// then one memcpy of one line.
//
// Line-splitting and blank-filtering match Python's text-mode file
// iteration exactly: terminators are \n, \r, and \r\n (universal
// newlines), and "blank" means every code point satisfies Python's
// str.isspace() — the same `ln.strip()` filter the Python fallback path
// applies. A corpus must index identically whether or not the native
// build succeeded.
//
// IMMUTABLE-CORPUS ASSUMPTION: the offset table is built once at open and
// the mapping is never revalidated. If the file is truncated or rewritten
// while a training run holds it open, later dpt_jsonl_get reads can touch
// unmapped pages and SIGBUS the process (the Python fallback, which copies
// lines at open, would not). Treat training corpora as append-never,
// replace-by-rename artifacts — the standard contract for mmap'd data.
//
// C ABI (ctypes, native/__init__.py):
//   dpt_jsonl_open(path)          -> handle | nullptr (open/mmap error)
//   dpt_jsonl_count(h)            -> number of non-blank lines
//   dpt_jsonl_get(h, i, buf, cap) -> byte length of line i (newline
//                                    stripped); copies min(len, cap) bytes;
//                                    -1 if i out of range
//   dpt_jsonl_close(h)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Index {
  const char* data = nullptr;
  size_t size = 0;
  int fd = -1;
  // line i = [starts[i], starts[i] + lens[i])
  std::vector<size_t> starts;
  std::vector<size_t> lens;
};

// Python str.isspace() code points (CPython Unicode WS + bidirectional
// classes): ASCII 0x09-0x0D, 0x1C-0x1F, 0x20, then 0x85, 0xA0, 0x1680,
// 0x2000-0x200A, 0x2028, 0x2029, 0x202F, 0x205F, 0x3000.
bool IsPySpace(uint32_t cp) {
  return (cp >= 0x09 && cp <= 0x0D) || (cp >= 0x1C && cp <= 0x20) ||
         cp == 0x85 || cp == 0xA0 || cp == 0x1680 ||
         (cp >= 0x2000 && cp <= 0x200A) || cp == 0x2028 || cp == 0x2029 ||
         cp == 0x202F || cp == 0x205F || cp == 0x3000;
}

// Blank = every UTF-8 code point is Python whitespace (mirrors
// `ln.strip()` in the fallback). Malformed UTF-8 counts as non-blank —
// json.loads would fail on it either way, and "keep the line" matches
// what Python does with the undecodable-but-kept bytes it can read.
bool IsBlank(const char* s, size_t n) {
  size_t i = 0;
  while (i < n) {
    unsigned char c = static_cast<unsigned char>(s[i]);
    uint32_t cp = c;
    size_t len = 1;
    if (c >= 0xF0) {
      len = 4;
    } else if (c >= 0xE0) {
      len = 3;
    } else if (c >= 0xC0) {
      len = 2;
    } else if (c >= 0x80) {
      return false;  // stray continuation byte
    }
    if (i + len > n) return false;
    if (len > 1) {
      cp = c & (0xFF >> (len + 1));
      for (size_t j = 1; j < len; ++j) {
        unsigned char cc = static_cast<unsigned char>(s[i + j]);
        if ((cc & 0xC0) != 0x80) return false;
        cp = (cp << 6) | (cc & 0x3F);
      }
    }
    if (!IsPySpace(cp)) return false;
    i += len;
  }
  return true;
}

}  // namespace

extern "C" {

void* dpt_jsonl_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 0) {
    ::close(fd);
    return nullptr;
  }
  auto idx = new Index();
  idx->fd = fd;
  idx->size = static_cast<size_t>(st.st_size);
  if (idx->size > 0) {
    void* p = mmap(nullptr, idx->size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (p == MAP_FAILED) {
      ::close(fd);
      delete idx;
      return nullptr;
    }
    idx->data = static_cast<const char*>(p);
  }
  // Universal newlines: \n, \r, and \r\n all terminate a line (Python
  // text-mode file iteration).
  size_t start = 0;
  for (size_t i = 0; i <= idx->size; ++i) {
    bool at_end = (i == idx->size);
    char c = at_end ? '\0' : idx->data[i];
    if (at_end || c == '\n' || c == '\r') {
      size_t len = i - start;
      if (len > 0 && !IsBlank(idx->data + start, len)) {
        idx->starts.push_back(start);
        idx->lens.push_back(len);
      }
      if (c == '\r' && i + 1 < idx->size && idx->data[i + 1] == '\n') {
        ++i;  // \r\n is one terminator
      }
      start = i + 1;
    }
  }
  return idx;
}

int64_t dpt_jsonl_count(void* h) {
  return static_cast<int64_t>(static_cast<Index*>(h)->starts.size());
}

int64_t dpt_jsonl_get(void* h, int64_t i, uint8_t* buf, int64_t cap) {
  auto idx = static_cast<Index*>(h);
  if (i < 0 || i >= static_cast<int64_t>(idx->starts.size())) return -1;
  size_t n = idx->lens[static_cast<size_t>(i)];
  if (cap > 0) {
    std::memcpy(buf, idx->data + idx->starts[static_cast<size_t>(i)],
                std::min(n, static_cast<size_t>(cap)));
  }
  return static_cast<int64_t>(n);
}

void dpt_jsonl_close(void* h) {
  auto idx = static_cast<Index*>(h);
  if (idx->data) munmap(const_cast<char*>(idx->data), idx->size);
  if (idx->fd >= 0) ::close(idx->fd);
  delete idx;
}

}  // extern "C"
