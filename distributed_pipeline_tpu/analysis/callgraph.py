"""Interprocedural graftlint: the whole-program call-graph pass.

The per-module rules (GL001..GL010) see one AST at a time, which left
three audited blind spots (ROADMAP item 6): tracedness did not propagate
through ordinary calls, GL003 donation tracking stopped at module scope
(the r6 orbax-restore corruption crossed exactly such a boundary), and
GL005 could not see ``static_argnums`` declared far from the call site.
This module turns those heuristics into proofs:

* :func:`summarize_module` distills one parsed :class:`~core.Module`
  into a **serializable** :class:`ModuleSummary` — per-function facts
  (host-sync sites rooted at parameters, PRNG-key consumption, call
  sites with signature-shaped argument descriptors, statement-ordered
  read/bind events) plus the module's symbol table (functions, classes,
  jit/partial bindings, absolutized import aliases). Serializable is
  load-bearing: the content-hash cache (:mod:`cache`) stores summaries
  keyed on file sha, so unchanged modules are never reparsed while the
  cross-module pass stays exact.
* :class:`CallGraph` links the summaries: imports resolve
  module-to-module (through re-export chains, ``functools.partial``
  bindings, and ``self.`` method calls), call-site arguments map to
  callee parameters signature-aware (positional/keyword; ``*args`` and
  ``**kwargs`` at a call site **widen honestly** — the mapping is
  dropped rather than guessed), and monotone fixpoints flow four fact
  families across call and module boundaries until stable (cycles and
  recursion converge; an unknown callee contributes nothing, so a fact
  is only ever *proven*, never assumed):

  - **tracedness**: a function reachable from any jit/scan-traced
    context is traced — its parameter-rooted host syncs are GL002
    findings even when the helper lives two modules away;
  - **blocking params**: a parameter a function (transitively)
    ``float()``s / ``.item()``s — a loop passing a jitted step's output
    into such a helper is a GL007 finding at the call site;
  - **key consumption**: a parameter a function (transitively) feeds to
    a ``jax.random`` sampler — the GL011 replay proves cross-module key
    reuse instead of guessing from parameter names;
  - **donation**: a parameter a function (transitively) passes at a
    donated position of a jitted binding — reading a tree after the
    donating call is GL003 even when donor and reader never share a
    module (the r6 shape).

Emission is owned here (the rules' ``check_graph`` methods delegate) so
the propagation machinery and the messages that cite witness chains
stay in one place.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

from .core import (
    TRACE_WRAPPERS,
    TRACED_ARG_POS,
    TRACED_ARG_SUFFIXES,
    Finding,
    Module,
)

__all__ = ["CallGraph", "ModuleSummary", "module_name_for_path",
           "summarize_module"]

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_LOOPS = (ast.For, ast.AsyncFor, ast.While)
_MAX_CHAIN = 32  # resolution chain cap (alias/partial/re-export hops)


# =========================================================== module naming

def module_name_for_path(path: str) -> Tuple[str, bool]:
    """(dotted module name, is_package) for a file path, by walking up
    while ``__init__.py`` markers continue — mirrors how the interpreter
    would import the file from the package root. A bare file in a
    non-package dir is a top-level module named by its stem."""
    p = os.path.abspath(path)
    d, base = os.path.split(p)
    stem = base[:-3] if base.endswith(".py") else base
    is_pkg = stem == "__init__"
    parts: List[str] = [] if is_pkg else [stem]
    while os.path.isfile(os.path.join(d, "__init__.py")):
        nd, name = os.path.split(d)
        if not name or nd == d:
            break
        parts.append(name)
        d = nd
    if not parts:  # degenerate: an __init__.py outside any package
        parts = [os.path.basename(os.path.dirname(p)) or "module"]
    return ".".join(reversed(parts)), is_pkg


def _absolutize(origin: str, modname: str, is_pkg: bool) -> str:
    """Resolve a relative import origin (``.x``, ``..utils.y``) against
    the importing module's dotted name; absolute origins pass through.
    Unresolvable relatives (more dots than package depth) are returned
    unchanged — they simply never match a module."""
    if not origin.startswith("."):
        return origin
    level = len(origin) - len(origin.lstrip("."))
    rest = [s for s in origin[level:].split(".") if s]
    base = modname.split(".")
    drop = level - 1 if is_pkg else level
    if drop < 0 or drop >= len(base) + (1 if is_pkg else 0):
        return origin
    kept = base[:len(base) - drop] if drop else base
    if not kept:
        return origin
    return ".".join(kept + rest)


# ======================================================== module summaries

@dataclasses.dataclass
class ModuleSummary:
    """Everything the cross-module pass needs from one file, as plain
    JSON-shaped data (the cache serializes this verbatim)."""

    path: str
    modname: str
    is_package: bool
    aliases: Dict[str, str]
    funcs: Dict[str, dict]
    classes: Dict[str, List[str]]
    jit_bindings: Dict[str, dict]
    partials: Dict[str, dict]
    local_donations: List[str]
    local_jitted: List[str]
    traced_refs: List[str]

    @property
    def relname(self) -> str:
        return "/".join(self.path.replace(os.sep, "/").split("/")[-2:])

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ModuleSummary":
        return cls(**{f.name: d[f.name] for f in dataclasses.fields(cls)})


def _site(node: ast.AST, module: Module) -> dict:
    line = getattr(node, "lineno", 1)
    return {"line": line, "col": getattr(node, "col_offset", 0) + 1,
            "snippet": module.snippet(line)}


def _root_of(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _shallow(node: ast.AST) -> Iterator[ast.AST]:
    """This statement's own expression nodes: no nested statements (the
    flatten walk visits those separately), no nested function bodies."""
    stack: List[ast.AST] = [node]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(c, ast.stmt) or isinstance(c, _FUNC_DEFS) \
                    or isinstance(c, ast.Lambda):
                continue
            stack.append(c)


def _scalar_hazard(arg: ast.AST) -> Optional[str]:
    """The GL005 hazard shapes (one owner shared with the local rule):
    a ``len()`` scalar, a ``.shape``-derived value, or an f-string."""
    if isinstance(arg, ast.JoinedStr):
        return "an f-string (fresh object per call)"
    for n in ast.walk(arg):
        if isinstance(n, _FUNC_DEFS) or isinstance(n, ast.Lambda):
            return None
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "len":
            return "a len() python scalar"
        if isinstance(n, ast.Attribute) and n.attr == "shape":
            return "a .shape-derived python value"
    return None


# ---- semantic fact tables shared with the per-module rules (rules.py
# imports these; callgraph must not import rules — that would cycle)

SYNC_NP = {"asarray", "array", "sum", "mean", "std", "var", "max", "min",
           "argmax", "argmin", "any", "all", "allclose", "isnan",
           "isfinite", "isinf", "where", "concatenate", "stack", "dot",
           "matmul", "prod", "abs", "clip", "sqrt", "exp", "log",
           "float32", "float64", "int32", "int64"}
NP_BLOCKERS = {"numpy.asarray", "numpy.array"}
BLOCKING_BUILTINS = {"float", "int", "bool"}
STEP_ATTRS = {"run_step", "forward_only", "train_step", "eval_step"}
KEY_DERIVERS = {"PRNGKey", "key", "fold_in", "key_data", "wrap_key_data",
                "clone", "key_impl"}
KEY_PARAM_PAT = ("rng", "key", "prng", "seed_key")


def is_key_param(name: str) -> bool:
    low = name.lower()
    return any(low == p or low.endswith("_" + p) or low.startswith(p + "_")
               or low.rstrip("0123456789") == p for p in KEY_PARAM_PAT)


def _sync_hit(module: Module, call: ast.Call,
              params: Set[str]) -> Optional[dict]:
    """A host-sync operation in ``call`` whose operand roots at one of
    ``params`` — the only syncs a *caller* can cause (traced values flow
    in through arguments), so the transitive findings stay proofs."""
    func = call.func
    fn = module.resolve(func)
    if isinstance(func, ast.Attribute) and func.attr == "item" \
            and not call.args:
        root = _root_of(func.value)
        if root in params:
            return {"param": root, "desc": ".item()", "blocking": True}
    if isinstance(func, ast.Name) and func.id in BLOCKING_BUILTINS \
            and len(call.args) == 1 \
            and not isinstance(call.args[0], ast.Constant):
        root = _root_of(call.args[0])
        if root in params:
            return {"param": root, "desc": f"{func.id}()",
                    "blocking": True}
    if fn and fn.startswith("numpy.") and fn.split(".")[-1] in SYNC_NP:
        for a in call.args:
            root = _root_of(a)
            if root in params:
                return {"param": root, "desc": fn,
                        "blocking": fn in NP_BLOCKERS}
    if fn == "jax.device_get" and call.args:
        root = _root_of(call.args[0])
        if root in params:
            return {"param": root, "desc": "jax.device_get",
                    "blocking": False}
    if isinstance(func, ast.Attribute) and func.attr == "block_until_ready":
        root = _root_of(func.value)
        if root in params:
            return {"param": root, "desc": "block_until_ready",
                    "blocking": False}
    return None


def _loop_bound_names(loop: ast.AST) -> Set[str]:
    """Names (re)bound anywhere inside the loop body (not nested defs)."""
    out: Set[str] = set()
    stack: List[ast.AST] = list(ast.iter_child_nodes(loop))
    while stack:
        n = stack.pop()
        if isinstance(n, _FUNC_DEFS) or isinstance(n, ast.Lambda) \
                or isinstance(n, ast.ClassDef):
            continue
        targets: List[ast.AST] = []
        if isinstance(n, ast.Assign):
            targets = list(n.targets)
        elif isinstance(n, (ast.AnnAssign, ast.AugAssign)):
            targets = [n.target]
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            targets = [n.target]
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            out |= {e.id for e in elts if isinstance(e, ast.Name)}
        stack.extend(ast.iter_child_nodes(n))
    return out


def _loop_step_names(module: Module, loop: ast.AST) -> Set[str]:
    """Names assigned in the loop from a jitted-step-shaped call — the
    values GL007 cares about (same heuristics as the local rule)."""
    names: Set[str] = set()
    for nd in ast.walk(loop):
        if not isinstance(nd, ast.Assign) \
                or not isinstance(nd.value, ast.Call):
            continue
        func = nd.value.func
        hit = isinstance(func, ast.Attribute) and func.attr in STEP_ATTRS
        if not hit:
            try:
                hit = ast.unparse(func) in module.jitted_bindings
            except Exception:  # pragma: no cover - defensive
                hit = False
        if not hit:
            continue
        for t in nd.targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            names |= {e.id for e in elts if isinstance(e, ast.Name)}
    return names


def _stmt_binds(s: ast.stmt) -> List[str]:
    targets: List[Optional[ast.AST]] = []
    if isinstance(s, ast.Assign):
        targets = list(s.targets)
    elif isinstance(s, (ast.AnnAssign, ast.AugAssign)):
        targets = [s.target]
    elif isinstance(s, (ast.For, ast.AsyncFor)):
        targets = [s.target]
    elif isinstance(s, (ast.With, ast.AsyncWith)):
        targets = [i.optional_vars for i in s.items if i.optional_vars]
    out: List[str] = []
    for t in targets:
        if t is None:
            continue
        elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
        for e in elts:
            if isinstance(e, ast.Name):
                out.append(e.id)
            elif isinstance(e, (ast.Attribute, ast.Subscript)):
                try:
                    out.append(ast.unparse(e))
                except Exception:  # pragma: no cover - defensive
                    pass
    return out


def _iter_funcs(tree: ast.AST) -> Iterator[Tuple[str, Optional[str],
                                                 ast.AST]]:
    """(qualname, enclosing class or None, def node) for every named
    function, including nested defs (``outer.inner``) and methods
    (``Class.method``)."""

    def visit(node: ast.AST, prefix: str,
              cls: Optional[str]) -> Iterator[Tuple[str, Optional[str],
                                                    ast.AST]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_DEFS):
                q = prefix + child.name
                yield q, cls, child
                yield from visit(child, q + ".", cls)
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, prefix + child.name + ".",
                                 child.name)

    yield from visit(tree, "", None)


def _terminates(stmts: List[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


def _summarize_function(module: Module, qual: str, cls: Optional[str],
                        node: ast.AST) -> dict:
    a = node.args
    params = [p.arg for p in a.posonlyargs + a.args]
    kwonly = [p.arg for p in a.kwonlyargs]
    pset = set(params) | set(kwonly)
    self_like = (bool(cls) and bool(params)
                 and params[0] in ("self", "cls")
                 and not any(module.resolve(d) == "staticmethod"
                             for d in node.decorator_list))

    # which loops are untraced (GL007 jurisdiction) + their step names
    loop_cache: Dict[int, Tuple[Set[str], Set[str]]] = {}

    def loop_facts(loop: ast.AST) -> Tuple[Set[str], Set[str]]:
        key = id(loop)
        if key not in loop_cache:
            steps = (set() if module.in_traced(loop)
                     else _loop_step_names(module, loop))
            loop_cache[key] = (steps, _loop_bound_names(loop))
        return loop_cache[key]

    calls: List[dict] = []
    syncs: List[dict] = []
    candidates: Set[str] = set()
    pending: List[Tuple[ast.stmt, dict]] = []

    def make_ev(s: ast.stmt, loop: Optional[ast.AST]) -> dict:
        ev: dict = {"calls": [], "binds": [], "fresh": [],
                    "reads": [], "kuses": [], "ksplits": []}
        step_names, loop_bound = loop_facts(loop) if loop is not None \
            else (set(), set())
        stmt_calls = [n for n in _shallow(s) if isinstance(n, ast.Call)]
        stmt_calls.sort(key=lambda c: (getattr(c, "lineno", 0),
                                       getattr(c, "col_offset", 0)))
        for call in stmt_calls:
            fn = module.resolve(call.func)
            # direct PRNG use/split events (GL001 semantics, recorded so
            # the GL011 replay can mix direct and cross-module consumers)
            if fn and fn.startswith("jax.random."):
                member = fn.rsplit(".", 1)[1]
                # jax.random.* consume the KEY argument only — the
                # first positional (or key=); counting shape/count args
                # would poison the key-consumption fixpoint
                key_args = [a for a in call.args[:1]
                            if isinstance(a, ast.Name)]
                key_args += [k.value for k in call.keywords
                             if k.arg == "key"
                             and isinstance(k.value, ast.Name)]
                for arg in key_args:
                    if member == "split":
                        ev["ksplits"].append(
                            {"name": arg.id, **_site(call, module)})
                    elif member not in KEY_DERIVERS:
                        ev["kuses"].append(
                            {"name": arg.id, "desc": fn,
                             **_site(call, module)})
            hit = _sync_hit(module, call, pset)
            if hit:
                syncs.append({**hit, **_site(call, module)})
            try:
                callee = ast.unparse(call.func)
            except Exception:  # pragma: no cover - defensive
                continue
            if not isinstance(call.func, (ast.Name, ast.Attribute)):
                continue  # calls of call results etc.: unresolvable

            def desc(arg: ast.AST) -> dict:
                d: dict = {}
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    try:
                        d["name"] = ast.unparse(arg)
                    except Exception:  # pragma: no cover - defensive
                        pass
                if isinstance(arg, ast.Name):
                    d["simple"] = True
                if isinstance(arg, ast.Constant):
                    d["const"] = True
                root = _root_of(arg)
                if root:
                    d["root"] = root
                    if root in step_names:
                        d["step"] = True
                hz = _scalar_hazard(arg)
                if hz:
                    d["hazard"] = hz
                    d.update({f"h{k}": v
                              for k, v in _site(arg, module).items()})
                return d

            site = {
                "callee": callee,
                **_site(call, module),
                "pos": [desc(arg) for arg in call.args
                        if not isinstance(arg, ast.Starred)],
                "kw": {k.arg: desc(k.value) for k in call.keywords
                       if k.arg},
                "star": (any(isinstance(arg, ast.Starred)
                             for arg in call.args)
                         or any(k.arg is None for k in call.keywords)),
                "in_loop": loop is not None,
                "loop_rebound": sorted(loop_bound) if loop is not None
                else [],
            }
            for d in site["pos"] + list(site["kw"].values()):
                if d.get("root"):
                    candidates.add(d["root"])
            ev["calls"].append(len(calls))
            calls.append(site)
        binds = _stmt_binds(s)
        ev["binds"] = binds
        if isinstance(s, ast.Assign) and isinstance(s.value, ast.Call):
            vfn = module.resolve(s.value.func)
            if vfn and vfn.startswith("jax.random.") \
                    and vfn.rsplit(".", 1)[1] in (KEY_DERIVERS | {"split"}):
                ev["fresh"] = [b for b in binds if "." not in b]
        pending.append((s, ev))
        return ev

    def build(body: List[ast.stmt], loop: Optional[ast.AST]
              ) -> List[dict]:
        """Statement-event tree in source order. ``if`` branches become
        nested {"branches": [{"events", "terminates"}, ...]} entries so
        the replays can give each arm its own state copy and drop
        terminated arms — a consumption inside an early-``return`` body
        must not leak into the fall-through path (the GL001 semantics,
        kept at the summary level)."""
        out: List[dict] = []
        for s in body:
            if isinstance(s, _FUNC_DEFS) or isinstance(s, ast.ClassDef):
                continue
            out.append(make_ev(s, loop))
            if isinstance(s, ast.If):
                branches = []
                for sub in (s.body, s.orelse):
                    if not sub:
                        continue
                    branches.append({"events": build(sub, loop),
                                     "terminates": _terminates(sub)})
                if any(br["events"] or br["terminates"]
                       for br in branches):
                    out.append({"branches": branches})
            elif isinstance(s, _LOOPS):
                out.extend(build(s.body, s))
                out.extend(build(s.orelse, loop))
            else:
                for field in ("body", "orelse", "finalbody"):
                    out.extend(build(getattr(s, field, []) or [], loop))
                for h in getattr(s, "handlers", []) or []:
                    out.extend(build(h.body, loop))
        return out

    events = build(node.body, None)

    # second pass: reads of candidate roots (donation liveness needs the
    # loads BETWEEN call sites, in order)
    for s, ev in pending:
        if not candidates:
            break
        for n in _shallow(s):
            if not isinstance(n, (ast.Name, ast.Attribute)):
                continue
            if not isinstance(getattr(n, "ctx", None), ast.Load):
                continue
            parent = module.parent.get(n)
            if isinstance(parent, (ast.Attribute, ast.Subscript)) \
                    and getattr(parent, "value", None) is n:
                continue  # outermost chain node only
            if isinstance(parent, ast.Call) and parent.func is n:
                continue  # the callee position is not a data read
            root = _root_of(n)
            if root not in candidates:
                continue
            try:
                text = ast.unparse(n)
            except Exception:  # pragma: no cover - defensive
                continue
            ev["reads"].append({"text": text, **_site(n, module)})

    def prune(evs: List[dict]) -> List[dict]:
        out = []
        for ev in evs:
            if "branches" in ev:
                for br in ev["branches"]:
                    br["events"] = prune(br["events"])
                if any(br["events"] or br["terminates"]
                       for br in ev["branches"]):
                    out.append(ev)
            elif any(ev[k] for k in ("calls", "binds", "fresh", "reads",
                                     "kuses", "ksplits")):
                out.append(ev)
        return out

    events = prune(events)

    return {
        "qual": qual,
        "cls": cls,
        "line": getattr(node, "lineno", 1),
        "params": params,
        "kwonly": kwonly,
        "vararg": a.vararg is not None,
        "kwarg": a.kwarg is not None,
        "self_like": self_like,
        # in_traced, not bare membership: a def nested INSIDE a traced
        # function is lexically traced too — its sync sites belong to
        # the local GL002 rule, and the graph half must not double-
        # report them (it still seeds the traced closure correctly)
        "directly_traced": (node in module.traced
                            or module.in_traced(node)),
        "calls": calls,
        "syncs": syncs,
        "events": events,
    }


def summarize_module(module: Module) -> ModuleSummary:
    modname, is_pkg = module_name_for_path(module.path)
    aliases = {k: _absolutize(v, modname, is_pkg)
               for k, v in module.imports.alias.items()}
    funcs: Dict[str, dict] = {}
    classes: Dict[str, List[str]] = {}
    for qual, cls, node in _iter_funcs(module.tree):
        funcs[qual] = _summarize_function(module, qual, cls, node)
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef):
            classes[node.name] = [b.name for b in node.body
                                  if isinstance(b, _FUNC_DEFS)]
    partials: Dict[str, dict] = {}
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        fn = module.resolve(node.value.func)
        if fn not in ("functools.partial", "partial"):
            continue
        if not node.value.args:
            continue
        tgt = node.value.args[0]
        if not isinstance(tgt, (ast.Name, ast.Attribute)):
            continue
        partials[node.targets[0].id] = {
            "target": ast.unparse(tgt),
            "n_pos": len(node.value.args) - 1,
            "kw": [k.arg for k in node.value.keywords if k.arg],
        }
    traced_refs: List[str] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = module.resolve(node.func)
        positions: Tuple[int, ...] = ()
        if module._wrapper_name(node.func) is not None and node.args:
            positions = (0,)
        elif fn in TRACED_ARG_POS:
            positions = TRACED_ARG_POS[fn]
        elif fn is not None:
            for suffix, pos in TRACED_ARG_SUFFIXES.items():
                if fn.split(".")[-1] == suffix:
                    positions = pos
        for p in positions:
            if p < len(node.args) and isinstance(
                    node.args[p], (ast.Name, ast.Attribute)):
                traced_refs.append(ast.unparse(node.args[p]))
    return ModuleSummary(
        path=module.path,
        modname=modname,
        is_package=is_pkg,
        aliases=aliases,
        funcs=funcs,
        classes=classes,
        jit_bindings={k: dict(v) for k, v in module.jit_info.items()
                      if "." not in k},  # only plain names are importable
        partials=partials,
        local_donations=sorted(module.donations),
        local_jitted=sorted(module.jitted_bindings),
        traced_refs=traced_refs,
    )


# ============================================================== call graph

@dataclasses.dataclass
class Target:
    """Resolution of a call-site callee: a function summary, a jitted
    binding (with its donate/static facts and, when resolvable, the
    wrapped function), or unknown (honest widening: contributes no
    facts). ``offset`` is the positional shift accumulated through
    ``functools.partial`` chains."""

    kind: str                         # "func" | "jit" | "unknown"
    module: Optional[ModuleSummary] = None
    qual: Optional[str] = None
    offset: int = 0
    self_call: bool = False
    jit: Optional[dict] = None

    @property
    def fid(self) -> Optional[Tuple[str, str]]:
        if self.module is not None and self.qual is not None:
            return (self.module.path, self.qual)
        return None

    def label(self) -> str:
        if self.module is not None and self.qual is not None:
            return f"{self.module.relname}:{self.qual}"
        return "<unknown>"


_UNKNOWN = Target("unknown")


class CallGraph:
    """Whole-program view over every module summary: symbol resolution,
    the call-edge table, and the four fixpoint fact families. All
    construction is lazy (``_build``) and pure over summaries, so a
    cache-served run never needs an AST."""

    def __init__(self, summaries: Dict[str, ModuleSummary]) -> None:
        self.by_path: Dict[str, ModuleSummary] = dict(summaries)
        self.by_name: Dict[str, ModuleSummary] = {}
        for s in self.by_path.values():
            self.by_name.setdefault(s.modname, s)
        self._built = False

    # ---------------------------------------------------------- resolution

    def _find_module(self, dotted: str
                     ) -> Optional[Tuple[ModuleSummary, str]]:
        parts = dotted.split(".")
        for i in range(len(parts), 0, -1):
            m = self.by_name.get(".".join(parts[:i]))
            if m is not None:
                return m, ".".join(parts[i:])
        return None

    def resolve(self, mod: ModuleSummary, text: str,
                scope_qual: Optional[str] = None,
                cls: Optional[str] = None) -> Target:
        return self._resolve(mod, text, scope_qual, cls, set())

    def _resolve(self, mod: ModuleSummary, text: str,
                 scope_qual: Optional[str],
                 cls: Optional[str],
                 seen: Set[Tuple[str, str]]) -> Target:
        offset = 0
        for _ in range(_MAX_CHAIN):
            key = (mod.path, text)
            if key in seen:
                return _UNKNOWN  # import cycle in the alias chain
            seen.add(key)
            if cls and text.startswith(("self.", "cls.")):
                m = text.split(".", 1)[1]
                if "." not in m and m in mod.classes.get(cls, ()):
                    return Target("func", mod, f"{cls}.{m}", offset,
                                  self_call=True)
                return _UNKNOWN
            if "." not in text:
                if scope_qual:  # nested def visible from the scope chain
                    parts = scope_qual.split(".")
                    for i in range(len(parts), 0, -1):
                        cand = ".".join(parts[:i]) + "." + text
                        if cand in mod.funcs:
                            return Target("func", mod, cand, offset)
                if text in mod.funcs:
                    return Target("func", mod, text, offset)
                if text in mod.jit_bindings:
                    info = mod.jit_bindings[text]
                    inner = _UNKNOWN
                    if info.get("target"):
                        # same `seen` guard: `f = jax.jit(f)` rebinding
                        # chains must terminate, not recurse
                        inner = self._resolve(mod, info["target"],
                                              None, None, seen)
                    return Target("jit", inner.module, inner.qual,
                                  offset, jit=info)
                if text in mod.partials:
                    p = mod.partials[text]
                    offset += int(p["n_pos"])
                    text = p["target"]
                    scope_qual = cls = None
                    continue
                if text in mod.aliases:
                    found = self._find_module(mod.aliases[text])
                    if found is None:
                        return _UNKNOWN
                    mod, rest = found
                    if not rest:
                        return _UNKNOWN  # a module object, not a callable
                    text = rest
                    scope_qual = cls = None
                    continue
                return _UNKNOWN
            root, rest = text.split(".", 1)
            if root in mod.aliases:
                found = self._find_module(mod.aliases[root] + "." + rest)
                if found is None:
                    return _UNKNOWN
                mod, text = found
                if not text:
                    return _UNKNOWN
                scope_qual = cls = None
                continue
            if root in mod.classes and "." not in rest \
                    and rest in mod.classes[root]:
                # Class.method(obj, ...): arg 0 binds self, no shift
                return Target("func", mod, f"{root}.{rest}", offset)
            return _UNKNOWN
        return _UNKNOWN

    # -------------------------------------------------------- construction

    def _build(self) -> None:
        if self._built:
            return
        # resolved call targets, aligned with each function's calls list
        self.targets: Dict[Tuple[str, str], List[Target]] = {}
        # call edges into each function: fid -> [(caller fid, site, target)]
        self.edges_in: Dict[Tuple[str, str],
                            List[Tuple[Tuple[str, str], dict,
                                       Target]]] = {}
        for path, mod in self.by_path.items():
            for qual, fs in mod.funcs.items():
                fid = (path, qual)
                resolved: List[Target] = []
                for site in fs["calls"]:
                    t = self.resolve(mod, site["callee"],
                                     scope_qual=qual, cls=fs.get("cls"))
                    resolved.append(t)
                    tfid = t.fid
                    if tfid is not None:
                        self.edges_in.setdefault(tfid, []).append(
                            (fid, site, t))
                self.targets[fid] = resolved
        self._traced = self._traced_closure()
        self._blocking = self._param_fixpoint(self._blocking_seeds())
        self._keys = self._param_fixpoint(self._key_seeds())
        self._donating = self._param_fixpoint(self._donation_seeds())
        self._built = True  # only a COMPLETE build counts (an exception
        # mid-build must rebuild, not serve half-initialized fact maps)

    def _func(self, fid: Tuple[str, str]) -> dict:
        return self.by_path[fid[0]].funcs[fid[1]]

    # tracedness: function-level reachability from traced contexts
    def _traced_closure(self) -> Dict[Tuple[str, str],
                                      Optional[Tuple[Tuple[str, str],
                                                     dict]]]:
        """fid -> witness (caller fid, call site) or None for seeds."""
        closure: Dict[Tuple[str, str], Optional[Tuple[Tuple[str, str],
                                                      dict]]] = {}
        queue: List[Tuple[str, str]] = []
        for path, mod in self.by_path.items():
            for qual, fs in mod.funcs.items():
                if fs["directly_traced"]:
                    closure[(path, qual)] = None
                    queue.append((path, qual))
            seeds: List[Target] = []
            for info in mod.jit_bindings.values():
                if info.get("target"):
                    seeds.append(self.resolve(mod, info["target"]))
            for ref in mod.traced_refs:
                seeds.append(self.resolve(mod, ref))
            for t in seeds:
                fid = t.fid
                if t.kind == "func" and fid is not None \
                        and fid not in closure:
                    closure[fid] = None
                    queue.append(fid)
        while queue:
            fid = queue.pop()
            for site, target in zip(self._func(fid)["calls"],
                                    self.targets[fid]):
                nxt = target.fid
                if nxt is None or nxt in closure:
                    continue
                closure[nxt] = (fid, site)
                queue.append(nxt)
        return closure

    # generic backward (callee -> caller) parameter-taint fixpoint
    def _param_fixpoint(self, seeds: Dict[Tuple[str, str, str], dict]
                        ) -> Dict[Tuple[str, str, str], dict]:
        """seeds: (path, qual, param) -> {"desc", "line", "snippet"}
        (terminal facts). Propagates through call sites whose argument
        roots at a caller parameter; each propagated entry records its
        next hop so messages can cite the chain. Monotone set growth +
        finite universe => cycles/recursion converge."""
        facts = dict(seeds)
        changed = True
        while changed:
            changed = False
            for fid, resolved in self.targets.items():
                fs = self._func(fid)
                pset = set(fs["params"]) | set(fs["kwonly"])
                for site, target in zip(fs["calls"], resolved):
                    mapping = self.map_args(site, target)
                    if not mapping:
                        continue
                    tfid = target.fid
                    if tfid is None:
                        continue
                    for arg, pname in mapping:
                        root = arg.get("root")
                        if root not in pset:
                            continue
                        down = facts.get((tfid[0], tfid[1], pname))
                        if down is None:
                            continue
                        key = (fid[0], fid[1], root)
                        if key in facts:
                            continue
                        facts[key] = {"via": site, "via_label":
                                      target.label(), "next": down}
                        changed = True
        return facts

    def _blocking_seeds(self) -> Dict[Tuple[str, str, str], dict]:
        seeds: Dict[Tuple[str, str, str], dict] = {}
        for path, mod in self.by_path.items():
            for qual, fs in mod.funcs.items():
                for s in fs["syncs"]:
                    if s.get("blocking"):
                        seeds.setdefault((path, qual, s["param"]), s)
        return seeds

    @staticmethod
    def _iter_stmt_events(events: List[dict]) -> Iterator[dict]:
        """Flat view over an event tree (branch structure is only
        meaningful to the ordered replays; seeding is order-free)."""
        stack = list(reversed(events))
        while stack:
            ev = stack.pop()
            if "branches" in ev:
                for br in ev["branches"]:
                    stack.extend(reversed(br["events"]))
                continue
            yield ev

    def _key_seeds(self) -> Dict[Tuple[str, str, str], dict]:
        seeds: Dict[Tuple[str, str, str], dict] = {}
        for path, mod in self.by_path.items():
            for qual, fs in mod.funcs.items():
                pset = set(fs["params"]) | set(fs["kwonly"])
                for ev in self._iter_stmt_events(fs["events"]):
                    for u in ev["kuses"] + ev["ksplits"]:
                        if u["name"] in pset:
                            seeds.setdefault(
                                (path, qual, u["name"]),
                                {"desc": u.get("desc", "jax.random.split"),
                                 "line": u["line"],
                                 "snippet": u["snippet"]})
        return seeds

    def _donation_seeds(self) -> Dict[Tuple[str, str, str], dict]:
        """Parameters passed directly at a donated position of a jit
        binding; the generic fixpoint then carries donation up through
        forwarding callers."""
        seeds: Dict[Tuple[str, str, str], dict] = {}
        for fid, resolved in self.targets.items():
            fs = self._func(fid)
            pset = set(fs["params"]) | set(fs["kwonly"])
            for site, target in zip(fs["calls"], resolved):
                if target.kind != "jit" or not target.jit \
                        or not target.jit.get("donate"):
                    continue
                for d in target.jit["donate"]:
                    cp = int(d) - target.offset
                    if not 0 <= cp < len(site["pos"]):
                        continue
                    arg = site["pos"][cp]
                    root = arg.get("root")
                    if arg.get("simple") and root in pset:
                        seeds.setdefault(
                            (fid[0], fid[1], root),
                            {"desc": f"donated to {target.label()}",
                             "line": site["line"],
                             "snippet": site["snippet"]})
        return seeds

    # ------------------------------------------------------------- mapping

    def map_args(self, site: dict, target: Target
                 ) -> Optional[List[Tuple[dict, str]]]:
        """(arg descriptor, callee parameter name) pairs, or None when
        the mapping cannot be trusted (* / ** at the call site, unknown
        callee) — honest widening, not a guess."""
        fid = target.fid
        if fid is None or site.get("star"):
            return None
        fs = self._func(fid)
        params = fs["params"]
        shift = target.offset + (1 if target.self_call and fs["self_like"]
                                 else 0)
        out: List[Tuple[dict, str]] = []
        for i, arg in enumerate(site["pos"]):
            j = i + shift
            if j < len(params):
                out.append((arg, params[j]))
        for k, arg in site["kw"].items():
            if k in params or k in fs["kwonly"]:
                out.append((arg, k))
        return out

    def _donated_args(self, site: dict, target: Target
                      ) -> List[Tuple[dict, int]]:
        """(arg descriptor, underlying position) pairs this call site
        donates — directly via a jit binding's donate_argnums, or through
        a callee that (transitively) donates the mapped parameter."""
        out: List[Tuple[dict, int]] = []
        if target.kind == "jit" and target.jit \
                and target.jit.get("donate"):
            for d in target.jit["donate"]:
                cp = int(d) - target.offset
                if 0 <= cp < len(site["pos"]):
                    out.append((site["pos"][cp], int(d)))
        elif target.kind == "func":
            mapping = self.map_args(site, target)
            if mapping:
                tfid = target.fid
                for arg, pname in mapping:
                    if (tfid[0], tfid[1], pname) in self._donating:
                        out.append((arg, -1))
        return out

    # ------------------------------------------------------------ messages

    @staticmethod
    def _chain(fact: dict, limit: int = 4) -> str:
        hops: List[str] = []
        cur: Optional[dict] = fact
        while cur is not None and len(hops) < limit:
            if "via_label" in cur:
                hops.append(f"via {cur['via_label']}")
                cur = cur.get("next")
            else:
                hops.append(f"{cur.get('desc', '?')} at line "
                            f"{cur.get('line', '?')}")
                cur = None
        return " ".join(hops)

    @staticmethod
    def _terminal(fact: dict) -> dict:
        cur = fact
        while "next" in cur:
            cur = cur["next"]
        return cur

    def _finding(self, rule: Any, path: str, site: dict,
                 message: str) -> Finding:
        return Finding(rule=rule.code, path=path, line=site["line"],
                       col=site.get("col", 1), message=message,
                       snippet=site.get("snippet", ""))

    # ------------------------------------------------------------ emitters

    def iter_transitive_host_syncs(self, rule: Any) -> Iterator[Finding]:
        """GL002 upgrade: parameter-rooted host syncs in functions that
        any traced context reaches transitively (the function itself is
        not lexically traced — those sites are the local rule's)."""
        self._build()
        emitted: Set[Tuple[str, str, int, int]] = set()
        for path, mod in self.by_path.items():
            for qual, fs in mod.funcs.items():
                fid = (path, qual)
                if fs["directly_traced"] or not fs["syncs"]:
                    continue
                if fid not in self._traced:
                    continue
                # which params provably receive a traced (non-constant)
                # value from a traced caller
                hot: Dict[str, str] = {}
                for caller, site, target in self.edges_in.get(fid, ()):
                    if caller not in self._traced:
                        continue
                    mapping = self.map_args(site, target)
                    if not mapping:
                        continue
                    cmod = self.by_path[caller[0]]
                    for arg, pname in mapping:
                        if arg.get("const"):
                            continue
                        hot.setdefault(
                            pname,
                            f"{cmod.relname}:{caller[1]} "
                            f"(line {site['line']})")
                for s in fs["syncs"]:
                    who = hot.get(s["param"])
                    if who is None:
                        continue
                    key = (path, qual, s["line"], s["col"])
                    if key in emitted:
                        continue
                    emitted.add(key)
                    yield self._finding(
                        rule, path, s,
                        f"{s['desc']} on parameter '{s['param']}' of "
                        f"'{qual}' — this helper is reached from traced "
                        f"code (called by {who}), so the sync happens "
                        "inside jit tracing; hoist the conversion out "
                        "or keep it a device value")

    def iter_loop_blocking_calls(self, rule: Any) -> Iterator[Finding]:
        """GL007 upgrade: a call inside an untraced loop hands a jitted
        step's output to a helper that (transitively) blocks on it."""
        self._build()
        for path, mod in self.by_path.items():
            for qual, fs in mod.funcs.items():
                fid = (path, qual)
                for site, target in zip(fs["calls"], self.targets[fid]):
                    if not site["in_loop"] or target.kind != "func":
                        continue
                    mapping = self.map_args(site, target)
                    if not mapping:
                        continue
                    tfid = target.fid
                    for arg, pname in mapping:
                        if not arg.get("step"):
                            continue
                        fact = self._blocking.get(
                            (tfid[0], tfid[1], pname))
                        if fact is None:
                            continue
                        term = self._terminal(fact)
                        yield self._finding(
                            rule, path, site,
                            f"'{target.label()}' blocks on its "
                            f"'{pname}' argument "
                            f"({term.get('desc', '?')} at line "
                            f"{term.get('line', '?')}) — calling it on "
                            "a step output inside the loop is a per-"
                            "step host sync that defeats async "
                            "dispatch; pass a device value through or "
                            "fetch once outside the loop")
                        break  # one finding per call site

    def iter_cross_module_donations(self, rule: Any) -> Iterator[Finding]:
        """GL003 upgrade: replay each function's statement events; a
        read after a call that donated the value — through an imported
        jitted binding or a helper that transitively donates — is
        use-after-free even when donor and reader share no module.
        ``if`` arms replay on their own state copy; terminated arms are
        dropped, surviving arms merge by union (a buffer dead on ANY
        surviving path is a hazard on that path)."""
        self._build()
        for path, mod in self.by_path.items():
            local = set(mod.local_donations)
            for qual, fs in mod.funcs.items():
                fid = (path, qual)
                yield from self._replay_donations(
                    rule, path, fs, self.targets[fid], local,
                    fs["events"], {})

    def _replay_donations(self, rule: Any, path: str, fs: dict,
                          resolved: List[Target], local: Set[str],
                          events: List[dict],
                          armed: Dict[str, str]) -> Iterator[Finding]:
        for ev in events:
            if "branches" in ev:
                survivors: List[Dict[str, str]] = []
                for br in ev["branches"]:
                    st = dict(armed)
                    yield from self._replay_donations(
                        rule, path, fs, resolved, local,
                        br["events"], st)
                    if not br["terminates"]:
                        survivors.append(st)
                if survivors:
                    armed.clear()
                    for st in survivors:
                        armed.update(st)
                continue
            for r in ev["reads"]:
                for d in sorted(armed):
                    if r["text"] == d or r["text"].startswith(d + "."):
                        yield self._finding(
                            rule, path, r,
                            f"'{d}' was {armed[d]} — its buffer "
                            "is dead after the donating call; "
                            "reading it is use-after-free (copy "
                            "first or use the call's result)")
                        armed.pop(d)
                        break
            for idx in ev["calls"]:
                site = fs["calls"][idx]
                target = resolved[idx]
                if site["callee"] in local:
                    continue  # the local rule owns this donor
                for arg, _pos in self._donated_args(site, target):
                    name = arg.get("name")
                    if name and name not in ev["binds"]:
                        armed[name] = (f"donated to "
                                       f"'{target.label()}' at "
                                       f"line {site['line']}")
            for b in ev["binds"]:
                for d in list(armed):
                    if d == b or d.startswith(b + "."):
                        armed.pop(d)

    def iter_distant_static_hazards(self, rule: Any) -> Iterator[Finding]:
        """GL005 upgrade: shape-derived scalars / f-strings flowing into
        a jitted binding that lives in ANOTHER module (or behind a
        partial chain), unless the argument position/name is declared
        static at the distant jax.jit site."""
        self._build()
        for path, mod in self.by_path.items():
            local = set(mod.local_jitted)
            for qual, fs in mod.funcs.items():
                fid = (path, qual)
                for site, target in zip(fs["calls"], self.targets[fid]):
                    if target.kind != "jit" or site["callee"] in local:
                        continue
                    info = target.jit or {}
                    argnums = {int(x) for x in
                               info.get("static_argnums", ())}
                    argnames = set(info.get("static_argnames", ()))
                    inner_params: List[str] = []
                    if target.fid is not None:
                        inner_params = self._func(target.fid)["params"]
                    for i, arg in enumerate(site["pos"]):
                        if not arg.get("hazard"):
                            continue
                        up = i + target.offset
                        pname = (inner_params[up]
                                 if up < len(inner_params) else None)
                        if up in argnums or (pname and pname in argnames):
                            continue
                        yield self._hazard_finding(rule, path, site, arg,
                                                   target)
                    for k, arg in site["kw"].items():
                        if not arg.get("hazard"):
                            continue
                        static = k in argnames or (
                            k in inner_params
                            and inner_params.index(k) in argnums)
                        if static:
                            continue
                        yield self._hazard_finding(rule, path, site, arg,
                                                   target)

    def _hazard_finding(self, rule: Any, path: str, site: dict,
                        arg: dict, target: Target) -> Finding:
        where = {"line": arg.get("hline", site["line"]),
                 "col": arg.get("hcol", site.get("col", 1)),
                 "snippet": arg.get("hsnippet", site.get("snippet", ""))}
        return self._finding(
            rule, path, where,
            f"{arg['hazard']} flows into '{site['callee']}' — a jitted "
            f"binding declared at {target.label() if target.fid else 'a distant site'} "
            "whose static_argnums/static_argnames do not cover this "
            "argument; every new value retraces and recompiles (mark it "
            "static at the jax.jit site or derive it inside the jit)")

    def iter_cross_module_key_reuse(self, rule: Any) -> Iterator[Finding]:
        """GL011: replay each function's events tracking its key-named
        parameters; a key consumed twice — where at least one consumer
        is a (transitively proven) key-consuming callee — or consumed
        after a split, or consumed every loop iteration by a proven
        consumer without rebinding, is correlated randomness the local
        GL001 could not see."""
        self._build()
        for path, mod in self.by_path.items():
            for qual, fs in mod.funcs.items():
                fid = (path, qual)
                keys = [p for p in fs["params"] + fs["kwonly"]
                        if is_key_param(p)]
                if not keys:
                    continue
                state: Dict[str, dict] = {
                    k: {"uses": [], "split": False} for k in keys}
                yield from self._replay_keys(
                    rule, path, fs, self.targets[fid], fs["events"],
                    state)

    def _replay_keys(self, rule: Any, path: str, fs: dict,
                     resolved: List[Target], events: List[dict],
                     state: Dict[str, dict]) -> Iterator[Finding]:

        def consume(name: str, kind: str, label: str,
                    site: dict) -> Optional[Finding]:
            st = state.get(name)
            if st is None:
                return None
            finding = None
            if st["split"] and kind == "callee":
                # a DIRECT use-after-split is GL001's finding already;
                # this rule only owns the half that crosses a call
                finding = self._finding(
                    rule, path, site,
                    f"key '{name}' consumed by {label} after "
                    "jax.random.split — use one of the split "
                    "results instead")
            elif st["uses"] and (kind == "callee" or any(
                    k2 == "callee" for k2, _l in st["uses"])):
                first = st["uses"][0][1]
                finding = self._finding(
                    rule, path, site,
                    f"key '{name}' consumed more than once: "
                    f"first by {first}, again by {label} — the "
                    "two consumers draw correlated randomness; "
                    "derive per-consumer keys with "
                    "jax.random.split/fold_in")
            st["uses"].append((kind, label))
            if finding is not None:
                state[name] = {"uses": [], "split": False}
            return finding

        for ev in events:
            if "branches" in ev:
                survivors: List[Dict[str, dict]] = []
                for br in ev["branches"]:
                    st2 = {k: {"uses": list(v["uses"]),
                               "split": v["split"]}
                           for k, v in state.items()}
                    yield from self._replay_keys(
                        rule, path, fs, resolved, br["events"], st2)
                    if not br["terminates"]:
                        survivors.append(st2)
                if survivors:
                    # GL001 merge semantics: a key survives only if every
                    # surviving arm still tracks it; uses = the heaviest
                    # arm's, split = any arm's
                    for name in list(state):
                        alive = [s[name] for s in survivors
                                 if name in s]
                        if len(alive) < len(survivors):
                            state.pop(name)
                            continue
                        best = max(alive, key=lambda s: len(s["uses"]))
                        state[name] = {
                            "uses": list(best["uses"]),
                            "split": any(s["split"] for s in alive)}
                continue
            for n in ev["fresh"]:
                if n in state:
                    state[n] = {"uses": [], "split": False}
            for u in ev["kuses"]:
                f = consume(u["name"], "direct",
                            f"{u.get('desc', 'jax.random')} "
                            f"(line {u['line']})", u)
                if f is not None:
                    yield f
            for u in ev["ksplits"]:
                st = state.get(u["name"])
                if st is None:
                    continue
                if any(k2 == "callee" for k2, _l in st["uses"]):
                    yield self._finding(
                        rule, path, u,
                        f"key '{u['name']}' split after already "
                        f"being consumed by "
                        f"{st['uses'][0][1]} — the split "
                        "results correlate with the earlier "
                        "draw")
                    state[u["name"]] = {"uses": [], "split": False}
                    continue
                st["split"] = True
            for idx in ev["calls"]:
                site = fs["calls"][idx]
                target = resolved[idx]
                if target.kind not in ("func", "jit"):
                    continue
                mapping = self.map_args(site, target)
                if not mapping:
                    continue
                tfid = target.fid
                for arg, pname in mapping:
                    name = arg.get("root")
                    if not arg.get("simple") or name not in state:
                        continue
                    fact = self._keys.get((tfid[0], tfid[1], pname))
                    if fact is None:
                        continue
                    term = self._terminal(fact)
                    label = (f"'{target.label()}' "
                             f"({term.get('desc', 'jax.random')}"
                             f" at line {term.get('line', '?')})")
                    if site["in_loop"] \
                            and name not in site["loop_rebound"]:
                        yield self._finding(
                            rule, path, site,
                            f"key '{name}' from outside the "
                            f"loop is consumed by {label} every "
                            "iteration without rebinding — same "
                            "randomness each pass; fold_in the "
                            "loop index")
                        state[name] = {"uses": [], "split": False}
                        continue
                    f = consume(name, "callee", label, site)
                    if f is not None:
                        yield f
            for b in ev["binds"]:
                # rebound to a non-key: stop tracking (fresh-key
                # rebinds were reset above instead)
                if b in state and b not in ev["fresh"]:
                    state.pop(b)
