"""Interprocedural graftlint: the whole-program call-graph pass.

The per-module rules (GL001..GL010) see one AST at a time, which left
three audited blind spots (ROADMAP item 6): tracedness did not propagate
through ordinary calls, GL003 donation tracking stopped at module scope
(the r6 orbax-restore corruption crossed exactly such a boundary), and
GL005 could not see ``static_argnums`` declared far from the call site.
This module turns those heuristics into proofs:

* :func:`summarize_module` distills one parsed :class:`~core.Module`
  into a **serializable** :class:`ModuleSummary` — per-function facts
  (host-sync sites rooted at parameters, PRNG-key consumption, call
  sites with signature-shaped argument descriptors, statement-ordered
  read/bind events) plus the module's symbol table (functions, classes,
  jit/partial bindings, absolutized import aliases). Serializable is
  load-bearing: the content-hash cache (:mod:`cache`) stores summaries
  keyed on file sha, so unchanged modules are never reparsed while the
  cross-module pass stays exact.
* :class:`CallGraph` links the summaries: imports resolve
  module-to-module (through re-export chains, ``functools.partial``
  bindings, and ``self.`` method calls), call-site arguments map to
  callee parameters signature-aware (positional/keyword; ``*args`` and
  ``**kwargs`` at a call site **widen honestly** — the mapping is
  dropped rather than guessed), and monotone fixpoints flow four fact
  families across call and module boundaries until stable (cycles and
  recursion converge; an unknown callee contributes nothing, so a fact
  is only ever *proven*, never assumed):

  - **tracedness**: a function reachable from any jit/scan-traced
    context is traced — its parameter-rooted host syncs are GL002
    findings even when the helper lives two modules away;
  - **blocking params**: a parameter a function (transitively)
    ``float()``s / ``.item()``s — a loop passing a jitted step's output
    into such a helper is a GL007 finding at the call site;
  - **key consumption**: a parameter a function (transitively) feeds to
    a ``jax.random`` sampler — the GL011 replay proves cross-module key
    reuse instead of guessing from parameter names;
  - **donation**: a parameter a function (transitively) passes at a
    donated position of a jitted binding — reading a tree after the
    donating call is GL003 even when donor and reader never share a
    module (the r6 shape).

Emission is owned here (the rules' ``check_graph`` methods delegate) so
the propagation machinery and the messages that cite witness chains
stay in one place.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

from .core import (
    TRACE_WRAPPERS,
    TRACED_ARG_POS,
    TRACED_ARG_SUFFIXES,
    Finding,
    Module,
)
from . import dataflow
from .dataflow import (  # re-exported for rules.py (one table owner)
    BLOCKING_BUILTINS,
    KEY_DERIVERS,
    KEY_PARAM_PAT,
    NP_BLOCKERS,
    STEP_ATTRS,
    SYNC_NP,
    field_path,
    is_key_param,
    is_key_path,
    path_prefix_of,
    path_root,
    path_suffix,
    paths_conflict,
)

__all__ = ["CallGraph", "ModuleSummary", "SUMMARY_SCHEMA",
           "module_name_for_path", "summarize_module"]

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_LOOPS = (ast.For, ast.AsyncFor, ast.While)
_MAX_CHAIN = 32  # resolution chain cap (alias/partial/re-export hops)


# =========================================================== module naming

def module_name_for_path(path: str) -> Tuple[str, bool]:
    """(dotted module name, is_package) for a file path, by walking up
    while ``__init__.py`` markers continue — mirrors how the interpreter
    would import the file from the package root. A bare file in a
    non-package dir is a top-level module named by its stem."""
    p = os.path.abspath(path)
    d, base = os.path.split(p)
    stem = base[:-3] if base.endswith(".py") else base
    is_pkg = stem == "__init__"
    parts: List[str] = [] if is_pkg else [stem]
    while os.path.isfile(os.path.join(d, "__init__.py")):
        nd, name = os.path.split(d)
        if not name or nd == d:
            break
        parts.append(name)
        d = nd
    if not parts:  # degenerate: an __init__.py outside any package
        parts = [os.path.basename(os.path.dirname(p)) or "module"]
    return ".".join(reversed(parts)), is_pkg


def _absolutize(origin: str, modname: str, is_pkg: bool) -> str:
    """Resolve a relative import origin (``.x``, ``..utils.y``) against
    the importing module's dotted name; absolute origins pass through.
    Unresolvable relatives (more dots than package depth) are returned
    unchanged — they simply never match a module."""
    if not origin.startswith("."):
        return origin
    level = len(origin) - len(origin.lstrip("."))
    rest = [s for s in origin[level:].split(".") if s]
    base = modname.split(".")
    drop = level - 1 if is_pkg else level
    if drop < 0 or drop >= len(base) + (1 if is_pkg else 0):
        return origin
    kept = base[:len(base) - drop] if drop else base
    if not kept:
        return origin
    return ".".join(kept + rest)


# ======================================================== module summaries

# Bump whenever the summary shape changes in a way from_dict's defaults
# cannot paper over; a cached entry with any other value deserializes to
# ValueError and the caller re-summarizes cold (cache.py's package salt
# usually invalidates first — the schema is the belt to that suspender,
# covering hand-edited or version-skewed cache files).
SUMMARY_SCHEMA = 2


@dataclasses.dataclass
class ModuleSummary:
    """Everything the cross-module pass needs from one file, as plain
    JSON-shaped data (the cache serializes this verbatim)."""

    path: str
    modname: str
    is_package: bool
    aliases: Dict[str, str]
    funcs: Dict[str, dict]
    classes: Dict[str, List[str]]
    jit_bindings: Dict[str, dict]
    partials: Dict[str, dict]
    local_donations: List[str]
    local_jitted: List[str]
    traced_refs: List[str]
    schema: int = SUMMARY_SCHEMA

    @property
    def relname(self) -> str:
        return "/".join(self.path.replace(os.sep, "/").split("/")[-2:])

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ModuleSummary":
        """Total over old-schema/garbled input in the sense that it
        raises ValueError (never KeyError/TypeError surprises) — the
        cache path treats that as a miss and re-summarizes cold."""
        if not isinstance(d, dict):
            raise ValueError(f"summary: expected dict, got {type(d)!r}")
        if d.get("schema") != SUMMARY_SCHEMA:
            raise ValueError(f"summary schema {d.get('schema')!r} != "
                             f"{SUMMARY_SCHEMA}")
        kwargs: Dict[str, Any] = {}
        for f in dataclasses.fields(cls):
            if f.name in d:
                kwargs[f.name] = d[f.name]
            elif f.default is not dataclasses.MISSING:
                kwargs[f.name] = f.default
            else:
                raise ValueError(f"summary missing field {f.name!r}")
        try:
            return cls(**kwargs)
        except TypeError as e:  # pragma: no cover - defensive
            raise ValueError(str(e))


def _site(node: ast.AST, module: Module) -> dict:
    line = getattr(node, "lineno", 1)
    return {"line": line, "col": getattr(node, "col_offset", 0) + 1,
            "snippet": module.snippet(line)}


def _root_of(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _shallow(node: ast.AST) -> Iterator[ast.AST]:
    """This statement's own expression nodes: no nested statements (the
    flatten walk visits those separately), no nested function bodies."""
    stack: List[ast.AST] = [node]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(c, ast.stmt) or isinstance(c, _FUNC_DEFS) \
                    or isinstance(c, ast.Lambda):
                continue
            stack.append(c)


def _scalar_hazard(arg: ast.AST) -> Optional[str]:
    """The GL005 hazard shapes (one owner shared with the local rule):
    a ``len()`` scalar, a ``.shape``-derived value, or an f-string."""
    if isinstance(arg, ast.JoinedStr):
        return "an f-string (fresh object per call)"
    for n in ast.walk(arg):
        if isinstance(n, _FUNC_DEFS) or isinstance(n, ast.Lambda):
            return None
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "len":
            return "a len() python scalar"
        if isinstance(n, ast.Attribute) and n.attr == "shape":
            return "a .shape-derived python value"
    return None


# (the semantic fact tables — SYNC_NP, KEY_PARAM_PAT, etc. — and the
# host-sync shape detector live in dataflow.py now, re-exported above so
# rules.py keeps one table owner; sync detection itself runs inside the
# value-flow walk, over *derived* operands rather than parameter roots)


def _loop_bound_names(loop: ast.AST) -> Set[str]:
    """Names (re)bound anywhere inside the loop body (not nested defs)."""
    out: Set[str] = set()
    stack: List[ast.AST] = list(ast.iter_child_nodes(loop))
    while stack:
        n = stack.pop()
        if isinstance(n, _FUNC_DEFS) or isinstance(n, ast.Lambda) \
                or isinstance(n, ast.ClassDef):
            continue
        targets: List[ast.AST] = []
        if isinstance(n, ast.Assign):
            targets = list(n.targets)
        elif isinstance(n, (ast.AnnAssign, ast.AugAssign)):
            targets = [n.target]
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            targets = [n.target]
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                if isinstance(e, ast.Name):
                    out.add(e.id)
                elif isinstance(e, (ast.Attribute, ast.Subscript)):
                    p = field_path(e)
                    if p is not None:
                        out.add(p)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _loop_step_names(module: Module, loop: ast.AST) -> Set[str]:
    """Names assigned in the loop from a jitted-step-shaped call — the
    values GL007 cares about (same heuristics as the local rule)."""
    names: Set[str] = set()
    for nd in ast.walk(loop):
        if not isinstance(nd, ast.Assign) \
                or not isinstance(nd.value, ast.Call):
            continue
        func = nd.value.func
        hit = isinstance(func, ast.Attribute) and func.attr in STEP_ATTRS
        if not hit:
            try:
                hit = ast.unparse(func) in module.jitted_bindings
            except Exception:  # pragma: no cover - defensive
                hit = False
        if not hit:
            continue
        for t in nd.targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            names |= {e.id for e in elts if isinstance(e, ast.Name)}
    return names


def _stmt_binds(s: ast.stmt) -> List[str]:
    targets: List[Optional[ast.AST]] = []
    if isinstance(s, ast.Assign):
        targets = list(s.targets)
    elif isinstance(s, (ast.AnnAssign, ast.AugAssign)):
        targets = [s.target]
    elif isinstance(s, (ast.For, ast.AsyncFor)):
        targets = [s.target]
    elif isinstance(s, (ast.With, ast.AsyncWith)):
        targets = [i.optional_vars for i in s.items if i.optional_vars]
    out: List[str] = []
    for t in targets:
        if t is None:
            continue
        elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
        for e in elts:
            if isinstance(e, ast.Name):
                out.append(e.id)
            elif isinstance(e, (ast.Attribute, ast.Subscript)):
                # canonical path first (field-sensitive kills need the
                # same spelling the arg descriptors use); a store with
                # no stable path still kills by its base container
                p = field_path(e)
                if p is None and isinstance(e, ast.Subscript):
                    p = field_path(e.value)
                if p is not None:
                    out.append(p)
                else:
                    try:
                        out.append(ast.unparse(e))
                    except Exception:  # pragma: no cover - defensive
                        pass
    return out


def _iter_funcs(tree: ast.AST) -> Iterator[Tuple[str, Optional[str],
                                                 ast.AST]]:
    """(qualname, enclosing class or None, def node) for every named
    function, including nested defs (``outer.inner``) and methods
    (``Class.method``)."""

    def visit(node: ast.AST, prefix: str,
              cls: Optional[str]) -> Iterator[Tuple[str, Optional[str],
                                                    ast.AST]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_DEFS):
                q = prefix + child.name
                yield q, cls, child
                yield from visit(child, q + ".", cls)
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, prefix + child.name + ".",
                                 child.name)

    yield from visit(tree, "", None)


def _terminates(stmts: List[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


def _has_break(loop: ast.AST) -> bool:
    """A ``break`` belonging to THIS loop (not a nested one) — decides
    whether the loop-``else`` suite may be skipped."""
    stack: List[ast.AST] = list(loop.body)
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Break):
            return True
        if isinstance(n, _LOOPS) or isinstance(n, _FUNC_DEFS) \
                or isinstance(n, (ast.ClassDef, ast.Lambda)):
            continue  # a break inside these binds to them, not to us
        stack.extend(c for c in ast.iter_child_nodes(n)
                     if isinstance(c, (ast.stmt, ast.excepthandler)))
    return False


def _summarize_function(module: Module, qual: str, cls: Optional[str],
                        node: ast.AST,
                        flow: "dataflow.FunctionFlow") -> dict:
    a = node.args
    params = [p.arg for p in a.posonlyargs + a.args]
    kwonly = [p.arg for p in a.kwonlyargs]
    pset = set(params) | set(kwonly)
    self_like = (bool(cls) and bool(params)
                 and params[0] in ("self", "cls")
                 and not any(module.resolve(d) == "staticmethod"
                             for d in node.decorator_list))

    # which loops are untraced (GL007 jurisdiction) + their step names
    loop_cache: Dict[int, Tuple[Set[str], Set[str]]] = {}

    def loop_facts(loop: ast.AST) -> Tuple[Set[str], Set[str]]:
        key = id(loop)
        if key not in loop_cache:
            steps = (set() if module.in_traced(loop)
                     else _loop_step_names(module, loop))
            loop_cache[key] = (steps, _loop_bound_names(loop))
        return loop_cache[key]

    calls: List[dict] = []
    # host-sync sites from the value-flow walk: operands *derived* from
    # parameters (gap 1), not merely rooted at them
    syncs: List[dict] = [dict(s) for s in flow.syncs]
    candidates: Set[str] = set()
    pending: List[Tuple[ast.stmt, dict]] = []

    def make_ev(s: ast.stmt, loop: Optional[ast.AST]) -> dict:
        ev: dict = {"calls": [], "binds": [], "fresh": [],
                    "reads": [], "kuses": [], "ksplits": []}
        step_names, loop_bound = loop_facts(loop) if loop is not None \
            else (set(), set())
        stmt_calls = [n for n in _shallow(s) if isinstance(n, ast.Call)]
        stmt_calls.sort(key=lambda c: (getattr(c, "lineno", 0),
                                       getattr(c, "col_offset", 0)))
        for call in stmt_calls:
            fn = module.resolve(call.func)
            # direct PRNG use/split events (GL001 semantics, recorded so
            # the GL011 replay can mix direct and cross-module consumers)
            if fn and fn.startswith("jax.random."):
                member = fn.rsplit(".", 1)[1]
                # jax.random.* consume the KEY argument only — the
                # first positional (or key=); counting shape/count args
                # would poison the key-consumption fixpoint. The key may
                # be a container field (state['rng'], self.key): any
                # canonical path works, not just a bare name.
                key_nodes = list(call.args[:1])
                key_nodes += [k.value for k in call.keywords
                              if k.arg == "key"]
                for arg in key_nodes:
                    kp = field_path(arg)
                    if kp is None:
                        continue
                    if member == "split":
                        ev["ksplits"].append(
                            {"name": kp, **_site(call, module)})
                    elif member not in KEY_DERIVERS:
                        ev["kuses"].append(
                            {"name": kp, "desc": fn,
                             **_site(call, module)})
            try:
                callee = ast.unparse(call.func)
            except Exception:  # pragma: no cover - defensive
                continue
            pt = flow.candidates.get(id(call))
            if not isinstance(call.func, (ast.Name, ast.Attribute)) \
                    and not pt:
                continue  # calls of call results etc.: unresolvable

            def desc(arg: ast.AST) -> dict:
                d: dict = {}
                path = field_path(arg)
                if path is not None:
                    d["name"] = path
                    d["suffix"] = dataflow.path_suffix(path)
                elif isinstance(arg, (ast.Name, ast.Attribute)):
                    try:  # pragma: no cover - field_path covers these
                        d["name"] = ast.unparse(arg)
                    except Exception:  # pragma: no cover - defensive
                        pass
                if isinstance(arg, ast.Name):
                    d["simple"] = True
                if isinstance(arg, ast.Constant):
                    d["const"] = True
                root = _root_of(arg)
                if root:
                    d["root"] = root
                    if root in step_names:
                        d["step"] = True
                hz = _scalar_hazard(arg)
                if hz:
                    d["hazard"] = hz
                    d.update({f"h{k}": v
                              for k, v in _site(arg, module).items()})
                return d

            site = {
                "callee": callee,
                **_site(call, module),
                "pos": [desc(arg) for arg in call.args
                        if not isinstance(arg, ast.Starred)],
                "kw": {k.arg: desc(k.value) for k in call.keywords
                       if k.arg},
                "star": (any(isinstance(arg, ast.Starred)
                             for arg in call.args)
                         or any(k.arg is None for k in call.keywords)),
                "in_loop": loop is not None,
                "loop_rebound": sorted(loop_bound) if loop is not None
                else [],
            }
            if pt:
                # bounded points-to candidates for a callee the static
                # symbol table cannot resolve (callable in a container/
                # dataclass field); the graph pass treats a fact as
                # proven only when every candidate carries it
                site["pt"] = list(pt)
            for d in site["pos"] + list(site["kw"].values()):
                if d.get("root"):
                    candidates.add(d["root"])
            ev["calls"].append(len(calls))
            calls.append(site)
        binds = _stmt_binds(s)
        ev["binds"] = binds
        if isinstance(s, ast.Assign) and isinstance(s.value, ast.Call):
            vfn = module.resolve(s.value.func)
            if vfn and vfn.startswith("jax.random.") \
                    and vfn.rsplit(".", 1)[1] in (KEY_DERIVERS | {"split"}):
                ev["fresh"] = list(binds)
        pending.append((s, ev))
        return ev

    def build(body: List[ast.stmt], loop: Optional[ast.AST]
              ) -> List[dict]:
        """Statement-event tree in source order. ``if`` arms, ``try``
        body-vs-handlers, and may-skip loop-``else`` suites become
        nested {"branches": [{"events", "terminates"}, ...]} entries so
        the replays can give each arm its own state copy and drop
        terminated arms — a consumption inside an early-``return`` body
        must not leak into the fall-through path, and a retry pattern
        (consume in ``try``, consume again in ``except``) must not
        count as a double consumption (the GL001 semantics, kept at the
        summary level)."""
        out: List[dict] = []
        for s in body:
            if isinstance(s, _FUNC_DEFS) or isinstance(s, ast.ClassDef):
                continue
            out.append(make_ev(s, loop))
            if isinstance(s, ast.If):
                branches = []
                for sub in (s.body, s.orelse):
                    if not sub:
                        continue
                    branches.append({"events": build(sub, loop),
                                     "terminates": _terminates(sub)})
                if any(br["events"] or br["terminates"]
                       for br in branches):
                    out.append({"branches": branches})
            elif isinstance(s, _LOOPS):
                out.extend(build(s.body, s))
                if s.orelse:
                    if _has_break(s):
                        # a break skips the else suite: one arm runs it,
                        # one falls through — replay both
                        out.append({"branches": [
                            {"events": build(s.orelse, loop),
                             "terminates": _terminates(s.orelse)},
                            {"events": [], "terminates": False},
                        ]})
                    else:
                        # no break: the else suite always runs — inline
                        out.extend(build(s.orelse, loop))
            elif isinstance(s, ast.Try) \
                    or s.__class__.__name__ == "TryStar":
                # body+else is one arm, each handler another, all
                # replayed from the pre-try state; finally is inline
                # (it always runs, after whichever arm)
                arms = [{"events": (build(s.body, loop)
                                    + build(s.orelse, loop)),
                         "terminates": _terminates(s.orelse or s.body)}]
                for h in s.handlers:
                    arms.append({"events": build(h.body, loop),
                                 "terminates": _terminates(h.body)})
                if any(br["events"] or br["terminates"] for br in arms):
                    out.append({"branches": arms})
                out.extend(build(s.finalbody, loop))
            else:
                for field in ("body", "orelse", "finalbody"):
                    out.extend(build(getattr(s, field, []) or [], loop))
        return out

    events = build(node.body, None)

    # second pass: reads of candidate roots (donation liveness needs the
    # loads BETWEEN call sites, in order)
    for s, ev in pending:
        if not candidates:
            break
        for n in _shallow(s):
            if not isinstance(n, (ast.Name, ast.Attribute, ast.Subscript)):
                continue
            if not isinstance(getattr(n, "ctx", None), ast.Load):
                continue
            parent = module.parent.get(n)
            if isinstance(parent, (ast.Attribute, ast.Subscript)) \
                    and getattr(parent, "value", None) is n:
                continue  # outermost chain node only
            if isinstance(parent, ast.Call) and parent.func is n:
                continue  # the callee position is not a data read
            root = _root_of(n)
            if root not in candidates:
                continue
            text = field_path(n)
            if text is None and isinstance(n, ast.Subscript):
                # dynamic index: any element may be the dead one, so
                # the read touches the whole container
                text = field_path(n.value)
            if text is None:
                try:
                    text = ast.unparse(n)
                except Exception:  # pragma: no cover - defensive
                    continue
            ev["reads"].append({"text": text, **_site(n, module)})

    def prune(evs: List[dict]) -> List[dict]:
        out = []
        for ev in evs:
            if "branches" in ev:
                for br in ev["branches"]:
                    br["events"] = prune(br["events"])
                if any(br["events"] or br["terminates"]
                       for br in ev["branches"]):
                    out.append(ev)
            elif any(ev[k] for k in ("calls", "binds", "fresh", "reads",
                                     "kuses", "ksplits")):
                out.append(ev)
        return out

    events = prune(events)

    return {
        "qual": qual,
        "cls": cls,
        "line": getattr(node, "lineno", 1),
        "params": params,
        "kwonly": kwonly,
        "vararg": a.vararg is not None,
        "kwarg": a.kwarg is not None,
        "self_like": self_like,
        # in_traced, not bare membership: a def nested INSIDE a traced
        # function is lexically traced too — its sync sites belong to
        # the local GL002 rule, and the graph half must not double-
        # report them (it still seeds the traced closure correctly)
        "directly_traced": (node in module.traced
                            or module.in_traced(node)),
        "calls": calls,
        "syncs": syncs,
        "events": events,
    }


def summarize_module(module: Module) -> ModuleSummary:
    modname, is_pkg = module_name_for_path(module.path)
    aliases = {k: _absolutize(v, modname, is_pkg)
               for k, v in module.imports.alias.items()}
    funcs: Dict[str, dict] = {}
    classes: Dict[str, List[str]] = {}
    # module-level + per-class points-to maps feed every function's
    # value-flow walk (callables in module dicts / dataclass fields)
    mod_penv, class_pt, class_names = dataflow.module_maps(module)
    for qual, cls, node in _iter_funcs(module.tree):
        flow = dataflow.analyze_function(module, node, cls, class_pt,
                                         mod_penv, class_names)
        funcs[qual] = _summarize_function(module, qual, cls, node, flow)
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef):
            classes[node.name] = [b.name for b in node.body
                                  if isinstance(b, _FUNC_DEFS)]
    partials: Dict[str, dict] = {}
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        fn = module.resolve(node.value.func)
        if fn not in ("functools.partial", "partial"):
            continue
        if not node.value.args:
            continue
        tgt = node.value.args[0]
        if not isinstance(tgt, (ast.Name, ast.Attribute)):
            continue
        partials[node.targets[0].id] = {
            "target": ast.unparse(tgt),
            "n_pos": len(node.value.args) - 1,
            "kw": [k.arg for k in node.value.keywords if k.arg],
        }
    traced_refs: List[str] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = module.resolve(node.func)
        positions: Tuple[int, ...] = ()
        if module._wrapper_name(node.func) is not None and node.args:
            positions = (0,)
        elif fn in TRACED_ARG_POS:
            positions = TRACED_ARG_POS[fn]
        elif fn is not None:
            for suffix, pos in TRACED_ARG_SUFFIXES.items():
                if fn.split(".")[-1] == suffix:
                    positions = pos
        for p in positions:
            if p < len(node.args) and isinstance(
                    node.args[p], (ast.Name, ast.Attribute)):
                traced_refs.append(ast.unparse(node.args[p]))
    return ModuleSummary(
        path=module.path,
        modname=modname,
        is_package=is_pkg,
        aliases=aliases,
        funcs=funcs,
        classes=classes,
        jit_bindings={k: dict(v) for k, v in module.jit_info.items()
                      if "." not in k},  # only plain names are importable
        partials=partials,
        local_donations=sorted(module.donations),
        local_jitted=sorted(module.jitted_bindings),
        traced_refs=traced_refs,
    )


# ============================================================== call graph

@dataclasses.dataclass
class Target:
    """Resolution of a call-site callee: a function summary, a jitted
    binding (with its donate/static facts and, when resolvable, the
    wrapped function), or unknown (honest widening: contributes no
    facts). ``offset`` is the positional shift accumulated through
    ``functools.partial`` chains."""

    kind: str                         # "func" | "jit" | "unknown"
    module: Optional[ModuleSummary] = None
    qual: Optional[str] = None
    offset: int = 0
    self_call: bool = False
    jit: Optional[dict] = None

    @property
    def fid(self) -> Optional[Tuple[str, str]]:
        if self.module is not None and self.qual is not None:
            return (self.module.path, self.qual)
        return None

    def label(self) -> str:
        if self.module is not None and self.qual is not None:
            return f"{self.module.relname}:{self.qual}"
        return "<unknown>"


_UNKNOWN = Target("unknown")


class CallGraph:
    """Whole-program view over every module summary: symbol resolution,
    the call-edge table, and the four fixpoint fact families. All
    construction is lazy (``_build``) and pure over summaries, so a
    cache-served run never needs an AST."""

    def __init__(self, summaries: Dict[str, ModuleSummary]) -> None:
        self.by_path: Dict[str, ModuleSummary] = dict(summaries)
        self.by_name: Dict[str, ModuleSummary] = {}
        for s in self.by_path.values():
            self.by_name.setdefault(s.modname, s)
        self._built = False

    # ---------------------------------------------------------- resolution

    def _find_module(self, dotted: str
                     ) -> Optional[Tuple[ModuleSummary, str]]:
        parts = dotted.split(".")
        for i in range(len(parts), 0, -1):
            m = self.by_name.get(".".join(parts[:i]))
            if m is not None:
                return m, ".".join(parts[i:])
        return None

    def resolve(self, mod: ModuleSummary, text: str,
                scope_qual: Optional[str] = None,
                cls: Optional[str] = None) -> Target:
        return self._resolve(mod, text, scope_qual, cls, set())

    def _resolve(self, mod: ModuleSummary, text: str,
                 scope_qual: Optional[str],
                 cls: Optional[str],
                 seen: Set[Tuple[str, str]]) -> Target:
        offset = 0
        for _ in range(_MAX_CHAIN):
            key = (mod.path, text)
            if key in seen:
                return _UNKNOWN  # import cycle in the alias chain
            seen.add(key)
            if cls and text.startswith(("self.", "cls.")):
                m = text.split(".", 1)[1]
                if "." not in m and m in mod.classes.get(cls, ()):
                    return Target("func", mod, f"{cls}.{m}", offset,
                                  self_call=True)
                return _UNKNOWN
            if "." not in text:
                if scope_qual:  # nested def visible from the scope chain
                    parts = scope_qual.split(".")
                    for i in range(len(parts), 0, -1):
                        cand = ".".join(parts[:i]) + "." + text
                        if cand in mod.funcs:
                            return Target("func", mod, cand, offset)
                if text in mod.funcs:
                    return Target("func", mod, text, offset)
                if text in mod.jit_bindings:
                    info = mod.jit_bindings[text]
                    inner = _UNKNOWN
                    if info.get("target"):
                        # same `seen` guard: `f = jax.jit(f)` rebinding
                        # chains must terminate, not recurse
                        inner = self._resolve(mod, info["target"],
                                              None, None, seen)
                    return Target("jit", inner.module, inner.qual,
                                  offset, jit=info)
                if text in mod.partials:
                    p = mod.partials[text]
                    offset += int(p["n_pos"])
                    text = p["target"]
                    scope_qual = cls = None
                    continue
                if text in mod.aliases:
                    found = self._find_module(mod.aliases[text])
                    if found is None:
                        return _UNKNOWN
                    mod, rest = found
                    if not rest:
                        return _UNKNOWN  # a module object, not a callable
                    text = rest
                    scope_qual = cls = None
                    continue
                return _UNKNOWN
            root, rest = text.split(".", 1)
            if root in mod.aliases:
                found = self._find_module(mod.aliases[root] + "." + rest)
                if found is None:
                    return _UNKNOWN
                mod, text = found
                if not text:
                    return _UNKNOWN
                scope_qual = cls = None
                continue
            if root in mod.classes and "." not in rest \
                    and rest in mod.classes[root]:
                # Class.method(obj, ...): arg 0 binds self, no shift
                return Target("func", mod, f"{root}.{rest}", offset)
            return _UNKNOWN
        return _UNKNOWN

    # -------------------------------------------------------- construction

    def _build(self) -> None:
        if self._built:
            return
        # resolved call targets, aligned with each function's calls
        # list; ``targets`` holds the UNIQUE resolution (or _UNKNOWN),
        # ``alt_targets`` the full bounded candidate list from the
        # points-to pass. A unique target (static or single-candidate
        # points-to) feeds everything including the traced closure; a
        # multi-candidate set feeds only the must-facts (a fact proven
        # for EVERY candidate), and an unresolvable one feeds nothing.
        self.targets: Dict[Tuple[str, str], List[Target]] = {}
        self.alt_targets: Dict[Tuple[str, str], List[List[Target]]] = {}
        # call edges into each function: fid -> [(caller fid, site, target)]
        self.edges_in: Dict[Tuple[str, str],
                            List[Tuple[Tuple[str, str], dict,
                                       Target]]] = {}
        for path, mod in self.by_path.items():
            for qual, fs in mod.funcs.items():
                fid = (path, qual)
                resolved: List[Target] = []
                alts: List[List[Target]] = []
                for site in fs["calls"]:
                    t = self.resolve(mod, site["callee"],
                                     scope_qual=qual, cls=fs.get("cls"))
                    cands: List[Target] = [t] if t.kind != "unknown" \
                        else []
                    if not cands and site.get("pt"):
                        pt = [self.resolve(mod, c, scope_qual=qual,
                                           cls=fs.get("cls"))
                              for c in site["pt"]]
                        # all-or-nothing: one unresolvable candidate
                        # poisons the set (the callable could be it)
                        if pt and all(c.kind != "unknown"
                                      and c.fid is not None
                                      for c in pt):
                            cands = pt
                    if len(cands) == 1:
                        t = cands[0]
                    resolved.append(t if len(cands) == 1 else _UNKNOWN)
                    alts.append(cands)
                    tfid = t.fid
                    if len(cands) == 1 and tfid is not None:
                        self.edges_in.setdefault(tfid, []).append(
                            (fid, site, t))
                self.targets[fid] = resolved
                self.alt_targets[fid] = alts
        self._traced = self._traced_closure()
        self._blocking = self._param_fixpoint(self._blocking_seeds())
        self._keys = self._param_fixpoint(self._key_seeds())
        self._donating = self._param_fixpoint(self._donation_seeds())
        self._built = True  # only a COMPLETE build counts (an exception
        # mid-build must rebuild, not serve half-initialized fact maps)

    def _func(self, fid: Tuple[str, str]) -> dict:
        return self.by_path[fid[0]].funcs[fid[1]]

    # tracedness: function-level reachability from traced contexts
    def _traced_closure(self) -> Dict[Tuple[str, str],
                                      Optional[Tuple[Tuple[str, str],
                                                     dict]]]:
        """fid -> witness (caller fid, call site) or None for seeds."""
        closure: Dict[Tuple[str, str], Optional[Tuple[Tuple[str, str],
                                                      dict]]] = {}
        queue: List[Tuple[str, str]] = []
        for path, mod in self.by_path.items():
            for qual, fs in mod.funcs.items():
                if fs["directly_traced"]:
                    closure[(path, qual)] = None
                    queue.append((path, qual))
            seeds: List[Target] = []
            for info in mod.jit_bindings.values():
                if info.get("target"):
                    seeds.append(self.resolve(mod, info["target"]))
            for ref in mod.traced_refs:
                seeds.append(self.resolve(mod, ref))
            for t in seeds:
                fid = t.fid
                if t.kind == "func" and fid is not None \
                        and fid not in closure:
                    closure[fid] = None
                    queue.append(fid)
        while queue:
            fid = queue.pop()
            for site, target in zip(self._func(fid)["calls"],
                                    self.targets[fid]):
                nxt = target.fid
                if nxt is None or nxt in closure:
                    continue
                closure[nxt] = (fid, site)
                queue.append(nxt)
        return closure

    # generic backward (callee -> caller) parameter-taint fixpoint
    def _param_fixpoint(self, seeds: Dict[Tuple[str, str, str], dict]
                        ) -> Dict[Tuple[str, str, str], dict]:
        """seeds: (path, qual, param) -> {"desc", "line", "snippet",
        optional "field"} (terminal facts). Propagates through call
        sites whose argument roots at a caller parameter; each
        propagated entry records its next hop so messages can cite the
        chain, and composes field suffixes (a fact on ``state['opt']``
        passed as ``cfg.state`` becomes a fact on ``cfg`` with field
        ``.state['opt']``). Multi-candidate (points-to) sites propagate
        only facts EVERY candidate proves, with an agreeing field.
        Monotone set growth + finite universe => cycles/recursion
        converge."""
        facts = dict(seeds)
        changed = True
        while changed:
            changed = False
            for fid, alts in self.alt_targets.items():
                fs = self._func(fid)
                pset = set(fs["params"]) | set(fs["kwonly"])
                for site, cands in zip(fs["calls"], alts):
                    if not cands:
                        continue
                    maps = []
                    for t in cands:
                        m = self.map_args(site, t)
                        if m is None or t.fid is None:
                            maps = None
                            break
                        maps.append((t, m))
                    if not maps:
                        continue
                    base_t, base_m = maps[0]
                    for arg, _pname0 in base_m:
                        root = arg.get("root")
                        if root not in pset:
                            continue
                        key = (fid[0], fid[1], root)
                        if key in facts:
                            continue
                        down = None
                        fields = set()
                        for t, m in maps:
                            pname = next((p for a, p in m if a is arg),
                                         None)
                            f = facts.get((t.fid[0], t.fid[1], pname)) \
                                if pname is not None else None
                            if f is None:
                                down = None
                                break
                            down = down or f
                            fields.add(f.get("field", ""))
                        if down is None or len(fields) != 1:
                            continue  # unproven on some candidate, or
                            # the candidates disagree on WHICH sub-path
                            # the fact touches: widen to silence
                        facts[key] = {
                            "via": site, "via_label": base_t.label()
                            + (f" (+{len(maps) - 1} candidate(s))"
                               if len(maps) > 1 else ""),
                            "next": down,
                            "field": arg.get("suffix", "")
                            + fields.pop()}
                        changed = True
        return facts

    def _blocking_seeds(self) -> Dict[Tuple[str, str, str], dict]:
        seeds: Dict[Tuple[str, str, str], dict] = {}
        for path, mod in self.by_path.items():
            for qual, fs in mod.funcs.items():
                for s in fs["syncs"]:
                    if s.get("blocking"):
                        # a sync operand may derive from SEVERAL params
                        # (loss = state.loss + aux): each one blocks
                        for p in s.get("params") or [s["param"]]:
                            seeds.setdefault((path, qual, p), s)
        return seeds

    @staticmethod
    def _iter_stmt_events(events: List[dict]) -> Iterator[dict]:
        """Flat view over an event tree (branch structure is only
        meaningful to the ordered replays; seeding is order-free)."""
        stack = list(reversed(events))
        while stack:
            ev = stack.pop()
            if "branches" in ev:
                for br in ev["branches"]:
                    stack.extend(reversed(br["events"]))
                continue
            yield ev

    def _key_seeds(self) -> Dict[Tuple[str, str, str], dict]:
        seeds: Dict[Tuple[str, str, str], dict] = {}
        for path, mod in self.by_path.items():
            for qual, fs in mod.funcs.items():
                pset = set(fs["params"]) | set(fs["kwonly"])
                for ev in self._iter_stmt_events(fs["events"]):
                    for u in ev["kuses"] + ev["ksplits"]:
                        # the consumed key may be a field of a param
                        # (state['rng']): seed the param with the field
                        # suffix so callers track the right sub-path
                        root = path_root(u["name"])
                        if root in pset:
                            seeds.setdefault(
                                (path, qual, root),
                                {"desc": u.get("desc", "jax.random.split"),
                                 "line": u["line"],
                                 "snippet": u["snippet"],
                                 "field": path_suffix(u["name"])})
        return seeds

    def _donation_seeds(self) -> Dict[Tuple[str, str, str], dict]:
        """Parameters passed directly at a donated position of a jit
        binding; the generic fixpoint then carries donation up through
        forwarding callers."""
        seeds: Dict[Tuple[str, str, str], dict] = {}
        for fid, resolved in self.targets.items():
            fs = self._func(fid)
            pset = set(fs["params"]) | set(fs["kwonly"])
            for site, target in zip(fs["calls"], resolved):
                if target.kind != "jit" or not target.jit \
                        or not target.jit.get("donate"):
                    continue
                for d in target.jit["donate"]:
                    cp = int(d) - target.offset
                    if not 0 <= cp < len(site["pos"]):
                        continue
                    arg = site["pos"][cp]
                    root = arg.get("root")
                    # a donated CONTAINER FIELD (state['params']) seeds
                    # the param with that field suffix — callers learn
                    # exactly which sub-tree dies (gap 2)
                    if root in pset and (arg.get("simple")
                                         or arg.get("suffix")):
                        seeds.setdefault(
                            (fid[0], fid[1], root),
                            {"desc": f"donated to {target.label()}",
                             "line": site["line"],
                             "snippet": site["snippet"],
                             "field": arg.get("suffix", "")})
        return seeds

    # ------------------------------------------------------------- mapping

    def map_args(self, site: dict, target: Target
                 ) -> Optional[List[Tuple[dict, str]]]:
        """(arg descriptor, callee parameter name) pairs, or None when
        the mapping cannot be trusted (* / ** at the call site, unknown
        callee) — honest widening, not a guess."""
        fid = target.fid
        if fid is None or site.get("star"):
            return None
        fs = self._func(fid)
        params = fs["params"]
        shift = target.offset + (1 if target.self_call and fs["self_like"]
                                 else 0)
        out: List[Tuple[dict, str]] = []
        for i, arg in enumerate(site["pos"]):
            j = i + shift
            if j < len(params):
                out.append((arg, params[j]))
        for k, arg in site["kw"].items():
            if k in params or k in fs["kwonly"]:
                out.append((arg, k))
        return out

    def _donated_args(self, site: dict, cands: List[Target]
                      ) -> List[Tuple[dict, str]]:
        """(arg descriptor, donated field suffix) pairs this call site
        donates — directly via a jit binding's donate_argnums, or
        through a callee that (transitively) donates the mapped
        parameter. With several points-to candidates the donation must
        be proven for EVERY candidate, on an agreeing field."""
        if not cands:
            return []
        target = cands[0]
        if len(cands) == 1 and target.kind == "jit" and target.jit \
                and target.jit.get("donate"):
            out: List[Tuple[dict, str]] = []
            for d in target.jit["donate"]:
                cp = int(d) - target.offset
                if 0 <= cp < len(site["pos"]):
                    out.append((site["pos"][cp], ""))
            return out
        if not all(t.kind == "func" for t in cands):
            return []
        maps = []
        for t in cands:
            m = self.map_args(site, t)
            if m is None or t.fid is None:
                return []
            maps.append((t, m))
        out = []
        for arg, _pname0 in maps[0][1]:
            fields = set()
            for t, m in maps:
                pname = next((p for a, p in m if a is arg), None)
                fact = self._donating.get((t.fid[0], t.fid[1], pname)) \
                    if pname is not None else None
                if fact is None:
                    fields = None
                    break
                fields.add(fact.get("field", ""))
            if fields and len(fields) == 1:
                out.append((arg, fields.pop()))
        return out

    # ------------------------------------------------------------ messages

    @staticmethod
    def _chain(fact: dict, limit: int = 4) -> str:
        hops: List[str] = []
        cur: Optional[dict] = fact
        while cur is not None and len(hops) < limit:
            if "via_label" in cur:
                hops.append(f"via {cur['via_label']}")
                cur = cur.get("next")
            else:
                hops.append(f"{cur.get('desc', '?')} at line "
                            f"{cur.get('line', '?')}")
                cur = None
        return " ".join(hops)

    @staticmethod
    def _terminal(fact: dict) -> dict:
        cur = fact
        while "next" in cur:
            cur = cur["next"]
        return cur

    def _finding(self, rule: Any, path: str, site: dict,
                 message: str) -> Finding:
        return Finding(rule=rule.code, path=path, line=site["line"],
                       col=site.get("col", 1), message=message,
                       snippet=site.get("snippet", ""))

    # ------------------------------------------------------------ emitters

    def iter_transitive_host_syncs(self, rule: Any) -> Iterator[Finding]:
        """GL002 upgrade: parameter-rooted host syncs in functions that
        any traced context reaches transitively (the function itself is
        not lexically traced — those sites are the local rule's)."""
        self._build()
        emitted: Set[Tuple[str, str, int, int]] = set()
        for path, mod in self.by_path.items():
            for qual, fs in mod.funcs.items():
                fid = (path, qual)
                if fs["directly_traced"] or not fs["syncs"]:
                    continue
                if fid not in self._traced:
                    continue
                # which params provably receive a traced (non-constant)
                # value from a traced caller
                hot: Dict[str, str] = {}
                for caller, site, target in self.edges_in.get(fid, ()):
                    if caller not in self._traced:
                        continue
                    mapping = self.map_args(site, target)
                    if not mapping:
                        continue
                    cmod = self.by_path[caller[0]]
                    for arg, pname in mapping:
                        if arg.get("const"):
                            continue
                        hot.setdefault(
                            pname,
                            f"{cmod.relname}:{caller[1]} "
                            f"(line {site['line']})")
                for s in fs["syncs"]:
                    # the operand may derive from several params; ANY
                    # of them receiving a traced value makes the sync
                    # real (derivation is value-preserving)
                    hit = next((p for p in (s.get("params")
                                            or [s["param"]])
                                if p in hot), None)
                    if hit is None:
                        continue
                    who = hot[hit]
                    key = (path, qual, s["line"], s["col"])
                    if key in emitted:
                        continue
                    emitted.add(key)
                    what = (f"a value derived from parameter '{hit}'"
                            if s.get("derived")
                            else f"parameter '{hit}'")
                    yield self._finding(
                        rule, path, s,
                        f"{s['desc']} on {what} of "
                        f"'{qual}' — this helper is reached from traced "
                        f"code (called by {who}), so the sync happens "
                        "inside jit tracing; hoist the conversion out "
                        "or keep it a device value")

    def iter_loop_blocking_calls(self, rule: Any) -> Iterator[Finding]:
        """GL007 upgrade: a call inside an untraced loop hands a jitted
        step's output to a helper that (transitively) blocks on it."""
        self._build()
        for path, mod in self.by_path.items():
            for qual, fs in mod.funcs.items():
                fid = (path, qual)
                for site, cands in zip(fs["calls"],
                                       self.alt_targets[fid]):
                    if not site["in_loop"] or not cands \
                            or not all(t.kind == "func" for t in cands):
                        continue
                    maps = []
                    for t in cands:
                        m = self.map_args(site, t)
                        if m is None or t.fid is None:
                            maps = None
                            break
                        maps.append((t, m))
                    if not maps:
                        continue
                    hit = False
                    for arg, _p in maps[0][1]:
                        if hit or not arg.get("step"):
                            continue
                        fact = None
                        pname = None
                        for t, m in maps:
                            pname = next((p for a, p in m if a is arg),
                                         None)
                            f = self._blocking.get(
                                (t.fid[0], t.fid[1], pname)) \
                                if pname is not None else None
                            if f is None:
                                fact = None
                                break
                            fact = fact or f
                        if fact is None:
                            continue
                        term = self._terminal(fact)
                        label = maps[0][0].label() + (
                            f" (+{len(maps) - 1} candidate(s), all "
                            "blocking)" if len(maps) > 1 else "")
                        yield self._finding(
                            rule, path, site,
                            f"'{label}' blocks on its "
                            f"'{pname}' argument "
                            f"({term.get('desc', '?')} at line "
                            f"{term.get('line', '?')}) — calling it on "
                            "a step output inside the loop is a per-"
                            "step host sync that defeats async "
                            "dispatch; pass a device value through or "
                            "fetch once outside the loop")
                        hit = True  # one finding per call site

    def iter_cross_module_donations(self, rule: Any) -> Iterator[Finding]:
        """GL003 upgrade: replay each function's statement events; a
        read after a call that donated the value — through an imported
        jitted binding or a helper that transitively donates — is
        use-after-free even when donor and reader share no module.
        ``if`` arms replay on their own state copy; terminated arms are
        dropped, surviving arms merge by union (a buffer dead on ANY
        surviving path is a hazard on that path)."""
        self._build()
        for path, mod in self.by_path.items():
            local = set(mod.local_donations)
            for qual, fs in mod.funcs.items():
                fid = (path, qual)
                yield from self._replay_donations(
                    rule, path, fs, self.alt_targets[fid], local,
                    fs["events"], {})

    def _replay_donations(self, rule: Any, path: str, fs: dict,
                          alts: List[List[Target]], local: Set[str],
                          events: List[dict],
                          armed: Dict[str, str]) -> Iterator[Finding]:
        for ev in events:
            if "branches" in ev:
                survivors: List[Dict[str, str]] = []
                for br in ev["branches"]:
                    st = dict(armed)
                    yield from self._replay_donations(
                        rule, path, fs, alts, local,
                        br["events"], st)
                    if not br["terminates"]:
                        survivors.append(st)
                if survivors:
                    armed.clear()
                    for st in survivors:
                        armed.update(st)
                continue
            for r in ev["reads"]:
                for d in sorted(armed):
                    # component-wise both ways: reading the whole
                    # container touches its dead field, reading the
                    # dead field is the r6 shape itself; reading a
                    # SIBLING field (state['opt'] vs state['params'])
                    # conflicts with neither
                    if paths_conflict(r["text"], d):
                        yield self._finding(
                            rule, path, r,
                            f"'{d}' was {armed[d]} — its buffer "
                            "is dead after the donating call; "
                            "reading it is use-after-free (copy "
                            "first or use the call's result)")
                        armed.pop(d)
                        break
            for idx in ev["calls"]:
                site = fs["calls"][idx]
                cands = alts[idx]
                if site["callee"] in local:
                    continue  # the local rule owns this donor
                for arg, extra in self._donated_args(site, cands):
                    name = arg.get("name")
                    if not name:
                        continue
                    full = name + extra
                    if not any(path_prefix_of(b, full)
                               for b in ev["binds"]):
                        armed[full] = (f"donated to "
                                       f"'{cands[0].label()}' at "
                                       f"line {site['line']}")
            for b in ev["binds"]:
                for d in list(armed):
                    if path_prefix_of(b, d):
                        armed.pop(d)

    def iter_distant_static_hazards(self, rule: Any) -> Iterator[Finding]:
        """GL005 upgrade: shape-derived scalars / f-strings flowing into
        a jitted binding that lives in ANOTHER module (or behind a
        partial chain), unless the argument position/name is declared
        static at the distant jax.jit site."""
        self._build()
        for path, mod in self.by_path.items():
            local = set(mod.local_jitted)
            for qual, fs in mod.funcs.items():
                fid = (path, qual)
                for site, target in zip(fs["calls"], self.targets[fid]):
                    if target.kind != "jit" or site["callee"] in local:
                        continue
                    info = target.jit or {}
                    argnums = {int(x) for x in
                               info.get("static_argnums", ())}
                    argnames = set(info.get("static_argnames", ()))
                    inner_params: List[str] = []
                    if target.fid is not None:
                        inner_params = self._func(target.fid)["params"]
                    for i, arg in enumerate(site["pos"]):
                        if not arg.get("hazard"):
                            continue
                        up = i + target.offset
                        pname = (inner_params[up]
                                 if up < len(inner_params) else None)
                        if up in argnums or (pname and pname in argnames):
                            continue
                        yield self._hazard_finding(rule, path, site, arg,
                                                   target)
                    for k, arg in site["kw"].items():
                        if not arg.get("hazard"):
                            continue
                        static = k in argnames or (
                            k in inner_params
                            and inner_params.index(k) in argnums)
                        if static:
                            continue
                        yield self._hazard_finding(rule, path, site, arg,
                                                   target)

    def _hazard_finding(self, rule: Any, path: str, site: dict,
                        arg: dict, target: Target) -> Finding:
        where = {"line": arg.get("hline", site["line"]),
                 "col": arg.get("hcol", site.get("col", 1)),
                 "snippet": arg.get("hsnippet", site.get("snippet", ""))}
        return self._finding(
            rule, path, where,
            f"{arg['hazard']} flows into '{site['callee']}' — a jitted "
            f"binding declared at {target.label() if target.fid else 'a distant site'} "
            "whose static_argnums/static_argnames do not cover this "
            "argument; every new value retraces and recompiles (mark it "
            "static at the jax.jit site or derive it inside the jit)")

    def iter_cross_module_key_reuse(self, rule: Any) -> Iterator[Finding]:
        """GL011: replay each function's events tracking key-shaped
        PATHS — key-named parameters plus any parameter-rooted
        container field whose last component is key-named
        (``state['rng']``, ``self._key``); a key consumed twice — where
        at least one consumer is a (transitively proven) key-consuming
        callee — or consumed after a split, or consumed every loop
        iteration by a proven consumer without rebinding, is correlated
        randomness the local GL001 could not see."""
        self._build()
        for path, mod in self.by_path.items():
            for qual, fs in mod.funcs.items():
                fid = (path, qual)
                state: Dict[str, dict] = {
                    p: {"uses": [], "split": False}
                    for p in fs["params"] + fs["kwonly"]
                    if is_key_param(p)}
                if not state and not any(
                        is_key_path(u["name"]) for ev in
                        self._iter_stmt_events(fs["events"])
                        for u in ev["kuses"] + ev["ksplits"]) \
                        and not any(
                        is_key_path(a["name"])
                        for site in fs["calls"]
                        for a in (list(site["pos"])
                                  + list(site["kw"].values()))
                        if a.get("name")):
                    continue
                yield from self._replay_keys(
                    rule, path, fs, self.alt_targets[fid],
                    fs["events"], state)

    def _replay_keys(self, rule: Any, path: str, fs: dict,
                     alts: List[List[Target]], events: List[dict],
                     state: Dict[str, dict]) -> Iterator[Finding]:
        pset = set(fs["params"]) | set(fs["kwonly"])

        def tracked(name: str) -> Optional[dict]:
            """The state entry for a consumed path, lazily starting to
            track a parameter-rooted key-shaped field on first touch
            (its pre-call history is unknown — honest zero)."""
            st = state.get(name)
            if st is not None:
                return st
            root = path_root(name)
            if name != root and root in pset and is_key_path(name):
                st = {"uses": [], "split": False}
                state[name] = st
                return st
            return None

        def consume(name: str, kind: str, label: str,
                    site: dict) -> Optional[Finding]:
            st = tracked(name)
            if st is None:
                return None
            finding = None
            if st["split"] and kind == "callee":
                # a DIRECT use-after-split is GL001's finding already;
                # this rule only owns the half that crosses a call
                finding = self._finding(
                    rule, path, site,
                    f"key '{name}' consumed by {label} after "
                    "jax.random.split — use one of the split "
                    "results instead")
            elif st["uses"] and (kind == "callee" or any(
                    k2 == "callee" for k2, _l in st["uses"])):
                first = st["uses"][0][1]
                finding = self._finding(
                    rule, path, site,
                    f"key '{name}' consumed more than once: "
                    f"first by {first}, again by {label} — the "
                    "two consumers draw correlated randomness; "
                    "derive per-consumer keys with "
                    "jax.random.split/fold_in")
            st["uses"].append((kind, label))
            if finding is not None:
                state[name] = {"uses": [], "split": False}
            return finding

        for ev in events:
            if "branches" in ev:
                survivors: List[Dict[str, dict]] = []
                for br in ev["branches"]:
                    st2 = {k: {"uses": list(v["uses"]),
                               "split": v["split"]}
                           for k, v in state.items()}
                    yield from self._replay_keys(
                        rule, path, fs, alts, br["events"], st2)
                    if not br["terminates"]:
                        survivors.append(st2)
                if survivors:
                    # GL001 merge semantics: a key survives only if every
                    # surviving arm still tracks it; uses = the heaviest
                    # arm's, split = any arm's
                    names = set(state)
                    names.update(*survivors)
                    for name in names:
                        alive = [s[name] for s in survivors
                                 if name in s]
                        if len(alive) < len(survivors):
                            state.pop(name, None)
                            continue
                        best = max(alive, key=lambda s: len(s["uses"]))
                        state[name] = {
                            "uses": list(best["uses"]),
                            "split": any(s["split"] for s in alive)}
                continue
            for n in ev["fresh"]:
                if n in state:
                    state[n] = {"uses": [], "split": False}
            for u in ev["kuses"]:
                f = consume(u["name"], "direct",
                            f"{u.get('desc', 'jax.random')} "
                            f"(line {u['line']})", u)
                if f is not None:
                    yield f
            for u in ev["ksplits"]:
                st = tracked(u["name"])
                if st is None:
                    continue
                if any(k2 == "callee" for k2, _l in st["uses"]):
                    yield self._finding(
                        rule, path, u,
                        f"key '{u['name']}' split after already "
                        f"being consumed by "
                        f"{st['uses'][0][1]} — the split "
                        "results correlate with the earlier "
                        "draw")
                    state[u["name"]] = {"uses": [], "split": False}
                    continue
                st["split"] = True
            for idx in ev["calls"]:
                site = fs["calls"][idx]
                cands = alts[idx]
                if not cands or not all(t.kind in ("func", "jit")
                                        for t in cands):
                    continue
                maps = []
                for t in cands:
                    m = self.map_args(site, t)
                    if m is None or t.fid is None:
                        maps = None
                        break
                    maps.append((t, m))
                if not maps:
                    continue
                for arg, _pname0 in maps[0][1]:
                    name = arg.get("name")
                    if not name:
                        continue
                    # the fact must hold on EVERY candidate, with an
                    # agreeing consumed field (gap 4: dispatch through
                    # a container/dataclass callable stays a proof)
                    fact = None
                    fields = set()
                    for t, m in maps:
                        pname = next((p for a, p in m if a is arg),
                                     None)
                        f = self._keys.get((t.fid[0], t.fid[1], pname)) \
                            if pname is not None else None
                        if f is None:
                            fact = None
                            break
                        fact = fact or f
                        fields.add(f.get("field", ""))
                    if fact is None or len(fields) != 1:
                        continue
                    # which key path the callee actually consumes:
                    # the argument's path plus the proven field (gap
                    # 3: state['rng'] passed whole, consumed inside)
                    consumed = name + fields.pop()
                    if consumed not in state \
                            and tracked(consumed) is None:
                        continue
                    term = self._terminal(fact)
                    label = (f"'{maps[0][0].label()}' "
                             f"({term.get('desc', 'jax.random')}"
                             f" at line {term.get('line', '?')})")
                    if site["in_loop"] and not any(
                            paths_conflict(consumed, r)
                            for r in site["loop_rebound"]):
                        yield self._finding(
                            rule, path, site,
                            f"key '{consumed}' from outside the "
                            f"loop is consumed by {label} every "
                            "iteration without rebinding — same "
                            "randomness each pass; fold_in the "
                            "loop index")
                        state[consumed] = {"uses": [], "split": False}
                        continue
                    f = consume(consumed, "callee", label, site)
                    if f is not None:
                        yield f
            for b in ev["binds"]:
                # rebound to a non-key: stop tracking every path the
                # bind covers (fresh-key rebinds were reset above)
                for p in list(state):
                    if path_prefix_of(b, p) and b not in ev["fresh"]:
                        state.pop(p)
