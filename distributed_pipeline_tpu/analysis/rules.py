"""The graftlint rule catalog — one rule per hazard class this repo has
actually hit (ISSUE 4 / CHANGES.md r6), each with the precision posture
of a CI gate: prefer missing a hazard over crying wolf, because every
finding either blocks a merge or must be audited into the baseline.

GL001 key-reuse            same PRNG key consumed twice / used after split
GL002 host-sync            .item()/float()/np.* on values inside traced code
GL003 donation-after-use   a donated argument read after the donating call
GL004 impure-jit           print/logkv/global/attr mutation under trace
GL005 recompile-hazard     jit built per iteration; shape-derived scalars
                           or f-strings flowing into jitted args
GL006 raw-shard-map        jax.experimental.shard_map / check_rep= used
                           directly instead of utils/jax_compat
GL007 host-sync-in-loop    float()/np.asarray/.item() on a jitted step's
                           output inside the outer (untraced) training
                           loop — a per-step host sync that defeats async
                           dispatch (dispatch_lag)
GL008 hand-wired-sharding  NamedSharding constructed (or a PartitionSpec
                           passed directly as a sharding) outside the
                           partition engine — sharding belongs in rule
                           tables (parallel/partition.py), not call sites
GL009 ad-hoc-timing        a raw time.time()/perf_counter()/monotonic()
                           delta booked straight into a metric sink
                           (logkv*, or += into a metrics mapping) outside
                           utils/perf.py and obs/ — wall-time accounting
                           belongs to the perf/obs abstractions
                           (StallBreakdown, GoodputTracker, ServingTracker,
                           obs.trace spans/Stopwatch), where one owner
                           keeps the trace and the ledgers consistent
GL010 unattributed-flops   a FLOPs/MFU figure computed from raw numeric
                           constants (a literal inside a * / / **
                           expression bound to a flops/mfu/fpt name or
                           key) outside utils/perf.py and obs/ledger.py —
                           FLOP accounting has two owners so every MFU
                           figure in the repo shares one numerator with
                           the roofline cost ledger; derive through
                           transformer_train_flops_per_token /
                           active_param_count / roofline_attribution
GL011 cross-module-key-reuse  the same PRNG key flowing into two
                           (transitively proven) key-consuming callees,
                           consumed after a split across a call
                           boundary, or consumed by a callee every loop
                           iteration without rebinding — the reuse
                           GL001 cannot see because the consumers live
                           behind calls (graph-only rule)
GL012 stray-pallas-call    pl.pallas_call outside ops/ — kernels live
                           behind the ops/ dispatch seams (auto/forced
                           impl knobs, interpret fallback, layout
                           contracts); a call site elsewhere bypasses
                           dispatch, fallback AND the bench accounting

Interprocedural halves (callgraph.py, ISSUE 15): GL002, GL003, GL005
and GL007 each carry a ``check_graph`` in addition to their per-module
``check`` — tracedness, donation liveness, and static-argnum facts flow
across call and module boundaries through the whole-program summary
fixpoint, turning the three audited blind spots (transitive host syncs,
cross-module donation-after-use, distant static_argnums) from
heuristics into proofs. Unknown callees widen to "don't know": the
graph half only reports what the whole chain proves.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

from . import callgraph
from .core import Finding, Module, Rule, register
from .dataflow import field_path, path_prefix_of, paths_conflict

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While)


def _calls_in(node: ast.AST) -> Iterator[ast.Call]:
    """Call nodes in an expression/statement, NOT descending into nested
    function definitions (those are separate scopes/contexts)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(node))
    if isinstance(node, ast.Call):
        yield node
    while stack:
        n = stack.pop()
        if isinstance(n, _FUNC_NODES):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def _terminates(stmts: List[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


def _shallow_nodes(stmt: ast.stmt) -> Iterator[ast.AST]:
    """Nodes belonging to THIS statement only: header expressions and
    value subtrees, not nested statements (a flattened walk visits those
    on their own) and not nested function bodies."""
    stack: List[ast.AST] = [stmt]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(c, ast.stmt) or isinstance(c, _FUNC_NODES):
                continue
            stack.append(c)


# --------------------------------------------------------------------- GL001


class _KeyState:
    __slots__ = ("uses", "split", "from_param")

    def __init__(self, from_param: bool = False):
        self.uses = 0
        self.split = False
        self.from_param = from_param

    def copy(self) -> "_KeyState":
        st = _KeyState(self.from_param)
        st.uses, st.split = self.uses, self.split
        return st


# jax.random members that DERIVE keys rather than consuming entropy
# (one owner: callgraph.py shares these tables with the graph pass)
_KEY_DERIVERS = callgraph.KEY_DERIVERS
# callables through which passing a key is not a (countable) consumption
_KEY_TRANSPARENT = {"jax.eval_shape", "jax.device_put", "jax.tree_util.tree_map",
                    "jax.tree.map", "jax.block_until_ready", "len", "print",
                    "isinstance", "type", "repr", "str", "jax.ShapeDtypeStruct"}
_is_key_param = callgraph.is_key_param


@register
class KeyReuse(Rule):
    """GL001: the same PRNG key consumed by two samplers, consumed after
    ``jax.random.split``, or consumed inside a loop without per-iteration
    rebinding — all three produce silently correlated randomness (the
    artifacts/moe_gap.py class of bug fixed by hand in r6)."""

    code = "GL001-key-reuse"
    description = ("PRNG key reused: each key must reach exactly one "
                   "consumer; derive fresh keys with split/fold_in")

    def check(self, module: Module) -> Iterator[Finding]:
        self._out: List[Finding] = []
        self._mod = module
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                state: Dict[str, _KeyState] = {}
                args = node.args
                for a in (args.posonlyargs + args.args + args.kwonlyargs):
                    if _is_key_param(a.arg):
                        state[a.arg] = _KeyState(from_param=True)
                self._walk(node.body, state, loop_events=None)
        yield from self._out

    # -- state machinery

    def _report(self, node: ast.AST, msg: str) -> None:
        self._out.append(self._mod.finding(self, node, msg))

    def _consume_calls(self, stmt: ast.AST, state: Dict[str, _KeyState],
                       loop_events: Optional[List[Tuple[str, str]]]) -> None:
        for call in _calls_in(stmt):
            fn = self._mod.resolve(call.func)
            key_args = [a for a in list(call.args)
                        + [k.value for k in call.keywords]
                        if isinstance(a, ast.Name) and a.id in state]
            if not key_args:
                continue
            if fn and fn.startswith("jax.random."):
                member = fn.rsplit(".", 1)[1]
                if member in _KEY_DERIVERS:
                    continue
                for a in key_args:
                    st = state[a.id]
                    if member == "split":
                        if st.split:
                            self._report(a, f"key '{a.id}' split twice — "
                                            "each split consumes the key")
                        elif st.uses:
                            self._report(a, f"key '{a.id}' split after "
                                            "already being consumed")
                        st.split = True
                    else:
                        self._use(a, st, loop_events)
            elif fn in _KEY_TRANSPARENT:
                continue
            else:
                # arbitrary call: counts only for keys this scope derived
                # itself (param-named heuristics would false-positive on
                # non-key 'key' variables reaching helper calls)
                for a in key_args:
                    st = state[a.id]
                    if not st.from_param:
                        self._use(a, st, loop_events)

    def _use(self, name_node: ast.Name, st: _KeyState,
             loop_events: Optional[List[Tuple[str, str]]]) -> None:
        if st.split:
            self._report(name_node, f"key '{name_node.id}' used after "
                                    "split — use one of the split results")
        elif st.uses >= 1:
            self._report(name_node, f"key '{name_node.id}' consumed more "
                                    "than once — derive per-consumer keys "
                                    "with jax.random.split/fold_in")
        st.uses += 1
        if loop_events is not None:
            loop_events.append(("use", name_node.id))

    def _rebind(self, target: ast.AST, value: Optional[ast.AST],
                state: Dict[str, _KeyState],
                loop_events: Optional[List[Tuple[str, str]]]) -> None:
        fresh = False
        if isinstance(value, ast.Call):
            fn = self._mod.resolve(value.func)
            if fn and fn.startswith("jax.random."):
                # only key-DERIVING members produce keys; a sampler's
                # output (jax.random.normal(...)) is data, not a key
                member = fn.rsplit(".", 1)[1]
                fresh = member in _KEY_DERIVERS or member == "split"
        names: List[str] = []
        if isinstance(target, ast.Name):
            names = [target.id]
        elif isinstance(target, (ast.Tuple, ast.List)):
            names = [e.id for e in target.elts if isinstance(e, ast.Name)]
        for n in names:
            if fresh:
                state[n] = _KeyState()
                if loop_events is not None:
                    loop_events.append(("rebind", n))
            else:
                state.pop(n, None)

    def _walk(self, stmts: List[ast.stmt], state: Dict[str, _KeyState],
              loop_events: Optional[List[Tuple[str, str]]]) -> None:
        for s in stmts:
            if isinstance(s, _FUNC_NODES[:2]) or isinstance(s, ast.ClassDef):
                continue  # separate scope
            if isinstance(s, ast.If):
                self._consume_calls(s.test, state, loop_events)
                branches = []
                for body in (s.body, s.orelse):
                    st = {k: v.copy() for k, v in state.items()}
                    self._walk(body, st, loop_events)
                    if not _terminates(body):
                        branches.append(st)
                self._merge(state, branches)
            elif isinstance(s, _LOOP_NODES):
                if isinstance(s, (ast.For, ast.AsyncFor)):
                    self._consume_calls(s.iter, state, loop_events)
                    self._rebind(s.target, None, state, loop_events)
                else:
                    self._consume_calls(s.test, state, loop_events)
                pre = set(state)
                events: List[Tuple[str, str]] = []
                self._walk(s.body, state, events)
                used = {n for kind, n in events if kind == "use"}
                rebound = {n for kind, n in events if kind == "rebind"}
                for n in sorted(used & pre - rebound):
                    self._report(s, f"key '{n}' from outside the loop is "
                                    "consumed every iteration without "
                                    "rebinding (same randomness each pass)")
                self._walk(s.orelse, state, loop_events)
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                for item in s.items:
                    self._consume_calls(item.context_expr, state, loop_events)
                self._walk(s.body, state, loop_events)
            elif isinstance(s, ast.Try):
                # try body on the live state (it's the path that runs);
                # handlers/orelse on throwaway copies — consuming the whole
                # Try subtree up front would double-count the body's uses
                self._walk(s.body, state, loop_events)
                for body in [h.body for h in s.handlers] + [s.orelse]:
                    st = {k: v.copy() for k, v in state.items()}
                    self._walk(body, st, None)
                self._walk(s.finalbody, state, loop_events)
            elif isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = getattr(s, "value", None)
                if value is not None:
                    self._consume_calls(value, state, loop_events)
                targets = (s.targets if isinstance(s, ast.Assign)
                           else [s.target])
                for t in targets:
                    self._rebind(t, value, state, loop_events)
            else:
                self._consume_calls(s, state, loop_events)

    @staticmethod
    def _merge(state: Dict[str, _KeyState],
               branches: List[Dict[str, _KeyState]]) -> None:
        if not branches:
            return  # both branches terminated: keep pre-branch state
        for name in list(state):
            alive = [b[name] for b in branches if name in b]
            if len(alive) < len(branches):
                state.pop(name)  # rebound to a non-key somewhere
                continue
            st = state[name]
            st.uses = max(b.uses for b in alive)
            st.split = any(b.split for b in alive)
        for b in branches:
            for name, st in b.items():
                if name not in state:
                    state[name] = st.copy()


# --------------------------------------------------------------------- GL002

# numpy members that force (or silently constant-fold) a host round-trip
# when handed a tracer; shape/constant builders (arange/zeros/linspace...)
# stay legal — they consume static python values. (Shared table:
# callgraph.py uses the same set for the transitive half.)
_SYNC_NP = callgraph.SYNC_NP


@register
class HostSync(Rule):
    """GL002: device->host synchronization inside traced code —
    ``.item()``, ``float()/int()/bool()`` on non-literals, numpy ops, and
    explicit ``device_get``/``block_until_ready`` all either fail at trace
    time or (worse) silently freeze a traced value at trace time.

    Graph half (PROVEN, not lexical): a helper whose parameter-rooted
    host sync is reached from any traced context through an
    interprocedurally resolved call chain — across modules — is flagged
    at the sync site with the traced caller as witness."""

    code = "GL002-host-sync"
    description = ("host sync inside jit/scan-traced code (or in a "
                   "helper any traced context reaches transitively): "
                   ".item(), float()/int(), np.*, device_get, "
                   "block_until_ready")

    def check_graph(self, graph: Any) -> Iterator[Finding]:
        return graph.iter_transitive_host_syncs(self)

    def check(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or not module.in_traced(node):
                continue
            func = node.func
            fn = module.resolve(func)
            if isinstance(func, ast.Attribute) and func.attr == "item" \
                    and not node.args:
                yield module.finding(self, node,
                                     ".item() forces a device->host sync "
                                     "(trace error under jit)")
            elif isinstance(func, ast.Name) and func.id in ("float", "int",
                                                            "bool") \
                    and len(node.args) == 1 \
                    and not isinstance(node.args[0], ast.Constant):
                yield module.finding(
                    self, node,
                    f"{func.id}() on a possibly-traced value blocks on the "
                    "device (or freezes a trace-time constant); keep it a "
                    "device scalar or hoist the conversion out of the "
                    "traced function")
            elif fn and fn.startswith("numpy.") \
                    and fn.split(".")[-1] in _SYNC_NP:
                yield module.finding(
                    self, node,
                    f"numpy call '{fn}' inside traced code syncs or "
                    "constant-folds at trace time; use jax.numpy")
            elif fn == "jax.device_get":
                yield module.finding(self, node,
                                     "jax.device_get inside traced code")
            elif isinstance(func, ast.Attribute) \
                    and func.attr == "block_until_ready":
                yield module.finding(self, node,
                                     "block_until_ready inside traced code")


# --------------------------------------------------------------------- GL003


@register
class DonationAfterUse(Rule):
    """GL003: an argument donated to a jitted call is read afterwards.
    The donated buffer is dead (or worse, aliased into the output — the
    r6 heap-corruption class when combined with cache-deserialized
    executables); every read after the donating call is a use of freed
    memory the runtime may or may not catch."""

    code = "GL003-donation-after-use"
    description = ("argument donated via donate_argnums is read after "
                   "the donating call — including donors imported from "
                   "another module or helpers that transitively donate")

    def check_graph(self, graph: Any) -> Iterator[Finding]:
        # cross-module donors (imported jitted bindings, helpers that
        # transitively donate a parameter) — the r6 orbax-restore shape
        return graph.iter_cross_module_donations(self)

    def check(self, module: Module) -> Iterator[Finding]:
        if not module.donations:
            return
        scopes: List[List[ast.stmt]] = [module.tree.body]
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node.body)
        for body in scopes:
            yield from self._scan_scope(module, body)

    def _scan_scope(self, module: Module,
                    body: List[ast.stmt]) -> Iterator[Finding]:
        # linear source-order walk of the whole scope (branch-insensitive:
        # donation sites are rare enough that simplicity wins)
        stmts: List[ast.stmt] = []

        def flatten(ss: List[ast.stmt]) -> None:
            for s in ss:
                if isinstance(s, _FUNC_NODES[:2]) or isinstance(s, ast.ClassDef):
                    continue
                stmts.append(s)
                for field in ("body", "orelse", "finalbody"):
                    flatten(getattr(s, field, []) or [])
                for h in getattr(s, "handlers", []) or []:
                    flatten(h.body)

        flatten(body)
        pending: Dict[str, ast.AST] = {}
        for s in stmts:
            live = {t for t in pending}
            if live:
                # maximal canonical read paths only: once state['params']
                # is recorded, its inner Name `state` is not a separate
                # read (otherwise a sibling-field read would conflict
                # through its container root)
                skip: Set[int] = set()
                for n in _shallow_nodes(s):
                    if id(n) in skip:
                        continue
                    if not isinstance(
                            n, (ast.Name, ast.Attribute, ast.Subscript)):
                        continue
                    if not isinstance(getattr(n, "ctx", None), ast.Load):
                        continue
                    text = field_path(n)
                    if text is not None:
                        for c in ast.walk(n):
                            skip.add(id(c))
                    elif isinstance(n, ast.Subscript):
                        # dynamic index: the base container is read;
                        # which field stays unproven, so only the base
                        # chain participates (its slice is still walked)
                        text = field_path(n.value)
                        if text is None:
                            continue
                        for c in ast.walk(n.value):
                            skip.add(id(c))
                    else:
                        continue
                    for donated in sorted(live):
                        # component-wise both ways: reading the dead
                        # field, a sub-path of it, or the whole
                        # container that still holds it; a SIBLING
                        # field (state['opt'] vs state['params'])
                        # conflicts with neither
                        if paths_conflict(text, donated):
                            yield module.finding(
                                self, n,
                                f"'{donated}' was donated to a jitted call "
                                "above — its buffer is dead; reading it is "
                                "use-after-free (copy it first or use the "
                                "call's result)")
                            live.discard(donated)
            # rebinds clear; new donations arm
            targets: List[ast.AST] = []
            if isinstance(s, ast.Assign):
                targets = list(s.targets)
            elif isinstance(s, (ast.AnnAssign, ast.AugAssign)):
                targets = [s.target]
            target_texts: Set[str] = set()
            for t in targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                for e in elts:
                    tp = field_path(e)
                    if tp is not None:
                        target_texts.add(tp)
            for call in (n for n in _shallow_nodes(s)
                         if isinstance(n, ast.Call)):
                try:
                    callee = ast.unparse(call.func)
                except Exception:  # pragma: no cover - defensive
                    continue
                positions = module.donations.get(callee)
                if not positions:
                    continue
                for p in positions:
                    if p < len(call.args):
                        donated = field_path(call.args[p])
                        if donated is not None \
                                and donated not in target_texts:
                            pending[donated] = call
            for t in target_texts:
                # assigning the container kills its donated fields too
                for d in list(pending):
                    if path_prefix_of(t, d):
                        pending.pop(d)


# --------------------------------------------------------------------- GL004


@register
class ImpureJit(Rule):
    """GL004: side effects inside traced code run ONCE at trace time, not
    per step — prints vanish, metrics log a single stale value, attribute
    and global mutation desyncs from the compiled computation."""

    code = "GL004-impure-jit"
    description = ("side effect under jit/scan: print, logkv/logging, "
                   "global/nonlocal, attribute mutation")

    def check(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not module.in_traced(node):
                continue
            if isinstance(node, ast.Call):
                func = node.func
                fn = module.resolve(func)
                if isinstance(func, ast.Name) and func.id == "print":
                    yield module.finding(
                        self, node, "print() under trace runs once at "
                        "trace time — use jax.debug.print")
                elif isinstance(func, ast.Attribute) \
                        and func.attr.startswith("logkv"):
                    yield module.finding(
                        self, node, "metric logging under trace records a "
                        "tracer once, not a value per step — log outside "
                        "the jitted step")
                elif fn and fn.startswith("logging."):
                    yield module.finding(
                        self, node, "logging call under trace runs once "
                        "at trace time")
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                yield module.finding(
                    self, node, f"{type(node).__name__.lower()} statement "
                    "under trace: mutation will not re-run per step")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        yield module.finding(
                            self, t, f"attribute mutation "
                            f"'{ast.unparse(t)} = ...' under trace happens "
                            "once at trace time — return the value instead")


# --------------------------------------------------------------------- GL005


@register
class RecompileHazard(Rule):
    """GL005: patterns that defeat jit's compile cache — a fresh jit
    wrapper built per loop iteration, and shape-derived Python scalars
    (``len(x)``, ``x.shape``) or per-step-varying f-strings flowing into
    a jitted call's traced arguments (each new value = a full retrace;
    the r6 hidden step-2 recompile class).

    The rule is static-argnum aware in BOTH halves: an argument the
    ``jax.jit``/``functools.partial`` site declares static (by position
    or name) is supposed to vary — no finding. The graph half resolves
    jitted bindings imported from other modules (including through
    re-exports and partial chains), closing the "static_argnums declared
    far from the call site" blind spot in both directions: a distant
    declaration suppresses the false positive, and a distant jitted
    binding called with a hazard argument is now caught at all."""

    code = "GL005-recompile-hazard"
    description = ("recompile hazard: jit built inside a loop, or "
                   "len()/.shape/f-string values passed NON-STATIC into "
                   "a jitted binding (local or imported)")

    def check(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if module._wrapper_name(node.func) == "jax.jit":
                cur = module.parent.get(node)
                while cur is not None and not isinstance(cur, _FUNC_NODES):
                    if isinstance(cur, _LOOP_NODES):
                        yield module.finding(
                            self, node, "jax.jit called inside a loop "
                            "builds a fresh wrapper (and cache entry) per "
                            "iteration — hoist the jit out of the loop")
                        break
                    cur = module.parent.get(cur)
                continue
            try:
                callee = ast.unparse(node.func)
            except Exception:  # pragma: no cover - defensive
                continue
            if callee not in module.jitted_bindings:
                continue
            info = module.jit_info.get(callee, {})
            argnums = {int(x) for x in info.get("static_argnums", ())}
            argnames = set(info.get("static_argnames", ()))
            wrapped_params = self._wrapped_params(module, info)
            for i, arg in enumerate(node.args):
                hazard = self._scalar_hazard(arg)
                if not hazard:
                    continue
                pname = (wrapped_params[i]
                         if i < len(wrapped_params) else None)
                if i in argnums or (pname and pname in argnames):
                    continue  # declared static: supposed to vary
                yield self._hazard(module, arg, hazard, callee)
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                hazard = self._scalar_hazard(kw.value)
                if not hazard:
                    continue
                if kw.arg in argnames or (
                        kw.arg in wrapped_params
                        and wrapped_params.index(kw.arg) in argnums):
                    continue
                yield self._hazard(module, kw.value, hazard, callee)

    def check_graph(self, graph: Any) -> Iterator[Finding]:
        # jitted bindings resolved across module boundaries, with the
        # distant static_argnums/static_argnames honored
        return graph.iter_distant_static_hazards(self)

    def _hazard(self, module: Module, arg: ast.AST, hazard: str,
                callee: str) -> Finding:
        return module.finding(
            self, arg, f"{hazard} flows into jitted call "
            f"'{callee}' as a traced argument — every new "
            "value retraces and recompiles; mark it static "
            "(static_argnums) or derive it inside the jit")

    @staticmethod
    def _wrapped_params(module: Module, info: dict) -> List[str]:
        """Positional parameter names of the function the binding
        wraps, when it is a plain local def (maps static_argnames to
        positions and vice versa); [] when unknown."""
        target = info.get("target")
        if not target or "." in target:
            return []
        defs = module.defs_by_name.get(target, ())
        for d in defs:
            a = d.args
            return [p.arg for p in a.posonlyargs + a.args]
        return []

    _scalar_hazard = staticmethod(callgraph._scalar_hazard)


# --------------------------------------------------------------------- GL006

_COMPAT_EXEMPT = "utils/jax_compat.py"
_RAW_SHARD_MAP = "jax.experimental.shard_map"


@register
class RawShardMap(Rule):
    """GL006: shard_map imported/used from jax.experimental (or a raw
    ``check_rep=`` kwarg) instead of utils/jax_compat — the one spelling
    that works on both the jax>=0.6 stable API and this image's 0.4.x
    (CHANGES.md r6: the raw import ImportError'd every ring/pipeline test
    at seed)."""

    code = "GL006-raw-shard-map"
    description = ("raw jax.experimental.shard_map / check_rep= bypasses "
                   "utils/jax_compat.py")

    def check(self, module: Module) -> Iterator[Finding]:
        if module.path.replace("\\", "/").endswith(_COMPAT_EXEMPT):
            return
        suggestion = ("import shard_map from "
                      "distributed_pipeline_tpu.utils.jax_compat (version "
                      "bridge for jax 0.4.x check_rep vs >=0.6 check_vma)")
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod.startswith(_RAW_SHARD_MAP) or (
                        mod == "jax.experimental"
                        and any(a.name == "shard_map" for a in node.names)):
                    yield module.finding(
                        self, node,
                        f"raw import from {_RAW_SHARD_MAP} — {suggestion}")
            elif isinstance(node, ast.Attribute) and not isinstance(
                    module.parent.get(node), ast.Attribute):
                fn = module.resolve(node)
                if fn and fn.startswith(_RAW_SHARD_MAP):
                    yield module.finding(
                        self, node,
                        f"direct use of {fn} — {suggestion}")
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg == "check_rep":
                        yield module.finding(
                            self, node,
                            "check_rep= is the pre-0.6 spelling — call "
                            "through utils/jax_compat.shard_map with "
                            "check_vma= instead")


# --------------------------------------------------------------------- GL007

# conversions that block the host on an in-flight device value
# (shared tables: callgraph.py uses the same sets for the graph half)
_GL007_NP_BLOCKERS = callgraph.NP_BLOCKERS
_GL007_BUILTINS = callgraph.BLOCKING_BUILTINS
# method names whose call result is (very likely) a jitted step's output:
# the trainer's own loop surface plus the conventional step-fn spellings
_GL007_STEP_ATTRS = callgraph.STEP_ATTRS


def _root_name(node: ast.AST) -> Optional[str]:
    """Base Name of a Subscript/Attribute chain (``m["loss"]`` -> ``m``,
    ``out.loss`` -> ``out``); None for anything not rooted at a plain
    name (so ``float(jax.device_get(m["loss"]))`` — the SANCTIONED
    explicit-fetch spelling — never matches)."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


@register
class HostSyncInLoop(Rule):
    """GL007: a blocking conversion (``float()``/``int()``,
    ``np.asarray``/``np.array``, ``.item()``) applied to a jitted step's
    output INSIDE the outer training loop. Unlike GL002 this code is not
    traced — it runs, and it quietly serializes the pipeline: every
    iteration the host stalls on the step it just dispatched, so async
    dispatch (``dispatch_lag``) and device prefetch buy nothing. The
    fix is to keep metrics as device scalars in the loop (the logger
    fetches them in one batch at dump time) or fetch explicitly with
    ``jax.device_get`` outside the loop."""

    code = "GL007-host-sync-in-loop"
    description = ("blocking conversion (float()/np.asarray/.item()) of a "
                   "jitted step's output inside the outer training loop "
                   "— directly or through a helper that transitively "
                   "blocks on its argument — serializes async dispatch")

    def check_graph(self, graph: Any) -> Iterator[Finding]:
        # a loop handing a step output to a helper that (transitively)
        # float()s/.item()s it — the hop the lexical rule cannot see
        return graph.iter_loop_blocking_calls(self)

    def check(self, module: Module) -> Iterator[Finding]:
        reported: Set[int] = set()
        for loop in ast.walk(module.tree):
            if not isinstance(loop, _LOOP_NODES) or module.in_traced(loop):
                continue
            step_names = self._step_output_names(module, loop)
            for node in ast.walk(loop):
                if id(node) in reported or not isinstance(node, ast.Call):
                    continue
                hit = self._blocking_conversion(module, node, step_names)
                if hit:
                    reported.add(id(node))
                    yield module.finding(
                        self, node,
                        f"{hit} blocks the host on the in-flight step "
                        "every loop iteration — a per-step sync that "
                        "defeats async dispatch (dispatch_lag) and device "
                        "prefetch; keep it a device scalar (the logger "
                        "batches the fetch at dump time) or device_get it "
                        "once outside the loop")

    @staticmethod
    def _is_step_call(module: Module, call: ast.Call) -> bool:
        func = call.func
        if isinstance(func, ast.Attribute) \
                and func.attr in _GL007_STEP_ATTRS:
            return True
        try:
            callee = ast.unparse(func)
        except Exception:  # pragma: no cover - defensive
            return False
        return callee in module.jitted_bindings

    def _step_output_names(self, module: Module,
                           loop: ast.AST) -> Set[str]:
        """Names assigned anywhere in the loop body from a step-ish call
        (``m = loop.run_step(...)``, ``out = compiled(...)`` for a known
        jitted binding) — the values whose conversion blocks."""
        names: Set[str] = set()
        for node in ast.walk(loop):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            if not self._is_step_call(module, node.value):
                continue
            for t in node.targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                for e in elts:
                    if isinstance(e, ast.Name):
                        names.add(e.id)
        return names

    def _blocking_conversion(self, module: Module, call: ast.Call,
                             step_names: Set[str]) -> Optional[str]:
        """Description of the blocking conversion this call performs on a
        step output, or None."""
        func = call.func
        # (step_output).item() / m["loss"].item()
        if isinstance(func, ast.Attribute) and func.attr == "item" \
                and not call.args:
            if self._operand_is_step_output(module, func.value, step_names):
                return ".item() on a step output"
            return None
        if len(call.args) != 1:
            return None
        operand = call.args[0]
        if not self._operand_is_step_output(module, operand, step_names):
            return None
        if isinstance(func, ast.Name) and func.id in _GL007_BUILTINS:
            return f"{func.id}() on a step output"
        fn = module.resolve(func)
        if fn in _GL007_NP_BLOCKERS:
            return f"{fn} on a step output"
        return None

    def _operand_is_step_output(self, module: Module, operand: ast.AST,
                                step_names: Set[str]) -> bool:
        root = _root_name(operand)
        if root is not None:
            return root in step_names
        # direct form: float(loop.run_step(...)["loss"])
        node = operand
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            node = node.value
        return isinstance(node, ast.Call) and self._is_step_call(module,
                                                                 node)


# --------------------------------------------------------------------- GL008

# The partition engine: the only modules allowed to BIND specs to meshes.
# parallel/partition.py is the rule engine itself; parallel/sharding.py is
# its compat shim (flax logical metadata + the batch/IO helpers).
_GL008_ENGINE = ("parallel/partition.py", "parallel/sharding.py")
_GL008_NAMED_SHARDING = "jax.sharding.NamedSharding"
_GL008_PSPEC = "jax.sharding.PartitionSpec"
# kwarg names through which a bare PartitionSpec acts as a sharding at the
# call site (jit/device_put surfaces). shard_map's in_specs/out_specs are
# deliberately NOT here: those are engine-level SPMD plumbing (pipeline /
# ring internals), not a parameter-sharding decision.
_GL008_SHARDING_KWARGS = {"in_shardings", "out_shardings", "out_sharding",
                          "sharding"}


@register
class HandWiredSharding(Rule):
    """GL008: a ``NamedSharding`` constructed — or a ``PartitionSpec``
    passed directly as a sharding — outside the partition engine. Hand-
    wired sharding trees are exactly what the regex-rule engine
    (parallel/partition.py: ``match_partition_rules`` + per-model tables)
    replaced: a spec decided at a call site is invisible to the rule
    tables, drifts from them silently, and puts the next model back to
    editing engine code. Declare a rule (or use the engine/sharding
    helpers: ``replicated``, ``batch_shardings``, ``resolve_shardings``,
    ``make_shard_and_gather_fns``) instead. Bare ``PartitionSpec``
    construction stays legal — rule tables and shard_map specs are made
    of them; only using one AS a sharding (device_put target,
    in_/out_shardings=) is flagged."""

    code = "GL008-hand-wired-sharding"
    description = ("NamedSharding/PartitionSpec hand-wired as a sharding "
                   "outside parallel/partition.py|sharding.py — declare a "
                   "partition rule or use the sharding helpers")

    def check(self, module: Module) -> Iterator[Finding]:
        path = module.path.replace("\\", "/")
        if any(path.endswith(e) for e in _GL008_ENGINE):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = module.resolve(node.func)
            if fn == _GL008_NAMED_SHARDING:
                yield module.finding(
                    self, node,
                    "NamedSharding constructed outside the partition "
                    "engine — declare a partition rule "
                    "(parallel/partition.py) or use the sharding helpers "
                    "(replicated/batch_shardings/resolve_shardings)")
            elif fn == _GL008_PSPEC and self._used_as_sharding(module,
                                                              node):
                yield module.finding(
                    self, node,
                    "PartitionSpec passed directly as a sharding — bind "
                    "specs to meshes through the partition engine "
                    "(resolve_shardings/make_shard_and_gather_fns), not "
                    "at the call site")

    @staticmethod
    def _used_as_sharding(module: Module, node: ast.Call) -> bool:
        parent = module.parent.get(node)
        if isinstance(parent, ast.keyword) \
                and parent.arg in _GL008_SHARDING_KWARGS:
            return True
        if isinstance(parent, ast.keyword) and parent.arg == "device":
            # device= is generic; only a device_put target is a sharding
            grand = module.parent.get(parent)
            return isinstance(grand, ast.Call) \
                and module.resolve(grand.func) == "jax.device_put"
        if isinstance(parent, ast.Call):
            fn = module.resolve(parent.func)
            if fn in ("jax.device_put", "jax.lax.with_sharding_constraint") \
                    and len(parent.args) >= 2 and parent.args[1] is node:
                return True
        return False


# --------------------------------------------------------------------- GL009

# The sanctioned owners of wall-time deltas that become metrics. perf.py
# holds the training-side accounting (StallBreakdown/GoodputTracker/
# StepTimer/EventStats); everything under obs/ holds the tracing layer
# (spans, Stopwatch) — both are WHERE the subtraction is supposed to live.
_GL009_EXEMPT_SUFFIXES = ("utils/perf.py",)
_GL009_EXEMPT_DIRS = ("/obs/",)
_GL009_CLOCKS = {"time.time", "time.perf_counter", "time.monotonic"}


def _gl009_exempt(path: str) -> bool:
    p = path.replace("\\", "/")
    return (any(p.endswith(s) for s in _GL009_EXEMPT_SUFFIXES)
            or any(d in p for d in _GL009_EXEMPT_DIRS))


@register
class AdHocTiming(Rule):
    """GL009: a raw clock delta (``time.time()``/``perf_counter()``/
    ``monotonic()`` subtraction) booked straight into a metric sink —
    a ``logkv*`` call, or ``+=`` into a metrics mapping entry — outside
    ``utils/perf.py``/``obs/``. Scattered ad-hoc timing is exactly what
    made "where did the wall time go" unanswerable before the goodput
    ledger: each such delta is a category no fold accounts for, invisible
    to the trace timeline, and (for ``time.time()``) vulnerable to clock
    steps. Book the window through the owning abstraction instead
    (StallBreakdown/GoodputTracker/ServingTracker ``add``/``timed``, an
    ``obs.trace`` span, or ``obs.trace.Stopwatch`` when a raw number is
    genuinely all that's needed). Computing a delta for control flow or
    a result dict stays legal — only the direct delta->metric-sink flow
    is flagged, so the rule gates without drowning the baseline."""

    code = "GL009-ad-hoc-timing"
    description = ("raw time.time()/perf_counter() delta booked into a "
                   "metric sink outside utils/perf.py|obs/ — use the "
                   "perf/obs timing abstractions")

    def check(self, module: Module) -> Iterator[Finding]:
        if _gl009_exempt(module.path):
            return
        scopes: List[List[ast.stmt]] = [module.tree.body]
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node.body)
        for body in scopes:
            yield from self._scan_scope(module, body)

    # -- helpers

    def _is_clock_call(self, module: Module, node: ast.AST) -> bool:
        return isinstance(node, ast.Call) \
            and module.resolve(node.func) in _GL009_CLOCKS

    def _is_delta(self, module: Module, node: ast.AST) -> bool:
        return (isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Sub)
                and (self._is_clock_call(module, node.left)
                     or self._is_clock_call(module, node.right)))

    def _delta_in(self, module: Module, tree: ast.AST,
                  delta_names: Set[str]) -> Optional[ast.AST]:
        """A clock-delta expression (or a name bound to one in this
        scope) inside ``tree``, not descending into nested functions."""
        stack: List[ast.AST] = [tree]
        while stack:
            n = stack.pop()
            if isinstance(n, _FUNC_NODES):
                continue
            if self._is_delta(module, n):
                return n
            if isinstance(n, ast.Name) \
                    and isinstance(getattr(n, "ctx", None), ast.Load) \
                    and n.id in delta_names:
                return n
            stack.extend(ast.iter_child_nodes(n))
        return None

    def _scan_scope(self, module: Module,
                    body: List[ast.stmt]) -> Iterator[Finding]:
        # flattened source-order walk of the scope's own statements
        # (nested defs are their own scope), like GL003
        stmts: List[ast.stmt] = []

        def flatten(ss: List[ast.stmt]) -> None:
            for s in ss:
                if isinstance(s, _FUNC_NODES[:2]) \
                        or isinstance(s, ast.ClassDef):
                    continue
                stmts.append(s)
                for field in ("body", "orelse", "finalbody"):
                    flatten(getattr(s, field, []) or [])
                for h in getattr(s, "handlers", []) or []:
                    flatten(h.body)

        flatten(body)
        delta_names: Set[str] = set()
        for s in stmts:
            # a name bound to a clock delta is a delta one hop later;
            # any other rebind clears it
            if isinstance(s, ast.Assign) and len(s.targets) == 1 \
                    and isinstance(s.targets[0], ast.Name):
                if self._is_delta(module, s.value):
                    delta_names.add(s.targets[0].id)
                else:
                    delta_names.discard(s.targets[0].id)
            # sink 1: logkv*(..., <delta>) — the logger books the raw
            # number with no category any ledger accounts for. Shallow
            # nodes only: nested statements are flattened separately.
            for call in (n for n in _shallow_nodes(s)
                         if isinstance(n, ast.Call)):
                func = call.func
                name = (func.attr if isinstance(func, ast.Attribute)
                        else func.id if isinstance(func, ast.Name)
                        else "")
                if not name.startswith("logkv"):
                    continue
                for arg in list(call.args) + [k.value for k in
                                              call.keywords]:
                    hit = self._delta_in(module, arg, delta_names)
                    if hit is not None:
                        yield module.finding(
                            self, hit,
                            "raw clock delta logged as a metric — book "
                            "the window through perf/obs (StallBreakdown/"
                            "GoodputTracker add, a trace span, or "
                            "obs.trace.Stopwatch) so the goodput fold "
                            "and the timeline account for it")
            # sink 2: metrics_map[key] += <delta> (the reference
            # logger's wall-time accumulator pattern)
            if isinstance(s, ast.AugAssign) and isinstance(s.op, ast.Add) \
                    and isinstance(s.target, ast.Subscript):
                hit = self._delta_in(module, s.value, delta_names)
                if hit is not None:
                    yield module.finding(
                        self, hit,
                        "raw clock delta accumulated into a metrics "
                        "mapping — use obs.trace.Stopwatch (or a perf "
                        "tracker) as the delta's owner")


# --------------------------------------------------------------------- GL010

# The two sanctioned owners of FLOPs/MFU arithmetic: the analytic
# numerators (utils/perf.py) and the roofline attribution (obs/ledger.py).
_GL010_EXEMPT_SUFFIXES = ("utils/perf.py", "obs/ledger.py")
_GL010_ARITH_OPS = (ast.Mult, ast.Div, ast.Pow)


def _gl010_exempt(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(p.endswith(s) for s in _GL010_EXEMPT_SUFFIXES)


def _gl010_name_hit(name: str) -> bool:
    low = name.lower()
    return ("mfu" in low or "flop" in low or low == "fpt"
            or low.endswith("_fpt") or low.startswith("fpt_"))


@register
class UnattributedFlops(Rule):
    """GL010: a FLOPs/MFU figure derived from raw numeric constants —
    a literal participating in a ``*``/``/``/``**`` expression whose
    result binds to a flops/mfu/fpt-named variable, keyword, or dict
    key — outside the two sanctioned owners. Scattered ``6*N + 12*l*h*s``
    re-derivations are how the repo's MFU numbers drift apart: each
    inline copy silently disagrees with the cost ledger's (the bench's
    MoE active-params adjustment lived exactly this way until it was
    dogfooded into ``perf.active_param_count``). A pure call into the
    owners (``transformer_train_flops_per_token(...)``, ``mfu(...)``,
    ``roofline_attribution(...)``) — or any expression without literal
    arithmetic — stays legal, so the rule gates without noise."""

    code = "GL010-unattributed-flops"
    description = ("FLOPs/MFU figure computed from raw numeric constants "
                   "outside utils/perf.py|obs/ledger.py — derive it "
                   "through the perf/ledger owners")

    def check(self, module: Module) -> Iterator[Finding]:
        if _gl010_exempt(module.path):
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _gl010_name_hit(node.targets[0].id):
                yield from self._flag(module, node.value,
                                      node.targets[0].id)
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Name) \
                    and _gl010_name_hit(node.target.id):
                yield from self._flag(module, node.value, node.target.id)
            elif isinstance(node, ast.keyword) and node.arg \
                    and _gl010_name_hit(node.arg):
                yield from self._flag(module, node.value, node.arg)
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str) \
                            and _gl010_name_hit(k.value):
                        yield from self._flag(module, v, k.value)

    def _flag(self, module: Module, expr: ast.AST,
              name: str) -> Iterator[Finding]:
        hit = self._literal_arith(expr)
        if hit is not None:
            yield module.finding(
                self, hit,
                f"{name!r} computed from raw numeric constants — FLOPs/"
                f"MFU arithmetic belongs to utils/perf.py (analytic "
                f"numerators: transformer_train_flops_per_token, "
                f"active_param_count, mfu) or obs/ledger.py (roofline "
                f"attribution), so every figure shares one numerator "
                f"with the cost ledger")

    @staticmethod
    def _literal_arith(expr: ast.AST) -> Optional[ast.AST]:
        """A BinOp multiplying/dividing by a numeric literal inside
        ``expr`` (not descending into nested function definitions)."""
        stack: List[ast.AST] = [expr]
        while stack:
            n = stack.pop()
            if isinstance(n, _FUNC_NODES):
                continue
            if isinstance(n, ast.BinOp) \
                    and isinstance(n.op, _GL010_ARITH_OPS):
                for side in (n.left, n.right):
                    if isinstance(side, ast.Constant) \
                            and isinstance(side.value, (int, float)) \
                            and not isinstance(side.value, bool):
                        return n
            stack.extend(ast.iter_child_nodes(n))
        return None


# --------------------------------------------------------------------- GL011


@register
class CrossModuleKeyReuse(Rule):
    """GL011: the same PRNG key flowing into two key-consuming callees
    (graph-only rule — the whole point is that the consumers live behind
    calls, often in other modules). GL001 deliberately does not count a
    key-named parameter passed to an arbitrary call — without knowing
    the callee, that would drown the report in maybes. The call graph
    removes the guesswork: a callee parameter is *proven* key-consuming
    when a ``jax.random`` sampler (or split) reaches it transitively, so
    the replay can count those calls as consumptions exactly. Flags:
    two consumptions of one key where at least one crosses a proven
    callee; consumption after ``jax.random.split`` across a call
    boundary; and a proven consumer called every loop iteration on a
    key from outside the loop without rebinding."""

    code = "GL011-cross-module-key-reuse"
    description = ("same PRNG key consumed by two (transitively proven) "
                   "key-consuming callees across call/module boundaries "
                   "— correlated randomness GL001 cannot see")

    def check_graph(self, graph: Any) -> Iterator[Finding]:
        return graph.iter_cross_module_key_reuse(self)


# --------------------------------------------------------------------- GL012


_OPS_DIR = "/ops/"
_PALLAS_ROOTS = ("jax.experimental.pallas", "jax._src.pallas")


@register
class StrayPallasCall(Rule):
    """GL012: ``pl.pallas_call`` outside ``ops/`` — kernels live behind
    the ops/ dispatch seams (``resolve_decode_impl`` auto/forced knobs,
    ``interpret=`` CPU fallback, the (8, 128) layout contracts and the
    schedule-derived HBM byte accounting the bench legs report). A call
    site anywhere else gets none of that: it hard-fails off-TPU, dodges
    the impl knob the configs thread through the stack, and its bytes
    never reach the ledger, so the kernel's roofline win is invisible
    to regress.py."""

    code = "GL012-stray-pallas-call"
    description = ("pl.pallas_call outside ops/ bypasses the dispatch "
                   "seam, interpret fallback and bench byte accounting")

    def check(self, module: Module) -> Iterator[Finding]:
        path = module.path.replace("\\", "/")
        if _OPS_DIR in path or path.startswith("ops/"):
            return
        suggestion = ("wrap the kernel in distributed_pipeline_tpu/ops/ "
                      "behind an impl='auto'|'pallas'|'xla' dispatch "
                      "function (see ops/flash_decode.py) and call the "
                      "seam instead")
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Attribute) and not isinstance(
                    module.parent.get(node), ast.Attribute):
                fn = module.resolve(node)
                if fn and fn.startswith(_PALLAS_ROOTS) \
                        and fn.endswith(".pallas_call"):
                    yield module.finding(
                        self, node,
                        f"{fn} used outside ops/ — {suggestion}")
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod.startswith(_PALLAS_ROOTS) and any(
                        a.name == "pallas_call" for a in node.names):
                    yield module.finding(
                        self, node,
                        f"pallas_call imported from {mod} outside ops/ "
                        f"— {suggestion}")


# --------------------------------------------------------------------- GL013


# duplicated from utils/perf.py (SANITIZE_REPORT_NAME) on purpose: the
# analyzer must stay importable without jax
SANITIZE_REPORT_NAME = "sanitize_report.json"


@register
class RuntimeCoverageGap(Rule):
    """GL013: the runtime sanitizer (``--sanitize``) observed a violation
    — a transfer-guard trip or a steady-state recompile — at a site the
    static pass CLEARED. The two passes audit each other: a runtime
    violation with no static finding at the same file+line means either
    a rule blind spot (file an issue, the evidence names the exact site)
    or true dynamic behavior no static pass can prove (audit it into the
    baseline with --write-baseline). Only fires in
    ``--runtime-evidence RUN_DIR`` mode; the per-module and graph passes
    yield nothing."""

    code = "GL013-runtime-coverage-gap"
    description = ("runtime sanitizer evidence (sanitize_report.json) "
                   "shows a violation at a site the static pass cleared "
                   "— a coverage gap: rule blind spot or true dynamic "
                   "behavior (only with --runtime-evidence)")

    def check(self, module: Module) -> Iterator[Finding]:
        return iter(())


_KIND_LABEL = {
    "transfer_guard": "an implicit host<->device transfer tripped the "
                      "transfer guard",
    "steady_recompile": "XLA kept compiling after steady state",
}


def runtime_evidence_findings(violations: List[Dict[str, Any]],
                              findings: List[Finding],
                              rule: Optional[Rule] = None
                              ) -> List[Finding]:
    """Cross-reference runtime sanitizer violations against this run's
    static findings. A violation is COVERED when some static finding
    sits at the same file (two-component path tail — the fingerprint
    normalization) and line: the linter already told the user. Anything
    else surfaces as GL013 — the static pass vouched for a site the
    runtime proved dirty."""
    from .baseline import path_tail

    rule = rule or RuntimeCoverageGap()
    covered = {(path_tail(f.path), f.line) for f in findings}
    out: List[Finding] = []
    seen: set = set()
    for v in violations:
        vpath = str(v.get("path") or "")
        vline = int(v.get("line") or 0)
        if not vpath:
            continue  # site-less evidence: nothing to cross-reference
        if (path_tail(vpath), vline) in covered:
            continue
        kind = str(v.get("kind", "violation"))
        key = (path_tail(vpath), vline, kind)
        if key in seen:
            continue  # one finding per site+kind, however many trips
        seen.add(key)
        label = _KIND_LABEL.get(kind, kind)
        detail = str(v.get("detail", ""))[:200]
        func = str(v.get("func", "") or "")
        out.append(Finding(
            rule=rule.code, path=vpath, line=max(1, vline), col=1,
            message=(f"runtime evidence: {label}"
                     + (f" in {func}()" if func else "")
                     + (f" [{detail}]" if detail else "")
                     + " — but the static pass reports no finding at "
                       "this line; rule blind spot or true dynamic "
                       "behavior (if dynamic, audit via "
                       "--write-baseline)"),
            snippet=str(v.get("snippet", ""))[:200]))
    return out
