"""Content-hash parse/summary cache for the analysis CLI and lint gate.

The gated path list grows every PR; reparsing ~70 unchanged modules per
``pytest -m lint`` run is pure waste. The cache memoizes exactly the
per-file work — the AST parse, the per-module rule findings, and the
:class:`~callgraph.ModuleSummary` the whole-program pass consumes — keyed
on the file's content sha. The cross-module findings are *never* cached:
they are recomputed from the (cached or fresh) summaries every run, so a
change in module A still updates the findings it causes in module B.

Soundness levers:

* entries key on the file's **content sha** (not mtime — a ``git
  checkout`` that restores bytes restores the hit);
* the whole cache keys on a **salt** hashed over the analysis package's
  own sources, so editing any rule or the summarizer invalidates every
  entry at once (a stale summary schema can never be half-loaded);
* an entry records the **rule codes** it was computed with; a run with a
  narrower ``--rules`` selection may read it (findings are filtered),
  but a run selecting rules the entry never ran misses.

The file lives beside the baseline (``graftlint_cache.json``), is
written atomically, and any unreadable/garbled state degrades to a cold
cache — the cache can slow a run down, never corrupt it.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Any, Dict, List, Optional

__all__ = ["AnalysisCache", "CACHE_NAME", "package_salt"]

CACHE_NAME = "graftlint_cache.json"
_VERSION = 1
_salt: Optional[str] = None


def package_salt() -> str:
    """sha over this package's own .py sources: any change to the
    analyzer invalidates every cached entry."""
    global _salt
    if _salt is None:
        h = hashlib.sha1()
        pkg = os.path.dirname(os.path.abspath(__file__))
        for name in sorted(os.listdir(pkg)):
            if not name.endswith(".py"):
                continue
            h.update(name.encode())
            try:
                with open(os.path.join(pkg, name), "rb") as f:
                    h.update(f.read())
            except OSError:  # pragma: no cover - defensive
                pass
        _salt = h.hexdigest()
    return _salt


class AnalysisCache:
    """sha-keyed store of (module summary, per-module findings) entries,
    duck-typed against by :func:`core.run_paths` (``get``/``put``/
    ``save``)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.salt = package_salt()
        self.hits = 0
        self.misses = 0
        self._dirty = False
        self._files: Dict[str, dict] = {}
        try:
            with open(path, "r", encoding="utf-8") as f:
                data = json.load(f)
            if (isinstance(data, dict)
                    and data.get("version") == _VERSION
                    and data.get("salt") == self.salt
                    and isinstance(data.get("files"), dict)):
                self._files = data["files"]
        except (OSError, ValueError):
            pass  # cold cache

    @staticmethod
    def _key(path: str) -> str:
        return os.path.abspath(path)

    def get(self, path: str, sha: str,
            codes: List[str]) -> Optional[dict]:
        e = self._files.get(self._key(path))
        if (isinstance(e, dict) and e.get("sha") == sha
                and set(codes) <= set(e.get("rules", ()))
                and isinstance(e.get("summary"), dict)
                and isinstance(e.get("findings"), list)):
            self.hits += 1
            return e
        self.misses += 1
        return None

    def put(self, path: str, sha: str, codes: List[str],
            summary: dict, findings: List[dict]) -> None:
        self._files[self._key(path)] = {
            "sha": sha, "rules": sorted(codes),
            "summary": summary, "findings": findings}
        self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        payload = {"version": _VERSION, "tool": "graftlint",
                   "salt": self.salt, "files": self._files}
        try:
            d = os.path.dirname(os.path.abspath(self.path)) or "."
            fd, tmp = tempfile.mkstemp(prefix=".graftlint_cache",
                                       dir=d, suffix=".tmp")
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)
            self._dirty = False
        except OSError:  # telemetry must never fail the lint run
            try:
                os.unlink(tmp)  # type: ignore[possibly-undefined]
            except (OSError, UnboundLocalError):
                pass
