"""graftlint CLI.

    python -m distributed_pipeline_tpu.analysis [options] PATHS...

Exit codes: 0 = clean against the baseline, 1 = findings outside the
baseline (CI fails), 2 = usage error. stdout carries the report in the
selected format (``json`` is a single object — machine-parseable, the
contract tests/test_analysis.py pins); notes go to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .baseline import BASELINE_NAME, Baseline, discover_baseline, path_tail
from .cache import CACHE_NAME, AnalysisCache
from .core import _assign_indices, all_rules, iter_py_files, run_paths
from . import rules as _rules  # noqa: F401  (register the catalog)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m distributed_pipeline_tpu.analysis",
        description="graftlint: JAX-aware static analysis "
                    "(PRNG reuse, host syncs, donation, purity, "
                    "recompiles, compat bypasses), interprocedural: a "
                    "whole-program call-graph pass flows tracedness/"
                    "donation/static-argnum/key facts across module "
                    "boundaries")
    p.add_argument("paths", nargs="*", metavar="PATH",
                   help="files or directories to lint")
    p.add_argument("--format", choices=("human", "json", "github"),
                   default="human",
                   help="report format (default: human); 'github' emits "
                        "::error file=...,line=...:: workflow annotations "
                        "so CI surfaces findings inline")
    p.add_argument("--baseline", default="auto", metavar="FILE",
                   help=f"baseline file; 'auto' (default) discovers "
                        f"{BASELINE_NAME} in cwd or above the first PATH; "
                        f"'none' disables")
    p.add_argument("--write-baseline", action="store_true",
                   help="write ALL current findings to the baseline file "
                        "and exit 0 (then audit the diff before committing)")
    p.add_argument("--rules", default="", metavar="CODES",
                   help="comma-separated rule-code prefixes to run "
                        "(default: all), e.g. GL001,GL004")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--changed", nargs="*", default=None, metavar="FILE",
                   help="report only findings in these files (the whole "
                        "program is still analyzed — cross-module facts "
                        "need every summary; this scopes the REPORT, for "
                        "per-PR CI annotation)")
    p.add_argument("--no-cache", action="store_true",
                   help="disable the content-hash parse/summary cache "
                        f"({CACHE_NAME} beside the baseline)")
    p.add_argument("--runtime-evidence", default="", metavar="RUN_DIR",
                   help="cross-reference runtime sanitizer evidence: "
                        "load sanitize_report.json sidecars (RUN_DIR "
                        "itself, a direct file path, or any depth below "
                        "RUN_DIR) and report each violation the static "
                        "pass did NOT flag at the same file+line as a "
                        "GL013 coverage-gap finding")
    return p


def _load_sanitize_reports(root: str) -> List[tuple]:
    """(path, report dict) for every readable sanitize_report.json at or
    under ``root`` (which may also name the file directly). Garbled
    sidecars are skipped with a note — evidence is best-effort by
    design, and a half-written report must not kill the lint."""
    from .rules import SANITIZE_REPORT_NAME

    candidates: List[str] = []
    if os.path.isfile(root):
        candidates.append(root)
    else:
        for dirpath, _dirs, files in os.walk(root):
            if SANITIZE_REPORT_NAME in files:
                candidates.append(
                    os.path.join(dirpath, SANITIZE_REPORT_NAME))
    out: List[tuple] = []
    for path in sorted(candidates):
        try:
            with open(path) as f:
                report = json.load(f)
            if not isinstance(report.get("violations"), list):
                raise ValueError("no violations list")
        except (OSError, ValueError) as e:
            print(f"# runtime-evidence: skipping unreadable {path}: {e}",
                  file=sys.stderr)
            continue
        out.append((path, report))
    return out


def _github_lines(findings) -> List[str]:
    """GitHub Actions workflow-command annotations. Newlines/percent in
    messages are URL-style escaped per the workflow-command spec."""
    out = []
    for f in findings:
        msg = f"{f.rule} {f.message}"
        msg = (msg.replace("%", "%25").replace("\r", "%0D")
               .replace("\n", "%0A"))
        out.append(f"::error file={f.path},line={f.line},"
                   f"col={f.col},title=graftlint {f.rule}::{msg}")
    return out


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.code}: {r.description}")
        return 0
    if not args.paths:
        print("error: no paths given (see --help)", file=sys.stderr)
        return 2
    if args.rules:
        wanted = [w.strip() for w in args.rules.split(",") if w.strip()]
        rules = [r for r in rules
                 if any(r.code.startswith(w) for w in wanted)]
        if not rules:
            print(f"error: no rules match {args.rules!r}", file=sys.stderr)
            return 2

    cache = None
    if not args.no_cache:
        # the cache lives beside the baseline (one discovery rule for
        # both committed-state files); no baseline home -> no cache,
        # rather than scattering cache files into arbitrary cwds
        home = discover_baseline(args.paths[0] if args.paths else None)
        if home:
            cache = AnalysisCache(
                os.path.join(os.path.dirname(home), CACHE_NAME))

    findings, n_files = run_paths(args.paths, rules, cache=cache)
    if cache is not None:
        print(f"# cache: {cache.hits} hit(s), {cache.misses} miss(es)",
              file=sys.stderr)
    if args.runtime_evidence:
        reports = _load_sanitize_reports(args.runtime_evidence)
        if not reports:
            print(f"error: no sanitize_report.json found under "
                  f"{args.runtime_evidence!r} (run with --sanitize to "
                  f"produce one)", file=sys.stderr)
            return 2
        violations = [v for _p, r in reports
                      for v in r.get("violations", [])]
        gaps = _rules.runtime_evidence_findings(violations, findings)
        # re-index the combined list: GL013 fingerprints must be as
        # stable as everyone else's so they can be baselined/audited
        findings = _assign_indices(findings + gaps)
        print(f"# runtime-evidence: {len(reports)} report(s), "
              f"{len(violations)} violation(s), {len(gaps)} coverage "
              f"gap(s)", file=sys.stderr)
    if n_files == 0:
        # a gate that lints zero files vouches for nothing — a typo'd CI
        # path must fail loudly, not report OK
        print(f"error: no .py files found under {args.paths!r}",
              file=sys.stderr)
        return 2

    baseline_path: Optional[str] = None
    if args.baseline == "auto":
        baseline_path = discover_baseline(args.paths[0])
    elif args.baseline not in ("none", ""):
        baseline_path = args.baseline

    if args.write_baseline:
        path = baseline_path or BASELINE_NAME
        notes = {}
        old_entries = []
        if baseline_path:
            try:  # carry audit notes forward across regenerations
                old_entries = Baseline.load(baseline_path).entries
                notes = {e["fingerprint"]: e["audit"]
                         for e in old_entries if "audit" in e}
            except (OSError, ValueError, KeyError):
                old_entries = []
        # MERGE, don't clobber: a narrowed run (--rules filter, or a
        # PATHS subset of what the baseline covers) must not silently
        # drop the audited entries it didn't re-lint. An old entry is
        # replaced only when this run actually re-covered it — its file
        # was visited AND its rule was selected; everything else is
        # preserved verbatim (stale entries in gated paths are caught by
        # the no-stale-entries CI test, not by losing them here).
        visited = {path_tail(p) for p in iter_py_files(args.paths)}
        selected = {r.code for r in rules} | {"GL000-parse-error"}
        preserved = [e for e in old_entries
                     if path_tail(e["path"]) not in visited
                     or e["rule"] not in selected]
        new_bl = Baseline.from_findings(findings, notes)
        new_bl.entries = preserved + new_bl.entries
        new_bl.save(path)
        print(f"wrote {len(findings)} finding(s) "
              + (f"(+{len(preserved)} preserved out-of-scope entr"
                 f"{'y' if len(preserved) == 1 else 'ies'}) "
                 if preserved else "")
              + f"to {path}; audit the diff before committing",
              file=sys.stderr)
        return 0

    baseline = None
    if baseline_path:
        try:
            baseline = Baseline.load(baseline_path)
        except (OSError, ValueError) as e:
            print(f"error: cannot read baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
    new, baselined = (findings, []) if baseline is None \
        else baseline.split(findings)
    if args.changed is not None:
        # scope the REPORT (and the exit code) to the changed files;
        # the analysis itself stayed whole-program
        changed = {os.path.abspath(c) for c in args.changed}
        new = [f for f in new if os.path.abspath(f.path) in changed]

    if args.format == "github":
        for line in _github_lines(new):
            print(line)
        print(f"{'FAIL' if new else 'OK'} {n_files} file(s), "
              f"{len(new)} finding(s)"
              + (f", {len(baselined)} baselined" if baselined else ""),
              file=sys.stderr)
    elif args.format == "json":
        print(json.dumps({
            "version": 1,
            "tool": "graftlint",
            "checked_files": n_files,
            "rules": [r.code for r in rules],
            "baseline": baseline_path,
            "baselined": len(baselined),
            "findings": [f.to_dict() for f in new],
        }, indent=2))
    else:
        for f in new:
            print(f"{f.path}:{f.line}:{f.col}: {f.rule} {f.message}")
            if f.snippet:
                print(f"    {f.snippet}")
        tail = (f"{n_files} file(s), {len(new)} finding(s)"
                + (f", {len(baselined)} baselined" if baselined else "")
                + (f" [baseline: {baseline_path}]" if baseline_path else ""))
        print(("FAIL " if new else "OK ") + tail,
              file=sys.stderr if new else sys.stdout)
    return 1 if new else 0
