"""Intraprocedural value-flow for graftlint (ROADMAP item 7 closure).

The r17 call-graph pass proved facts about values *rooted at
parameters*: ``float(x)`` where ``x`` is a parameter, donation of a
bare name, a key passed as the first positional. Everything one hop of
local dataflow away — ``loss = state.loss * 2; float(loss)``, donation
of ``state["params"]``, a sampler called as ``normal(key=k)``, a
callable fetched from a dict — was widened to silence. This module is
that missing hop: a statement-ordered abstract interpretation of one
function body producing three fact families the summarizer
(:mod:`callgraph`) folds into :class:`~callgraph.ModuleSummary`:

* **derivation** (gap 1): for every expression, the set of parameters
  it *provably* derives from, under must-semantics — an operand is
  derived only when every path to the current statement built it from
  parameters through value-preserving operations (arithmetic,
  ``jax.numpy``/``jax.lax``/``jax.random`` calls, array methods,
  container fields). A call to an unknown function, a read of a static
  attribute (``.shape``, ``.dtype``), or a branch that rebinds on one
  arm all widen to "not derived". Host-sync sites are re-detected over
  derived operands, so ``float(jnp.mean(x))`` on a traced parameter is
  a proof, not a guess.
* **field paths** (gap 2/3): ``state["params"]``, ``cfg.step`` and
  friends canonicalize to textual paths (:func:`field_path`) with a
  component-wise conflict test (:func:`paths_conflict`), so donation
  arming, rebind kills and key tracking distinguish sibling fields
  while a read of the whole container still conflicts with a dead
  leaf.
* **points-to** (gap 4): a bounded set of callable references per
  path — ``h = HANDLERS["relu"]``, ``self.step = train_step``,
  ``Cfg(step=f)`` — kept only while every store to the path is a
  recognized reference (one lambda, one unknown call result, one
  non-constant subscript store and the whole subtree widens to
  ``None`` = silence). The summarizer attaches the candidates to call
  sites; the graph pass treats a fact as proven only when *all*
  candidates carry it.

Everything here is honest-widening by construction: the analysis only
ever *adds* proofs on top of the r17 behavior, never speculates. The
semantic fact tables (``SYNC_NP`` etc.) live here so this module stays
import-cycle-free (``callgraph`` imports us and re-exports them for
``rules``).
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from .core import Module

__all__ = [
    "ARRAY_METHODS", "BLOCKING_BUILTINS", "DERIVING_PREFIXES",
    "FunctionFlow", "KEY_DERIVERS", "KEY_PARAM_PAT", "NP_BLOCKERS",
    "PT_BOUND", "STATIC_ATTRS", "STEP_ATTRS", "SYNC_NP",
    "analyze_function", "field_path", "is_key_param", "is_key_path",
    "last_component", "module_maps", "path_prefix_of", "path_root",
    "path_suffix", "paths_conflict",
]

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

# ---- semantic fact tables (shared with rules.py via callgraph re-export)

SYNC_NP = {"asarray", "array", "sum", "mean", "std", "var", "max", "min",
           "argmax", "argmin", "any", "all", "allclose", "isnan",
           "isfinite", "isinf", "where", "concatenate", "stack", "dot",
           "matmul", "prod", "abs", "clip", "sqrt", "exp", "log",
           "float32", "float64", "int32", "int64"}
NP_BLOCKERS = {"numpy.asarray", "numpy.array"}
BLOCKING_BUILTINS = {"float", "int", "bool"}
STEP_ATTRS = {"run_step", "forward_only", "train_step", "eval_step"}
KEY_DERIVERS = {"PRNGKey", "key", "fold_in", "key_data", "wrap_key_data",
                "clone", "key_impl"}
KEY_PARAM_PAT = ("rng", "key", "prng", "seed_key")

# attributes whose value is host metadata, not the traced array — a
# derivation chain through one of these is NOT a device value
STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "itemsize", "nbytes",
                "sharding", "device", "devices", "aval", "weak_type",
                "name", "__name__"}
# resolved-prefix call families that return values derived from their
# array arguments (jnp.mean(x) is as traced as x)
DERIVING_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.nn.", "jax.scipy.",
                     "jax.random.", "jax.tree_util.", "jax.tree.")
DERIVING_EXACT = {"jax.device_put", "jax.block_until_ready"}
# members of the deriving families that return host metadata instead
_NONDERIVING_MEMBERS = {"shape", "ndim", "size", "dtype", "result_type",
                        "iinfo", "finfo", "save", "load"}
# array methods whose result derives from the receiver; .item()/.tolist()
# are deliberately absent (they return host scalars — the sync detector
# owns them, the derivation must stop)
ARRAY_METHODS = {"sum", "mean", "max", "min", "argmax", "argmin", "std",
                 "var", "prod", "reshape", "astype", "transpose", "dot",
                 "ravel", "squeeze", "flatten", "copy", "conj", "cumsum",
                 "cumprod", "clip", "round", "repeat", "take",
                 "swapaxes", "at", "set", "add", "get", "block_until_ready"}

PT_BOUND = 4  # max points-to candidates per path before widening


def is_key_param(name: str) -> bool:
    low = name.lower()
    return any(low == p or low.endswith("_" + p) or low.startswith(p + "_")
               or low.rstrip("0123456789") == p for p in KEY_PARAM_PAT)


# ============================================================ field paths

def field_path(node: ast.AST) -> Optional[str]:
    """Canonical textual path of a Name/Attribute/Subscript chain:
    ``x`` / ``x.attr`` / ``x['key']`` / ``x[0]`` — composable. None for
    anything else (a non-constant subscript key, a call in the chain):
    such a value has no stable identity, so every consumer widens."""
    parts: List[str] = []
    while True:
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return "".join(reversed(parts))
        if isinstance(node, ast.Attribute):
            parts.append("." + node.attr)
            node = node.value
            continue
        if isinstance(node, ast.Subscript):
            sl = node.slice
            if isinstance(sl, ast.Constant) \
                    and isinstance(sl.value, (str, int)):
                parts.append(f"[{sl.value!r}]")
                node = node.value
                continue
            return None
        return None


def path_root(path: str) -> str:
    for i, ch in enumerate(path):
        if ch in ".[":
            return path[:i]
    return path


def path_suffix(path: str) -> str:
    return path[len(path_root(path)):]


def last_component(path: str) -> str:
    """The final segment of a path, unquoted: ``state['rng']`` -> rng,
    ``cfg.key`` -> key, ``k`` -> k."""
    depth = 0
    for i in range(len(path) - 1, -1, -1):
        ch = path[i]
        if ch == "]":
            depth += 1
        elif ch == "[" and depth:
            depth -= 1
            if not depth:
                return path[i + 1:-1].strip("'\"")
        elif ch == "." and not depth:
            return path[i + 1:]
    return path


def is_key_path(path: str) -> bool:
    """A path whose final component is key-named — the paths the GL011
    replay tracks lazily when they root at a parameter."""
    return is_key_param(last_component(path))


def path_prefix_of(shorter: str, longer: str) -> bool:
    """True when ``shorter`` is ``longer`` or a component-wise prefix of
    it (``state`` covers ``state['params'].w`` but not ``state2``)."""
    return longer.startswith(shorter) and (
        len(longer) == len(shorter) or longer[len(shorter)] in ".[")


def paths_conflict(a: str, b: str) -> bool:
    """Either path covers the other: a read of ``state`` conflicts with
    a donated ``state['params']`` and vice versa; ``state['opt']`` does
    not."""
    return path_prefix_of(a, b) or path_prefix_of(b, a)


# ========================================================== shared helpers

def _shallow_exprs(node: ast.AST) -> Iterator[ast.AST]:
    """This statement's own expression nodes, source-ordered enough for
    sync detection: no nested statements, no nested function/lambda
    bodies (their dataflow is their own scope's problem)."""
    stack: List[ast.AST] = [node]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(c, ast.stmt) or isinstance(c, _FUNC_DEFS) \
                    or isinstance(c, ast.Lambda):
                continue
            stack.append(c)


def _is_ref(node: ast.AST) -> Optional[str]:
    """The canonical text of a plain callable *reference* (Name or
    dotted Attribute chain) — the only values the points-to map stores;
    anything computed widens."""
    if isinstance(node, (ast.Name, ast.Attribute)):
        p = node
        while isinstance(p, ast.Attribute):
            p = p.value
        if isinstance(p, ast.Name):
            try:
                return ast.unparse(node)
            except Exception:  # pragma: no cover - defensive
                return None
    return None


# ============================================================= module maps

def module_maps(module: Module) -> Tuple[Dict[str, Optional[Tuple[str, ...]]],
                                         Dict[str, Dict[str, Optional[
                                             Tuple[str, ...]]]],
                                         Set[str]]:
    """(module-level points-to env, per-class attribute points-to map,
    class names). The class map unions every recognized reference store
    to an attribute — class-body assigns plus ``self.attr = ref`` across
    all methods; any non-reference store to the same attribute widens it
    to ``None`` (a call through it proves nothing).

    The module env only keeps facts the WHOLE module agrees on: after
    the module-body scan, every function-body statement that mutates a
    module-level path (``HANDLERS[name] = fn`` registration, ``del``,
    ``CFG.step = other``) or lets the container object escape as a bare
    reference (aliased, passed as an argument, returned) widens the
    touched subtree — a dispatch through it then proves nothing, per
    the r17 contract."""
    penv: Dict[str, Optional[Tuple[str, ...]]] = {}
    class_pt: Dict[str, Dict[str, Optional[Tuple[str, ...]]]] = {}
    classes: Set[str] = set()

    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        classes.add(node.name)
        attrs = class_pt.setdefault(node.name, {})

        def store(attr: str, value: ast.AST) -> None:
            ref = _is_ref(value)
            if ref is None or isinstance(value, ast.Lambda):
                attrs[attr] = None  # widened: unprovable store
                return
            if attr in attrs and attrs[attr] is None:
                return
            cur = tuple(attrs.get(attr) or ())
            if ref not in cur:
                cur = cur + (ref,)
            attrs[attr] = cur if len(cur) <= PT_BOUND else None

        for stmt in node.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        store(t.id, stmt.value)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                    and isinstance(stmt.target, ast.Name):
                store(stmt.target.id, stmt.value)
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign):
                continue
            for t in sub.targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    store(t.attr, sub.value)

    for node in module.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            _pt_assign(penv, node.targets[0].id, node.value,
                       classes=classes, class_pt=class_pt)

    _widen_module_mutations(module, penv, class_pt)
    return penv, class_pt, classes


def _widen_module_mutations(
        module: Module, penv: Dict[str, Optional[Tuple[str, ...]]],
        class_pt: Dict[str, Dict[str, Optional[Tuple[str, ...]]]]) -> None:
    """Honest-widening escape pass over every scope BELOW the module
    body: stores/deletes through a module-level path kill that subtree;
    a bare Load of a tracked container root (not as the base of a
    canonical field read) means the object escaped — anyone may mutate
    it, so the whole root widens."""
    roots = {path_root(p) for p in penv}

    def widen(path: str) -> None:
        for k in [p for p in penv if path_prefix_of(path, p)]:
            penv[k] = None
        penv[path] = None

    module_stmts = {id(s) for s in module.tree.body}
    for node in ast.walk(module.tree):
        if id(node) in module_stmts:
            continue
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (list(node.targets) if isinstance(node, ast.Assign)
                       else [node.target])
        elif isinstance(node, ast.Delete):
            targets = list(node.targets)
        else:
            continue
        for t in targets:
            for e in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                      else [t]):
                p = field_path(e)
                if p is None and isinstance(e, ast.Subscript):
                    p = field_path(e.value)  # dynamic key: widen base
                if p is not None and path_root(p) in roots:
                    widen(p)
                if isinstance(e, ast.Attribute) \
                        and isinstance(e.value, ast.Name) \
                        and e.value.id in class_pt:
                    # Cfg.step = ... from below module scope: the class
                    # default is no longer a proof for ANY instance
                    class_pt[e.value.id][e.attr] = None
    # escape scan: a tracked container used as a bare reference (not the
    # base of a canonical path read) may be mutated by whoever got it;
    # a mutating method through any chain is mutation outright
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Name) and node.id in roots
                and isinstance(node.ctx, ast.Load)):
            continue
        top = node
        parent = module.parent.get(top)
        while isinstance(parent, (ast.Attribute, ast.Subscript)) \
                and parent.value is top:
            top = parent
            parent = module.parent.get(top)
        if top is node:
            # bare use: len(HANDLERS), f(HANDLERS), h = HANDLERS — the
            # object is out of the module env's hands now
            widen(node.id)
        elif isinstance(top, ast.Attribute) and top.attr in _MUTATORS \
                and isinstance(parent, ast.Call) and parent.func is top:
            widen(node.id)


_MUTATORS = frozenset({
    "update", "clear", "pop", "popitem", "setdefault",
    "append", "extend", "insert", "remove"})


def _pt_assign(penv: Dict[str, Optional[Tuple[str, ...]]], base: str,
               value: ast.AST,
               classes: Optional[Set[str]] = None,
               class_pt: Optional[Dict[str, Dict[str, Optional[
                   Tuple[str, ...]]]]] = None) -> None:
    """Record a ``base = value`` store into a points-to env: reference
    texts, dict literals (per-constant-key entries plus an all-keys
    wildcard when every value is a reference), a local-class constructor
    call (``CFG = Cfg(step=fn)`` — per-kwarg attribute entries over the
    class defaults), everything else widens the subtree."""
    for k in [p for p in penv if path_prefix_of(base, p)]:
        del penv[k]
    if classes and isinstance(value, ast.Call) \
            and isinstance(value.func, ast.Name) \
            and value.func.id in classes \
            and not value.args \
            and all(kw.arg is not None for kw in value.keywords):
        defaults = (class_pt or {}).get(value.func.id, {})
        for attr, cands in defaults.items():
            penv[f"{base}.{attr}"] = cands
        for kw in value.keywords:
            ref = _is_ref(kw.value)
            # a non-ref kwarg blocks the class default for that field
            penv[f"{base}.{kw.arg}"] = \
                (ref,) if ref is not None else None
        return
    if isinstance(value, ast.Dict):
        complete = True
        wild: List[str] = []
        for kx, vx in zip(value.keys, value.values):
            ref = _is_ref(vx)
            if ref is None:
                complete = False
                continue
            wild.append(ref)
            if isinstance(kx, ast.Constant) \
                    and isinstance(kx.value, (str, int)):
                penv[f"{base}[{kx.value!r}]"] = (ref,)
        if complete and wild and len(set(wild)) <= PT_BOUND:
            penv[base + "[*]"] = tuple(dict.fromkeys(wild))
        else:
            penv[base + "[*]"] = None
        return
    ref = _is_ref(value)
    if ref is not None and not isinstance(value, ast.Lambda):
        penv[base] = (ref,)
    else:
        penv[base] = None  # widened


# ======================================================== function analysis

class FunctionFlow:
    """The per-function result the summarizer consumes: proven host-sync
    sites (with the parameter set each operand derives from) and
    points-to candidate lists keyed by ``id(Call node)``."""

    def __init__(self) -> None:
        self.syncs: List[dict] = []
        self.candidates: Dict[int, List[str]] = {}


_EMPTY: FrozenSet[str] = frozenset()


class _Walker:
    """One pass over a function body maintaining two environments:

    * ``denv``: path -> frozenset of parameter roots it provably derives
      from (empty set = an explicit kill that blocks prefix fallback);
    * ``penv``: path -> tuple of callable reference texts, or ``None``
      for a widened subtree.

    Branch arms run on copies and merge under must-semantics: a
    derivation survives only when every surviving arm agrees; a
    points-to entry missing from any arm widens."""

    def __init__(self, module: Module, params: Set[str],
                 class_pt: Dict[str, Dict[str, Optional[Tuple[str, ...]]]],
                 classes: Set[str], cls: Optional[str]) -> None:
        self.module = module
        self.params = params
        self.class_pt = class_pt
        self.classes = classes
        self.cls = cls
        self.flow = FunctionFlow()
        self._seen_syncs: Set[int] = set()

    # ---------------------------------------------------------- derivation

    def deriv(self, node: ast.AST,
              denv: Dict[str, FrozenSet[str]]) -> FrozenSet[str]:
        if isinstance(node, ast.Name):
            return denv.get(node.id, _EMPTY)
        if isinstance(node, (ast.Attribute, ast.Subscript)):
            # any static-metadata hop (x.shape[0], x.dtype.name) makes
            # the whole chain trace-static — not a derived value
            link: ast.AST = node
            while isinstance(link, (ast.Attribute, ast.Subscript)):
                if isinstance(link, ast.Attribute) \
                        and link.attr in STATIC_ATTRS:
                    return _EMPTY
                link = link.value
            path = field_path(node)
            if path is not None:
                cur = path
                while True:
                    if cur in denv:
                        return denv[cur]
                    root = path_root(cur)
                    if cur == root:
                        return _EMPTY
                    cur = self._parent_path(cur)
            return self.deriv(node.value, denv)
        if isinstance(node, ast.BinOp):
            return self.deriv(node.left, denv) | self.deriv(node.right,
                                                            denv)
        if isinstance(node, ast.UnaryOp):
            return self.deriv(node.operand, denv)
        if isinstance(node, ast.Compare):
            out = self.deriv(node.left, denv)
            for c in node.comparators:
                out |= self.deriv(c, denv)
            return out
        if isinstance(node, ast.BoolOp):
            # `a and b` returns ONE operand: proven only if all are
            parts = [self.deriv(v, denv) for v in node.values]
            return frozenset().union(*parts) if all(parts) else _EMPTY
        if isinstance(node, ast.IfExp):
            a, b = self.deriv(node.body, denv), self.deriv(node.orelse,
                                                           denv)
            return a | b if (a and b) else _EMPTY
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out: FrozenSet[str] = _EMPTY
            for e in node.elts:
                out |= self.deriv(e, denv)
            return out
        if isinstance(node, ast.Starred):
            return self.deriv(node.value, denv)
        if isinstance(node, ast.Await):
            return self.deriv(node.value, denv)
        if isinstance(node, ast.NamedExpr):
            return self.deriv(node.value, denv)
        if isinstance(node, ast.Call):
            return self._call_deriv(node, denv)
        return _EMPTY

    @staticmethod
    def _parent_path(path: str) -> str:
        depth = 0
        for i in range(len(path) - 1, -1, -1):
            ch = path[i]
            if ch == "]":
                depth += 1
            elif ch == "[":
                depth -= 1
                if not depth:
                    return path[:i]
            elif ch == "." and not depth:
                return path[:i]
        return path_root(path)

    def _call_deriv(self, call: ast.Call,
                    denv: Dict[str, FrozenSet[str]]) -> FrozenSet[str]:
        fn = self.module.resolve(call.func)
        args_deriv: FrozenSet[str] = _EMPTY
        for a in call.args:
            args_deriv |= self.deriv(a, denv)
        for k in call.keywords:
            args_deriv |= self.deriv(k.value, denv)
        if fn is not None:
            member = fn.rsplit(".", 1)[-1]
            if fn in DERIVING_EXACT:
                return args_deriv
            if fn.startswith(DERIVING_PREFIXES) \
                    and member not in _NONDERIVING_MEMBERS:
                return args_deriv
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr in ARRAY_METHODS:
            return self.deriv(func.value, denv) | args_deriv
        return _EMPTY  # unknown callee: honest widening

    # -------------------------------------------------------- sync shapes

    def _sync_check(self, call: ast.Call,
                    denv: Dict[str, FrozenSet[str]]) -> Optional[dict]:
        """The GL002/GL007 host-sync shapes, with derived (not merely
        parameter-rooted) operands. Returns the proven record or None."""
        func = call.func
        fn = self.module.resolve(func)
        hit: Optional[Tuple[ast.AST, str, bool]] = None
        if isinstance(func, ast.Attribute) and func.attr == "item" \
                and not call.args:
            hit = (func.value, ".item()", True)
        elif isinstance(func, ast.Name) and func.id in BLOCKING_BUILTINS \
                and len(call.args) == 1 \
                and not isinstance(call.args[0], ast.Constant):
            hit = (call.args[0], f"{func.id}()", True)
        elif fn and fn.startswith("numpy.") \
                and fn.split(".")[-1] in SYNC_NP:
            for a in call.args:
                if self.deriv(a, denv):
                    hit = (a, fn, fn in NP_BLOCKERS)
                    break
        elif fn == "jax.device_get" and call.args:
            hit = (call.args[0], "jax.device_get", False)
        elif isinstance(func, ast.Attribute) \
                and func.attr == "block_until_ready":
            hit = (func.value, "block_until_ready", False)
        if hit is None:
            return None
        operand, desc, blocking = hit
        roots = self.deriv(operand, denv)
        if not roots:
            return None
        direct = isinstance(operand, ast.Name) and operand.id in roots
        params = sorted(roots)
        return {"param": params[0], "params": params, "desc": desc,
                "blocking": blocking, "derived": not direct}

    # ----------------------------------------------------------- points-to

    def _pt_lookup(self, node: ast.AST,
                   penv: Dict[str, Optional[Tuple[str, ...]]],
                   cenv: Dict[str, str]) -> Optional[Tuple[str, ...]]:
        path = field_path(node)
        if path is not None:
            if path in penv:
                return penv[path]
            # a widened ancestor poisons the whole subtree
            cur = path
            while True:
                root = path_root(cur)
                if cur == root:
                    break
                cur = self._parent_path(cur)
                if penv.get(cur, ()) is None:
                    return None
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name):
                cname = cenv.get(node.value.id)
                if cname and cname in self.class_pt:
                    return self.class_pt[cname].get(node.attr)
            return None
        if isinstance(node, ast.Subscript):
            base = field_path(node.value)
            if base is not None:
                return penv.get(base + "[*]")
        return None

    # --------------------------------------------------- statement walking

    def visit_exprs(self, stmt: ast.stmt,
                    denv: Dict[str, FrozenSet[str]],
                    penv: Dict[str, Optional[Tuple[str, ...]]],
                    cenv: Dict[str, str]) -> None:
        stmt_calls = [n for n in _shallow_exprs(stmt)
                      if isinstance(n, ast.Call)]
        stmt_calls.sort(key=lambda c: (getattr(c, "lineno", 0),
                                       getattr(c, "col_offset", 0)))
        for call in stmt_calls:
            if id(call) not in self._seen_syncs:
                self._seen_syncs.add(id(call))
                hit = self._sync_check(call, denv)
                if hit is not None:
                    line = getattr(call, "lineno", 1)
                    hit.update({"line": line,
                                "col": getattr(call, "col_offset", 0) + 1,
                                "snippet": self.module.snippet(line)})
                    self.flow.syncs.append(hit)
            cands = self._pt_lookup(call.func, penv, cenv)
            if cands:
                self.flow.candidates.setdefault(id(call), list(cands))

    def _kill(self, path: str, denv: Dict[str, FrozenSet[str]],
              penv: Dict[str, Optional[Tuple[str, ...]]],
              cenv: Dict[str, str]) -> None:
        for k in [p for p in denv if path_prefix_of(path, p)]:
            del denv[k]
        for k in [p for p in penv if path_prefix_of(path, p)]:
            del penv[k]
        denv[path] = _EMPTY
        cenv.pop(path, None)

    def assign(self, target: ast.AST, value: Optional[ast.AST],
               denv: Dict[str, FrozenSet[str]],
               penv: Dict[str, Optional[Tuple[str, ...]]],
               cenv: Dict[str, str]) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value, (ast.Tuple, ast.List)) \
                    and len(value.elts) == len(target.elts) \
                    and not any(isinstance(e, ast.Starred)
                                for e in target.elts):
                for t, v in zip(target.elts, value.elts):
                    self.assign(t, v, denv, penv, cenv)
                return
            dv = self.deriv(value, denv) if value is not None else _EMPTY
            for t in target.elts:
                t2 = t.value if isinstance(t, ast.Starred) else t
                self._assign_one(t2, value, dv, denv, penv, cenv,
                                 exact=False)
            return
        dv = self.deriv(value, denv) if value is not None else _EMPTY
        self._assign_one(target, value, dv, denv, penv, cenv, exact=True)

    def _assign_one(self, target: ast.AST, value: Optional[ast.AST],
                    dv: FrozenSet[str],
                    denv: Dict[str, FrozenSet[str]],
                    penv: Dict[str, Optional[Tuple[str, ...]]],
                    cenv: Dict[str, str], exact: bool) -> None:
        path = field_path(target)
        if path is None:
            # e.g. d[i] = v: an unidentifiable store widens the base
            base = field_path(getattr(target, "value", None)) \
                if isinstance(target, ast.Subscript) else None
            if base is not None:
                self._kill(base, denv, penv, cenv)
                denv[base] = _EMPTY
            return
        self._kill(path, denv, penv, cenv)
        denv[path] = dv  # empty = explicit not-derived kill
        if value is None or not exact:
            return
        # points-to transfer
        vpath = field_path(value) if isinstance(
            value, (ast.Name, ast.Attribute, ast.Subscript)) else None
        if vpath is not None and (
                vpath in penv or vpath in cenv
                or any(path_prefix_of(vpath, p) for p in penv)):
            # alias copy: mirror the source's points-to subtree
            if vpath in penv:
                penv[path] = penv[vpath]
            for k in [p for p in penv if path_prefix_of(vpath, p)
                      and p != vpath]:
                penv[path + k[len(vpath):]] = penv[k]
            if vpath in cenv:
                cenv[path] = cenv[vpath]
            return
        if isinstance(value, ast.Call) and isinstance(value.func, ast.Name) \
                and value.func.id in self.classes \
                and not any(k.arg is None for k in value.keywords):
            # Cfg(step=fn): dataclass-style constructor field stores
            cenv[path] = value.func.id
            for k in value.keywords:
                if k.arg is None:
                    continue
                ref = _is_ref(k.value)
                fpath = f"{path}.{k.arg}"
                penv[fpath] = (ref,) if ref is not None \
                    and not isinstance(k.value, ast.Lambda) else None
            return
        _pt_assign(penv, path, value)
        if isinstance(value, (ast.Name, ast.Attribute)):
            v2 = field_path(value)
            if v2 is not None and v2 in cenv:
                cenv[path] = cenv[v2]

    # the env-triple type is heavy; pass the three dicts positionally
    def walk(self, stmts: List[ast.stmt],
             denv: Dict[str, FrozenSet[str]],
             penv: Dict[str, Optional[Tuple[str, ...]]],
             cenv: Dict[str, str]) -> bool:
        """Walk ``stmts`` updating the envs in place. Returns True when
        the suite provably terminates (return/raise/break/continue)."""
        for s in stmts:
            if isinstance(s, _FUNC_DEFS) or isinstance(s, ast.ClassDef):
                self._kill(s.name, denv, penv, cenv)
                continue
            self.visit_exprs(s, denv, penv, cenv)
            if isinstance(s, ast.Assign):
                for t in s.targets:
                    self.assign(t, s.value, denv, penv, cenv)
            elif isinstance(s, ast.AnnAssign):
                if s.value is not None:
                    self.assign(s.target, s.value, denv, penv, cenv)
            elif isinstance(s, ast.AugAssign):
                path = field_path(s.target)
                dv = self.deriv(s.target, denv) | self.deriv(s.value,
                                                             denv)
                if path is not None:
                    self._kill(path, denv, penv, cenv)
                    denv[path] = dv
            elif isinstance(s, ast.If):
                self._walk_arms(
                    [s.body, s.orelse], denv, penv, cenv)
            elif isinstance(s, (ast.For, ast.AsyncFor)):
                self._walk_loop(s, denv, penv, cenv)
            elif isinstance(s, ast.While):
                self._walk_loop(s, denv, penv, cenv)
            elif isinstance(s, ast.Try) \
                    or s.__class__.__name__ == "TryStar":
                self._walk_try(s, denv, penv, cenv)
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                for item in s.items:
                    if item.optional_vars is not None:
                        self.assign(item.optional_vars, None,
                                    denv, penv, cenv)
                if self.walk(s.body, denv, penv, cenv):
                    return True
            elif isinstance(s, ast.Delete):
                for t in s.targets:
                    p = field_path(t)
                    if p is not None:
                        self._kill(p, denv, penv, cenv)
            elif isinstance(s, (ast.Global, ast.Nonlocal)):
                for n in s.names:
                    self._kill(n, denv, penv, cenv)
            elif isinstance(s, (ast.Return, ast.Raise, ast.Break,
                                ast.Continue)):
                return True
        return False

    def _copies(self, denv: Dict[str, FrozenSet[str]],
                penv: Dict[str, Optional[Tuple[str, ...]]],
                cenv: Dict[str, str]) -> Tuple[dict, dict, dict]:
        return dict(denv), dict(penv), dict(cenv)

    @staticmethod
    def _merge_into(denv: Dict[str, FrozenSet[str]],
                    penv: Dict[str, Optional[Tuple[str, ...]]],
                    cenv: Dict[str, str],
                    arms: List[Tuple[dict, dict, dict]]) -> None:
        """Must-merge the arm envs into the outer envs in place."""
        denv.clear()
        penv.clear()
        cenv.clear()
        if not arms:
            return
        dkeys = set().union(*(a[0] for a in arms))
        for k in dkeys:
            vals = [a[0].get(k, _EMPTY) for a in arms]
            denv[k] = (frozenset().union(*vals)
                       if all(vals) else _EMPTY)
        pkeys = set().union(*(a[1] for a in arms))
        for k in pkeys:
            vals = [a[1].get(k, ()) for a in arms]  # () = unbound arm
            if any(v is None or v == () for v in vals):
                penv[k] = None  # an arm without the binding widens it
                continue
            merged = tuple(dict.fromkeys(r for v in vals for r in v))
            penv[k] = merged if len(merged) <= PT_BOUND else None
        ckeys = set().union(*(a[2] for a in arms))
        for k in ckeys:
            vals = {a[2].get(k) for a in arms}
            if len(vals) == 1 and None not in vals:
                cenv[k] = vals.pop()

    def _walk_arms(self, suites: List[List[ast.stmt]],
                   denv: Dict[str, FrozenSet[str]],
                   penv: Dict[str, Optional[Tuple[str, ...]]],
                   cenv: Dict[str, str]) -> None:
        survivors: List[Tuple[dict, dict, dict]] = []
        for suite in suites:
            arm = self._copies(denv, penv, cenv)
            if not self.walk(suite, *arm):
                survivors.append(arm)
        self._merge_into(denv, penv, cenv, survivors)

    def _walk_loop(self, s: ast.AST,
                   denv: Dict[str, FrozenSet[str]],
                   penv: Dict[str, Optional[Tuple[str, ...]]],
                   cenv: Dict[str, str]) -> None:
        entry = self._copies(denv, penv, cenv)
        body = self._copies(denv, penv, cenv)
        if isinstance(s, (ast.For, ast.AsyncFor)):
            dv = self.deriv(s.iter, denv)
            tpath = field_path(s.target)
            if tpath is not None:
                self._kill(tpath, *body)
                body[0][tpath] = dv
            else:
                self.assign(s.target, None, *body)
        self.walk(s.body, *body)
        # after the loop: zero-or-more iterations ran
        self._merge_into(denv, penv, cenv, [entry, body])
        self.walk(s.orelse, denv, penv, cenv)

    def _walk_try(self, s: ast.AST,
                  denv: Dict[str, FrozenSet[str]],
                  penv: Dict[str, Optional[Tuple[str, ...]]],
                  cenv: Dict[str, str]) -> None:
        entry = self._copies(denv, penv, cenv)
        body = self._copies(denv, penv, cenv)
        body_done = self.walk(s.body, *body)
        if not body_done:
            body_done = self.walk(s.orelse, *body)
        # a handler runs after an arbitrary body prefix: its entry state
        # keeps only facts surviving both the entry and the full body
        hentry = self._copies(*entry)
        self._merge_into(*hentry, [entry, body])
        survivors: List[Tuple[dict, dict, dict]] = []
        if not body_done:
            survivors.append(body)
        for h in s.handlers:
            arm = self._copies(*hentry)
            if h.name:
                self._kill(h.name, *arm)
            if not self.walk(h.body, *arm):
                survivors.append(arm)
        self._merge_into(denv, penv, cenv, survivors or [entry])
        self.walk(s.finalbody, denv, penv, cenv)


def analyze_function(module: Module, node: ast.AST, cls: Optional[str],
                     class_pt: Dict[str, Dict[str, Optional[
                         Tuple[str, ...]]]],
                     module_env: Dict[str, Optional[Tuple[str, ...]]],
                     classes: Set[str]) -> FunctionFlow:
    """Run the value-flow walk over one function def; the result feeds
    :func:`callgraph._summarize_function` (sync records with derivation
    sets; per-call-site points-to candidates)."""
    a = node.args
    params = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    w = _Walker(module, params, class_pt, classes, cls)
    denv: Dict[str, FrozenSet[str]] = {p: frozenset([p]) for p in params}
    penv: Dict[str, Optional[Tuple[str, ...]]] = dict(module_env)
    cenv: Dict[str, str] = {}
    pos = a.posonlyargs + a.args
    if cls and pos and pos[0].arg in ("self", "cls"):
        cenv[pos[0].arg] = cls
    try:
        w.walk(list(node.body), denv, penv, cenv)
    except RecursionError:  # pragma: no cover - pathological nesting
        pass
    return w.flow
