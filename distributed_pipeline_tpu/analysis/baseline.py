"""Committed allowlist for audited findings.

A finding the team has audited and judged unavoidable (e.g. a ``float()``
on a rate STRING inside a traced step — safe, but indistinguishable
statically from a device sync) goes into ``graftlint_baseline.json``
instead of the rule being weakened for everyone. Entries match by the
Finding fingerprint — rule + path tail + stripped source line + an
occurrence index — so the baseline survives line-number churn but
invalidates itself when the flagged line actually changes.

Discovery: an explicit ``--baseline FILE`` wins; ``auto`` (the default)
looks for ``graftlint_baseline.json`` in the current directory, then up
the parents of the first linted path — so ``python -m
distributed_pipeline_tpu.analysis distributed_pipeline_tpu/`` run from
the repo root gates against the committed file with zero flags.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from .core import Finding

__all__ = ["Baseline", "discover_baseline", "path_tail", "BASELINE_NAME"]

BASELINE_NAME = "graftlint_baseline.json"


def path_tail(path: str) -> str:
    """The last two path components — the same normalization Finding
    fingerprints use, so entry paths compare stably across cwds."""
    return "/".join(path.replace(os.sep, "/").split("/")[-2:])


def discover_baseline(first_path: Optional[str]) -> Optional[str]:
    candidates = [os.path.join(os.getcwd(), BASELINE_NAME)]
    if first_path:
        cur = os.path.dirname(os.path.abspath(first_path))
        for _ in range(16):
            candidates.append(os.path.join(cur, BASELINE_NAME))
            parent = os.path.dirname(cur)
            if parent == cur:
                break
            cur = parent
    for c in candidates:
        if os.path.isfile(c):
            return c
    return None


class Baseline:
    """Fingerprint set with enough sidecar detail (path/line/snippet/
    audit note) that a human can re-audit an entry without re-running
    the tool against the old tree."""

    def __init__(self, entries: Optional[List[Dict]] = None,
                 path: Optional[str] = None) -> None:
        self.entries: List[Dict] = entries or []
        self.path = path
        self._fps = {e["fingerprint"] for e in self.entries}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        if not isinstance(data, dict) or "entries" not in data:
            raise ValueError(f"{path}: not a graftlint baseline "
                             "(expected {'version': 1, 'entries': [...]})")
        return cls(list(data["entries"]), path=path)

    @classmethod
    def from_findings(cls, findings: Iterable[Finding],
                      notes: Optional[Dict[str, str]] = None) -> "Baseline":
        notes = notes or {}
        entries = []
        for f in findings:
            e = f.to_dict()
            e.pop("col", None)
            e.pop("message", None)
            if f.fingerprint in notes:
                e["audit"] = notes[f.fingerprint]
            entries.append(e)
        return cls(entries)

    def save(self, path: str) -> None:
        payload = {
            "version": 1,
            "tool": "graftlint",
            "note": ("audited-unavoidable findings; regenerate with "
                     "`python -m distributed_pipeline_tpu.analysis "
                     "--write-baseline <paths>` and re-audit the diff"),
            "entries": sorted(self.entries,
                              key=lambda e: (e["path"], e.get("line", 0),
                                             e["rule"])),
        }
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2, sort_keys=False)
            fh.write("\n")
        self.path = path

    def __contains__(self, finding: Finding) -> bool:
        return finding.fingerprint in self._fps

    def split(self, findings: Iterable[Finding]
              ) -> Tuple[List[Finding], List[Finding]]:
        """(new, baselined) partition preserving order."""
        new, old = [], []
        for f in findings:
            (old if f in self else new).append(f)
        return new, old
