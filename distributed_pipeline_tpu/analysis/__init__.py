"""graftlint: JAX-aware static analysis for this training stack.

Generic linters cannot see the bug classes that actually burn TPU runs
here — the ones past rounds fixed by hand (CHANGES.md r6): PRNG key
reuse (artifacts/moe_gap.py), a hidden step-2 recompile from unpinned
``out_shardings``, donating Orbax-restored buffers into a
cache-deserialized executable. This subpackage is the correctness-
tooling layer production JAX stacks carry for exactly these hazards:

* :mod:`core` — AST module model (import resolution, traced-context
  discovery, donation map), the rule registry, and the file runner.
* :mod:`rules` — the rule catalog (GL001..GL011), one visitor per
  hazard class this repo has hit.
* :mod:`callgraph` — the whole-program pass (ISSUE 15): per-module
  summaries + import resolution + signature-aware fixpoints flow
  tracedness, donation liveness, static-argnum and PRNG-key facts
  across call and module boundaries, turning the r7 audit's blind
  spots into proofs (GL002/GL003/GL005/GL007 graph halves, GL011).
* :mod:`cache` — content-hash parse/summary cache so the lint gate
  stops reparsing unchanged modules as the gated path list grows.
* :mod:`baseline` — committed allowlist store: findings audited as
  unavoidable are fingerprinted into ``graftlint_baseline.json``
  instead of the rule being suppressed.
* :mod:`cli` — ``python -m distributed_pipeline_tpu.analysis
  [--format json|human|github] [--baseline FILE] [--changed FILE...]
  [--no-cache] PATHS``.

The static pass is paired with a runtime "sanitizer mode"
(``--sanitize``, utils/perf.RecompileMonitor + transfer guards in
utils/trainer.TrainLoop) that catches dynamically what the AST pass
cannot prove: actual recompiles and implicit host<->device transfers.
"""

from __future__ import annotations

from .baseline import Baseline
from .cache import AnalysisCache
from .callgraph import CallGraph, ModuleSummary, summarize_module
from .core import Finding, Module, Rule, all_rules, run_paths
from . import rules as _rules  # noqa: F401  (imports register the catalog)

__all__ = ["AnalysisCache", "Baseline", "CallGraph", "Finding", "Module",
           "ModuleSummary", "Rule", "all_rules", "run_paths",
           "summarize_module"]
