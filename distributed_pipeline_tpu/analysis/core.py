"""graftlint engine: module model, traced-context discovery, registry.

The rules in :mod:`rules` need three module-level facts that plain
``ast.walk`` does not give them:

1. **What a dotted name means** (``Imports``): ``jnp.sum`` must resolve
   to ``jax.numpy.sum`` whatever the import spelling, including relative
   imports (``from ..utils.jax_compat import shard_map``).
2. **Which code is traced** (``Module.traced`` / ``in_traced``): host
   syncs and side effects are only hazards inside code JAX traces — a
   function jitted directly (decorator or ``jax.jit(f)`` call), a
   ``lax.scan``/``fori_loop``/``while_loop``/``shard_map`` body, or
   anything lexically nested in one. Tracedness is deliberately NOT
   propagated through ordinary calls: that keeps the pass precise (a
   helper also called from eager code would otherwise drown the report
   in maybes; the runtime sanitizer covers the dynamic remainder).
3. **Which callables donate** (``Module.donations``): call sites of a
   binding built from ``jax.jit(f, donate_argnums=...)`` — directly,
   through a wrapper call like ``AOTStep(jax.jit(...))``, or a
   ``@partial(jax.jit, donate_argnums=...)`` decorator.

Findings carry a line-number-independent ``fingerprint`` (rule + the
last two path components + the stripped source line + an occurrence
index) so a committed baseline survives unrelated edits to the file.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple, Type

__all__ = ["Finding", "Imports", "Module", "Rule", "register",
           "all_rules", "run_paths", "dotted"]

# Wrappers whose function argument (or decorated function) is traced.
TRACE_WRAPPERS = {
    "jax.jit", "jax.pmap", "jax.vmap", "jax.grad", "jax.value_and_grad",
    "jax.checkpoint", "jax.remat", "jax.named_call", "jax.eval_shape",
    "nn.jit", "flax.linen.jit",
}
# callable -> positions of traced function arguments
TRACED_ARG_POS = {
    "jax.lax.scan": (0,), "jax.lax.map": (0,), "jax.lax.fori_loop": (2,),
    "jax.lax.while_loop": (0, 1), "jax.lax.cond": (1, 2),
    "jax.lax.associative_scan": (0,),
}
# any resolved name ending in one of these is a shard_map-style wrapper
TRACED_ARG_SUFFIXES = {"shard_map": (0,)}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str
    index: int = 0  # occurrence disambiguator among identical snippets

    @property
    def fingerprint(self) -> str:
        tail = "/".join(self.path.replace(os.sep, "/").split("/")[-2:])
        raw = "|".join([self.rule, tail, self.snippet, str(self.index)])
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def to_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "snippet": self.snippet, "fingerprint": self.fingerprint}


def dotted(node: ast.AST, imports: "Imports") -> Optional[str]:
    """Resolve a Name/Attribute chain to a dotted path through the import
    aliases, e.g. ``jnp.sum`` -> ``jax.numpy.sum``; None for anything
    rooted in a non-name expression (calls, subscripts, ...)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(imports.alias.get(node.id, node.id))
    return ".".join(reversed(parts))


class Imports:
    """local name -> dotted origin, from the module's import statements."""

    def __init__(self, tree: ast.AST) -> None:
        self.alias: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.alias[a.asname] = a.name
                    else:
                        # ``import jax.numpy`` binds ``jax``
                        root = a.name.split(".")[0]
                        self.alias[root] = root
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name == "*":
                        continue
                    # normalize so the dot count always equals the
                    # relative level: ``from . import x`` -> ``.x`` (the
                    # old spelling "..x" was indistinguishable from a
                    # level-2 import, which matters to the call-graph
                    # pass's module-to-module resolution)
                    if node.module:
                        origin = "." * node.level + f"{node.module}.{a.name}"
                    else:
                        origin = "." * node.level + a.name
                    self.alias[a.asname or a.name] = origin


class Module:
    """One parsed file plus the shared semantic maps rules consume."""

    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.source = source
        self.tree = ast.parse(source)
        self.lines = source.splitlines()
        self.parent: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        self.imports = Imports(self.tree)
        self.defs_by_name: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(node.name, []).append(node)
        self.traced: set = self._find_traced()
        # binding text -> {"donate", "static_argnums", "static_argnames",
        # "target"}; donations/jitted_bindings are the derived views the
        # per-module rules consume
        self.jit_info: Dict[str, Dict[str, Any]] = {}
        self.donations: Dict[str, Tuple[int, ...]] = {}
        self.jitted_bindings: set = set()
        self._find_jit_bindings()

    # ---------------------------------------------------------- tracedness

    def resolve(self, node: ast.AST) -> Optional[str]:
        return dotted(node, self.imports)

    def _wrapper_name(self, node: ast.AST) -> Optional[str]:
        """Resolved name of a trace wrapper: ``jax.jit`` itself or
        ``partial(jax.jit, ...)``."""
        name = self.resolve(node)
        if name in TRACE_WRAPPERS:
            return name
        if isinstance(node, ast.Call) and node.args:
            fn = self.resolve(node.func)
            if fn in ("functools.partial", "partial"):
                inner = self.resolve(node.args[0])
                if inner in TRACE_WRAPPERS:
                    return inner
        return None

    def _mark(self, node: Optional[ast.AST], traced: set) -> None:
        if isinstance(node, ast.Lambda):
            traced.add(node)
        elif isinstance(node, ast.Name):
            for d in self.defs_by_name.get(node.id, ()):
                traced.add(d)

    def _find_traced(self) -> set:
        traced: set = set()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    base = dec.func if isinstance(dec, ast.Call) else dec
                    if (self._wrapper_name(dec) is not None
                            or self._wrapper_name(base) is not None):
                        traced.add(node)
            elif isinstance(node, ast.Call):
                fn = self.resolve(node.func)
                if self._wrapper_name(node.func) is not None and node.args:
                    self._mark(node.args[0], traced)
                positions: Tuple[int, ...] = ()
                if fn in TRACED_ARG_POS:
                    positions = TRACED_ARG_POS[fn]
                elif fn is not None:
                    for suffix, pos in TRACED_ARG_SUFFIXES.items():
                        if fn.split(".")[-1] == suffix:
                            positions = pos
                for p in positions:
                    if p < len(node.args):
                        self._mark(node.args[p], traced)
        return traced

    def in_traced(self, node: ast.AST) -> bool:
        """True when ``node`` sits lexically inside a traced function."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            if cur in self.traced:
                return True
            cur = self.parent.get(cur)
        return False

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parent.get(node)
        while cur is not None and not isinstance(cur, _FUNC_NODES):
            cur = self.parent.get(cur)
        return cur

    # ------------------------------------------------------- donation map

    @staticmethod
    def _literal_tuple(kws: List[ast.keyword], name: str,
                       want: type) -> Tuple[Any, ...]:
        """Literal value of keyword ``name`` coerced to a tuple of
        ``want``; () when absent or not statically evaluable."""
        for kw in kws:
            if kw.arg != name:
                continue
            try:
                val = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                return ()
            if isinstance(val, want):
                return (val,)
            try:
                return tuple(want(v) for v in val)
            except (TypeError, ValueError):
                return ()
        return ()

    @classmethod
    def _jit_kw_info(cls, kws: List[ast.keyword],
                     target: Optional[str]) -> Dict[str, Any]:
        return {
            "donate": cls._literal_tuple(kws, "donate_argnums", int),
            "static_argnums": cls._literal_tuple(kws, "static_argnums",
                                                 int),
            "static_argnames": cls._literal_tuple(kws, "static_argnames",
                                                  str),
            "target": target,
        }

    def _jit_call_keywords(self, call: ast.Call) -> List[ast.keyword]:
        """Keywords carrying jit options: the call's own, plus — for the
        ``partial(jax.jit, static_argnums=...)(f)`` spelling — the inner
        partial call's."""
        kws = list(call.keywords)
        if isinstance(call.func, ast.Call):
            kws += list(call.func.keywords)
        return kws

    def _binding_target(self, call: ast.Call) -> Optional[str]:
        """Source text of the Name/Attribute this call's result is bound
        to — directly or through ONE wrapping call (``AOTStep(jit(...))``)."""
        node: ast.AST = call
        parent = self.parent.get(node)
        if isinstance(parent, ast.Call) and node in parent.args:
            node, parent = parent, self.parent.get(parent)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            tgt = parent.targets[0]
            if isinstance(tgt, (ast.Name, ast.Attribute)):
                return ast.unparse(tgt)
        return None

    def _find_jit_bindings(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                if self._wrapper_name(node.func) != "jax.jit":
                    continue
                target = self._binding_target(node)
                if target is None:
                    continue
                wrapped = (ast.unparse(node.args[0]) if node.args
                           and isinstance(node.args[0],
                                          (ast.Name, ast.Attribute))
                           else None)
                info = self._jit_kw_info(self._jit_call_keywords(node),
                                         wrapped)
                self._record_binding(target, info)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if (self._wrapper_name(dec) == "jax.jit"
                            or (isinstance(dec, ast.Call) and
                                self._wrapper_name(dec.func) == "jax.jit")):
                        kws = (list(dec.keywords)
                               if isinstance(dec, ast.Call) else [])
                        self._record_binding(
                            node.name, self._jit_kw_info(kws, node.name))

    def _record_binding(self, name: str, info: Dict[str, Any]) -> None:
        self.jit_info[name] = info
        self.jitted_bindings.add(name)
        if info["donate"]:
            self.donations[name] = tuple(info["donate"])

    # ------------------------------------------------------------ helpers

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: "Rule", node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(rule=rule.code, path=self.path, line=line,
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message, snippet=self.snippet(line))


class Rule:
    """One hazard class. Subclasses set ``code``/``description`` and
    implement ``check`` (per-module findings) and/or ``check_graph``
    (whole-program findings off the interprocedural call graph —
    :mod:`callgraph`). A rule may have either half or both: the local
    half sees one AST, the graph half sees every module's summary plus
    the fixpoint facts (tracedness, key consumption, donation, statics)
    that flow across call and module boundaries."""

    code: str = ""
    description: str = ""

    def check(self, module: Module) -> Iterator[Finding]:
        return iter(())

    def check_graph(self, graph: Any) -> Iterator[Finding]:
        """Findings provable only with the whole-program call graph
        (a :class:`callgraph.CallGraph`). Default: none."""
        return iter(())

    def run(self, module: Module) -> List[Finding]:
        try:
            return list(self.check(module))
        except RecursionError:  # pathological nesting: skip, don't crash
            return []

    def run_graph(self, graph: Any) -> List[Finding]:
        try:
            return list(self.check_graph(graph))
        except RecursionError:  # pragma: no cover - defensive
            return []


_REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    assert cls.code and cls.code not in _REGISTRY, cls
    _REGISTRY[cls.code] = cls
    return cls


def all_rules() -> List[Rule]:
    return [cls() for _, cls in sorted(_REGISTRY.items())]


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    seen = set()
    for p in paths:
        if os.path.isfile(p):
            files: Iterable[str] = [p]
        else:
            files = (os.path.join(root, f)
                     for root, dirs, names in os.walk(p)
                     if "__pycache__" not in root
                     for f in sorted(names) if f.endswith(".py"))
        for f in files:
            key = os.path.abspath(f)
            if key not in seen:
                seen.add(key)
                yield f


def _assign_indices(findings: List[Finding]) -> List[Finding]:
    """Stable occurrence indices so identical lines in one file get
    distinct fingerprints (ordered by line so edits above shift nothing)."""
    out: List[Finding] = []
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        key = (f.rule, f.path, f.snippet)
        idx = counts.get(key, 0)
        counts[key] = idx + 1
        out.append(dataclasses.replace(f, index=idx))
    return out


def run_paths(paths: Iterable[str],
              rules: Optional[List[Rule]] = None,
              cache: Optional[Any] = None
              ) -> Tuple[List[Finding], int]:
    """Lint every .py under ``paths``: per-module rules file by file,
    then the whole-program call-graph pass (:mod:`callgraph`) over every
    module's summary, so tracedness/donation/static-argnum/key facts
    flow across module boundaries. Returns (findings, files_checked).

    ``cache`` (an :class:`cache.AnalysisCache`) skips the parse and the
    per-module rules for files whose content hash is unchanged — the
    cached entry carries the module SUMMARY the graph pass consumes, so
    cross-module findings stay exact (they are recomputed from the
    summaries every run; only the per-file work is memoized).

    Unparseable files surface as ``parse-error`` findings (they gate —
    code the analyzer cannot read is code nothing can vouch for)."""
    from . import callgraph

    rules = rules if rules is not None else all_rules()
    codes = sorted(r.code for r in rules)
    findings: List[Finding] = []
    summaries: Dict[str, Any] = {}
    n = 0
    for path in iter_py_files(paths):
        n += 1
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
        except OSError as e:
            findings.append(Finding(
                rule="GL000-parse-error", path=path, line=1, col=1,
                message=f"could not parse: {e}", snippet=""))
            continue
        sha = hashlib.sha1(raw).hexdigest()
        entry = cache.get(path, sha, codes) if cache is not None else None
        if entry is not None:
            try:
                summary = callgraph.ModuleSummary.from_dict(
                    entry["summary"])
                cached = [Finding(**{**f, "path": path})
                          for f in entry["findings"]
                          if f["rule"] in codes
                          or f["rule"] == "GL000-parse-error"]
            except (KeyError, TypeError, ValueError):
                # old-schema or garbled entry: degrade to a cold
                # re-summarize below, never a crash
                entry = None
            else:
                # the entry may have been written under a different path
                # SPELLING (relative CLI run vs absolute gate run);
                # re-key to this run's spelling so graph fids and report
                # paths agree
                summary.path = path
                summaries[path] = summary
                findings.extend(cached)
                continue
        try:
            module = Module(path, raw.decode("utf-8"))
        except (SyntaxError, UnicodeDecodeError, ValueError) as e:
            findings.append(Finding(
                rule="GL000-parse-error", path=path,
                line=getattr(e, "lineno", None) or 1, col=1,
                message=f"could not parse: {e}", snippet=""))
            continue
        local: List[Finding] = []
        for rule in rules:
            local.extend(rule.run(module))
        summary = callgraph.summarize_module(module)
        summaries[path] = summary
        findings.extend(local)
        if cache is not None:
            cache.put(path, sha, codes, summary.to_dict(),
                      [dataclasses.asdict(f) for f in local])
    graph = callgraph.CallGraph(summaries)
    for rule in rules:
        findings.extend(rule.run_graph(graph))
    if cache is not None:
        cache.save()
    return _assign_indices(findings), n
