"""Auto-tuner settings (``run/tune.py``).

Same declarative surface as training/serving: every field is a
``--flag``, round-trips through JSON, documents itself in ``--help``.
The knobs mirror the tuner's layers — the model/shape under tune, the
search space (mesh axes, rule-table mutations, ZeRO toggle), the
measurement geometry (screen window, ABBA finals), and the wall-clock
budget + journal/artifact locations.
"""

from __future__ import annotations

from typing import Literal

from .base import ArgparseCompatibleBaseModel as S
from .base import item as _


class TuneSettings(S):
    """Profile-guided layout search for a model/shape on a device set."""

    family: str = _("diffuseq", "model families to tune, comma-separated "
                                "(e.g. 'diffuseq,gpt2'): each family runs "
                                "its own search into the shared journal "
                                "and emits its own artifact")
    model_size: str = _("base", "preset size")
    seq_len: int = _(128, "sequence length")
    vocab_size: int = _(8192, "vocabulary size")
    hidden_size: int = _(0, "override hidden size; 0 = preset")
    num_layers: int = _(0, "override layer count; 0 = preset")
    num_heads: int = _(0, "override head count; 0 = preset")
    dtype: Literal["bfloat16", "float32"] = _("float32",
                                              "activation/compute dtype")
    batch_size: int = _(8, "per-host batch size measured")
    microbatch: int = _(0, "microbatch per optimizer step; 0 = batch")

    n_devices: int = _(0, "device count to tune for: 0 = all visible "
                          "devices; off-TPU the measurement children are "
                          "FORCED to this many host CPU devices "
                          "(xla_force_host_platform_device_count), so a "
                          "one-core box still tunes a dp=2 mesh")
    axes: str = _("data,fsdp,tensor", "mesh axes the search factorizes "
                                      "the device count over (sequence/"
                                      "expert/pipe change step semantics "
                                      "and stay out of the default space)")
    include_zero1: bool = _(True, "search the --shard_optimizer (ZeRO-1) "
                                  "toggle per candidate (only where the "
                                  "data axis is > 1)")
    max_candidates: int = _(0, "cap the enumerated candidate list "
                               "(baseline-first, so the hand-tuned "
                               "reference always survives the cap); "
                               "0 = no cap")

    peak_bytes_ceiling: float = _(
        0.0, "memory-headroom objective (ISSUE 14 satellite; the r15 "
             "NOTE's unwired ranking input): candidates whose measured "
             "peak_live_bytes exceed this ceiling are RANKED OUT — "
             "journaled as over_ceiling with accounting still closed "
             "(measured + pruned + rejected + skipped + over_ceiling == "
             "enumerated) and never a winner. 0 disables. The xl "
             "presets' path onto bigger meshes: the fastest layout that "
             "does not fit is not a layout")
    budget_s: float = _(240.0, "wall-clock budget for the whole tune: "
                               "candidates the budget cannot afford are "
                               "journaled as skipped and the ranking "
                               "proceeds on what WAS measured")
    screen_steps: int = _(4, "timed steps per screen (rung-0) trial; "
                             "halving rungs double it")
    warmup_steps: int = _(2, "child warmup steps before the timed window "
                             "(the first pays the compile)")
    final_rounds: int = _(6, "ABBA rounds for the top-2 final (forced "
                             "even: position balance)")
    final_window_steps: int = _(4, "steps per ABBA window in the final")
    screen_only: bool = _(False, "stop after the screen rung (no halving "
                                 "or finals): the cheap mode --auto_tune "
                                 "and the bench leg run")
    child_timeout_s: float = _(150.0, "hard cap per measurement child; a "
                                      "wedged candidate folds to a pruned "
                                      "row at this deadline")

    out_dir: str = _("model_checkpoints/tune", "journal + artifact "
                                               "directory")
    resume: bool = _(True, "replay completed trials from an existing "
                           "tune_trials.jsonl instead of re-measuring "
                           "them (an interrupted tune continues); false "
                           "wipes the journal first")
    trace: bool = _(False, "span tracing (obs/): book one span per trial "
                           "into trace_tune.jsonl in out_dir, exportable "
                           "to the Perfetto timeline (DPT_TRACE arms it "
                           "too); journaled trials also export without "
                           "tracing, from tune_trials.jsonl itself")
    seed: int = _(0, "measurement seed (the children's data/init seed)")
