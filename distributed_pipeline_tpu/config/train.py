"""Concrete training settings.

Parity with the reference schema (``/root/reference/config/train.py:6-80``):
``GeneralSettings`` (optimizer/loop hyperparameters, identical defaults),
``DataSettings``, and a composed ``TrainSettings`` whose argparse adds a
mutually-exclusive ``--config_json`` that overrides the whole CLI
(reference train.py:57-77).

Where the reference leaves ``YourSettings`` as an empty stub (train.py:44-46),
this framework fills it with the concrete TPU workload settings:
``ModelSettings`` (DiffuSeq diffusion / GPT-2 causal-LM families) and
``MeshSettings`` (device-mesh axis sizes for data/fsdp/tensor/sequence
parallelism — the TPU-native replacement for DDP process groups).
"""

from __future__ import annotations

import argparse
from typing import Literal, Optional

from .base import ArgparseCompatibleBaseModel as S
from .base import item as _


class GeneralSettings(S):
    """Optimizer and loop hyperparameters (reference config/train.py:6-32)."""

    lr: float = _(1e-4, "learning rate")
    batch_size: int = _(2048, "per-host batch size; global = batch_size * num_hosts "
                              "(reference semantics, trainer.py:89)")
    microbatch: int = _(64, "microbatch size per optimizer step; -1 = batch_size")
    learning_steps: int = _(320000, "total optimizer steps")
    log_interval: int = _(50, "steps between metric dumps")
    save_interval: int = _(10000, "steps between checkpoints")
    eval_interval: int = _(1000, "steps between eval passes")
    ema_rate: str = _("0.5,0.9,0.99", "comma-separated EMA decay rates")
    seed: int = _(102, "global RNG seed")
    resume_checkpoint: str = _("", "explicit checkpoint path to resume from")
    checkpoint_path: str = _("", "run/checkpoint directory (auto-generated if empty)")
    gradient_clipping: float = _(-1.0, "global-norm gradient clip; <=0 disables")
    weight_decay: float = _(0.0, "AdamW decoupled weight decay")
    warmup_steps: int = _(0, "linear LR warmup steps before the anneal "
                             "(0 = reference behavior: no warmup)")
    keep_checkpoints: int = _(0, "retain only the newest N checkpoint steps "
                                 "(model+EMA+opt pruned together); 0 = keep "
                                 "all (reference behavior)")
    debug_nans: bool = _(False, "enable jax_debug_nans: fail loudly at the op "
                                "that first produces a NaN (debug runs only; "
                                "disables async dispatch)")
    eval_decode: bool = _(False, "decode a validation batch at every eval "
                                 "interval and log decode_acc (DiffuSeq "
                                 "reverse diffusion / GPT-2 greedy)")
    eval_decode_sample_steps: int = _(32, "reverse-diffusion steps for "
                                         "eval decoding (diffuseq only)")
    profile_dir: str = _("", "capture a jax.profiler trace of a few steps "
                             "into this directory (TensorBoard format)")
    profile_steps: str = _("", "jax.profiler capture window as 'A:B' loop "
                               "steps counted from loop entry (with "
                               "--profile_dir; empty = the default 3:8 "
                               "window past compilation) — the XLA-level "
                               "view next to the obs/ span timeline")
    trace: bool = _(False, "span tracing (obs/): book step/save/restore/"
                           "compile/eval spans into trace_rank{k}.jsonl "
                           "in the run dir, exportable to a Perfetto "
                           "timeline with python -m "
                           "distributed_pipeline_tpu.obs.export; the "
                           "DPT_TRACE env arms it too (reaches every "
                           "worker of a launcher ring, incl. "
                           "--config_json runs); off = zero-cost no-op")
    cost_ledger: bool = _(False, "per-compiled-program cost ledger (obs/"
                                 "ledger.py): extract XLA's FLOPs/bytes "
                                 "accounting + an HLO collective-bytes "
                                 "tally off the AOT step executables and "
                                 "log the roofline MFU-gap attribution "
                                 "(mfu_gap_host/comms/memory_bound/"
                                 "residual, collective_bytes_per_step, "
                                 "padding_waste_frac) each log window, "
                                 "snapshotted to <run_dir>/"
                                 "perf_ledger.json (read by run/"
                                 "perf_report.py, run/status.py, and "
                                 "obs/export.py counter tracks)")
    sanitize: bool = _(False, "runtime sanitizer mode: count every XLA "
                              "compile into a recompile_count gauge "
                              "(jax_log_compiles) and disallow implicit "
                              "host<->device transfers inside the train/"
                              "eval step dispatch — the dynamic half of "
                              "the graftlint static pass (python -m "
                              "distributed_pipeline_tpu.analysis); cheap "
                              "enough for CI runs")
    compilation_cache_dir: str = _(
        "auto", "persistent XLA compilation-cache directory: 'auto' = "
                "<run_dir>/compile_cache (restarts/resumes of the run "
                "recompile nothing), 'off' disables, else an explicit dir "
                "shared across runs; exported to spawned workers as "
                "JAX_COMPILATION_CACHE_DIR")
    prefetch_depth: int = _(
        2, "device-side input prefetch depth: keep N batches already "
           "device_put onto the mesh (with the compiled step's sharding) "
           "while the current step runs, so the TPU never waits on the "
           "host transfer; 2 = classic double buffering, 0 disables "
           "(exact-resume data order is identical either way)")
    dispatch_lag: int = _(
        1, "async metrics dispatch: fetch/log step N-k's device scalars "
           "while step N dispatches instead of blocking on the step just "
           "enqueued; logged values are exact, just k steps late (flushed "
           "at eval/checkpoint/exit boundaries); 0 = eager")
    chaos_plan: str = _(
        "", "fault-injection schedule (chaos harness): inline JSON, "
            "@/path/to/plan.json, or a bare path — faults like "
            '{"kind": "kill", "step": N, "rank": R} / crash_in_save / '
            "stall_data / stall_step (wedge the step loop alive — the "
            "hang the launcher's --hang_timeout_s watchdog detects) / "
            "slow_rank (straggler: seconds delay per step through "
            "until_step — must NOT trip the watchdog) / "
            "corrupt_checkpoint fire at exact optimizer "
            "steps to prove the restart+resume stack survives them; the "
            "DPT_CHAOS_PLAN env var overrides (it reaches --config_json "
            "ring workers like DPT_PREFETCH_DEPTH does); empty disables")


class DataSettings(S):
    """Dataset selection (reference config/train.py:35-41)."""

    dataset: str = _("synthetic-seq2seq", "dataset name")
    data_dir: str = _("", "dataset directory (empty = synthetic data)")
    data_loader_workers: int = _(2, "host-side loader worker threads")


class ModelSettings(S):
    """Workload settings — fills the reference's ``YourSettings`` stub
    (config/train.py:44-46) with the concrete DiffuSeq/GPT-2 families."""

    model_family: Literal["diffuseq", "gpt2"] = _("diffuseq", "model family")
    model_size: Literal["base", "large", "xl", "medium"] = _("base", "preset size")
    vocab_size: int = _(8192, "vocabulary size")
    seq_len: int = _(128, "sequence length (source+target for seq2seq)")
    hidden_size: int = _(0, "override hidden size; 0 = use preset")
    num_layers: int = _(0, "override layer count; 0 = use preset")
    num_heads: int = _(0, "override head count; 0 = use preset")
    diffusion_steps: int = _(2000, "diffusion timesteps (diffuseq only)")
    noise_schedule: Literal["sqrt", "cosine", "linear"] = _(
        "sqrt", "diffusion noise schedule (diffuseq only)"
    )
    dtype: Literal["bfloat16", "float32"] = _("bfloat16", "activation/compute dtype")
    remat: bool = _(False, "rematerialize (jax.checkpoint) each block")
    attention_impl: Literal["auto", "xla", "pallas", "ring"] = _(
        "auto", "attention kernel: XLA dot-product, pallas flash, or ring (SP)"
    )
    moe_experts: int = _(0, "mixture-of-experts: expert count (0 = dense MLPs)")
    moe_top_k: int = _(2, "MoE router top-k")
    moe_every: int = _(2, "MoE replaces the MLP in every k-th block")
    moe_capacity_factor: float = _(
        1.25, "MoE expert capacity = ceil(L/E * factor * top_k) slots; "
        "tokens over capacity fall through on the residual path")
    scan_layers: bool = _(False, "stacked layer weights (lax.scan over "
                                 "blocks; enables pipeline parallelism and "
                                 "fast compiles for deep models)")
    pp_chunks: int = _(4, "GPipe microchunks per per-shard batch "
                          "(pipeline parallelism; bubble = (S-1)/(chunks+S-1))")
    scan_unroll: int = _(
        0, "scan_layers unroll factor: 0 auto-unrolls stacks of <= 16 "
           "layers fully (restores unrolled-graph fusion the scan backward "
           "loses; ~6x compile time) and keeps longer stacks as true "
           "scans; N forces a factor (1 or full recommended — partial "
           "factors measured pathological on TPU)")
    pp_schedule: Literal["1f1b", "gpipe", "interleaved"] = _(
        "1f1b", "pipeline training schedule: 1f1b streams each chunk's "
                "backward as soon as its forward clears the last stage "
                "(peak stash <= 2S-1 chunks, so pp_chunks can grow to "
                "shrink the bubble); interleaved additionally splits each "
                "device into pp_virtual non-contiguous stage slices, "
                "cutting the bubble ~Vx at the cost of V*min(M,3S) "
                "stashed chunks and a per-step weight permute; gpipe "
                "differentiates through the forward-only schedule "
                "(simpler, but activation residuals scale with pp_chunks)")
    pp_virtual: int = _(
        2, "virtual stage slices per device under "
           "--pp_schedule interleaved (bubble ~ (S-1)/(V*M+S-1); "
           "num_layers must divide by pipe * pp_virtual, pp_chunks by "
           "pipe)")


class MeshSettings(S):
    """Device-mesh axes — the TPU-native replacement for the reference's DDP
    process group (utils/trainer.py:115-128). Axis size -1 means "all
    remaining devices"; 1 disables the axis."""

    dp: int = _(-1, "data-parallel axis size (-1 = all remaining devices)")
    fsdp: int = _(1, "FSDP/zero param-sharding axis size")
    tensor: int = _(1, "tensor-parallel axis size")
    sequence: int = _(1, "sequence/context-parallel axis size (ring attention)")
    expert: int = _(1, "expert-parallel axis size (MoE expert sharding)")
    pipe: int = _(1, "pipeline-parallel axis size (GPipe stage streaming; "
                     "requires --scan_layers true)")
    shard_optimizer: bool = _(
        False, "ZeRO-1 cross-replica weight-update sharding: Adam moments "
               "and EMA copies sharded across the data mesh axis with "
               "gather-on-use inside the compiled train step — per-replica "
               "optimizer/EMA memory drops ~dp x at unchanged step math "
               "(params/grads keep their layout; checkpoints restore "
               "across the flag in either direction)")
    fused_update: str = _(
        "auto", "fused optimizer+EMA Pallas kernel (ops/fused_update.py): "
                "one pass per param leaf reads param/grad/mu/nu and writes "
                "param/mu/nu plus every EMA copy, replacing the staged "
                "optax chain that re-reads the tree once per state copy; "
                "losses bit-identical, opt_state structure unchanged "
                "(checkpoints and --shard_optimizer compose either way). "
                "auto (default) = fused on TPU, staged optax elsewhere "
                "(off-TPU the kernel only has interpreter mode, which is "
                "pure overhead); true/false force an arm")
    partition_rules: str = _(
        "", "override the model's parameter partition-rule table "
            "(parallel/partition.py): inline JSON, @/path.json, or a bare "
            "path — an ordered list of [path-regex, spec] pairs, spec a "
            "list of mesh-axis names / null / nested list (several axes "
            "on one dim), ending with an explicit catch-all ['.*', []]; "
            "a TUNER ARTIFACT (run/tune.py) is accepted verbatim — its "
            "rules always apply, and its mesh/ZeRO recommendations apply "
            "when the mesh flags are still at their defaults; empty = "
            "the model family's built-in table")
    auto_tune: bool = _(
        False, "run the sharding auto-tuner's SCREEN inline before "
               "training (tune/): rank 0 measures candidate rule tables "
               "x mesh splits for this exact model/shape/device set in "
               "child processes under --auto_tune_budget_s, writes the "
               "winner to <run_dir>/tune_artifact.json, and the run "
               "consumes it like --partition_rules (mesh/ZeRO "
               "recommendations apply only when those flags are still "
               "at their defaults); a restart attempt reuses the "
               "existing artifact instead of re-tuning; ignored when "
               "--partition_rules is set explicitly")
    auto_tune_budget_s: float = _(
        60.0, "wall-clock budget for the inline --auto_tune screen "
              "(candidates it cannot afford are skipped; the baseline "
              "table is measured first so a tiny budget degrades to "
              "the hand-tuned layout)")

    # --------------------------------------------------- MPMD (ISSUE 16)
    mpmd: bool = _(
        False, "MPMD pipeline training (mpmd/): each stage runs as its "
               "OWN supervised process ring with its own restart budget "
               "and snapshots (stages are independently preemptible), a "
               "jax-free host driver broadcasts the --pp_schedule "
               "microbatch schedule, and activations/grads move over the "
               "StageLink transport instead of a collective; requires "
               "--scan_layers true; the in-program mesh axes (dp/pipe/"
               "...) apply WITHIN each stage, so keep them 1/-1 defaults "
               "unless each stage really has a sub-mesh")
    mpmd_stages: int = _(2, "MPMD stage count (process rings); "
                            "num_layers need not divide it — stages take "
                            "floor-balanced layer slices")
    mpmd_link_capacity: int = _(8, "StageLink in-flight frame cap per "
                                   "direction (backpressure: a sender "
                                   "blocks past this and books the wait "
                                   "as link_wait)")
    mpmd_hang_timeout_s: float = _(0.0, "per-stage beacon watchdog: a "
                                        "stage whose beacons freeze this "
                                        "long is SIGKILLed and restarted "
                                        "by ITS OWN ring (0 = off)")
    mpmd_max_restarts: int = _(3, "per-stage restart budget (sliding "
                                  "window, launcher semantics)")


class TrainSettings(GeneralSettings, DataSettings, ModelSettings, MeshSettings):
    """Composed settings, flat like the reference's reverse-MRO composition
    (config/train.py:49-55): every field addressable as a top-level CLI flag."""

    @classmethod
    def to_argparse(cls, parser=None, add_json: bool = False, **kw):  # type: ignore[override]
        parser = super().to_argparse(parser, **kw)
        if add_json:
            parser.add_argument(
                "--config_json",
                default=None,
                help="JSON config file; mutually exclusive with individual flags "
                "(overrides the entire CLI, reference config/train.py:57-68)",
            )
        return parser

    @classmethod
    def from_argparse(cls, namespace: argparse.Namespace, _consume: bool = True):  # type: ignore[override]
        parsed_argv = vars(namespace).pop("_parsed_argv", "absent")
        config_json = vars(namespace).pop("config_json", None)
        if config_json:
            # True mutual exclusivity (reference's mutually-exclusive group,
            # config/train.py:63-67): a flag explicitly set to its default
            # value still conflicts. Only an argv explicitly recorded on the
            # namespace (by from_argv / parse_and_autorun) is inspected —
            # never the hosting process's sys.argv, whose flags may belong
            # to a wrapper script, not this parse. Programmatic namespaces
            # without a recorded argv fall back to value-vs-default drift.
            import sys
            if parsed_argv == "absent" or parsed_argv is None:
                argv = []
            else:
                argv = parsed_argv
            fields = set(cls.model_fields)
            explicit = sorted({
                tok.split("=")[0].lstrip("-") for tok in argv
                if tok.startswith("--")
                and tok.split("=")[0].lstrip("-") in fields})
            defaults = cls()
            drifted = [
                k for k, v in vars(namespace).items()
                if hasattr(defaults, k) and getattr(defaults, k) != v
            ]
            overridden = sorted(set(explicit) | set(drifted))
            if overridden:
                raise SystemExit(
                    f"--config_json is mutually exclusive with individual flags "
                    f"(got: {', '.join('--' + k for k in overridden)})"
                )
            return cls.parse_file(config_json)
        return super().from_argparse(namespace, _consume=_consume)


class YourSettings(S):
    """Kept for reference-API familiarity (config/train.py:44-46); the real
    workload settings live in :class:`ModelSettings`/:class:`MeshSettings`."""


if __name__ == "__main__":
    # Reference README.md:18-21 one-liner equivalent: dump default config JSON.
    print(TrainSettings().to_json())
