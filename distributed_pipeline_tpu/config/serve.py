"""Serving settings (``run/serve.py``).

Same declarative config surface as training (config/base.py): every field
is a ``--flag``, round-trips through JSON, and documents itself in
``--help``. The knobs mirror the serving stack's layers — engine geometry
(slots/pages/lengths), sampling, workload (prompt file or synthetic
arrival process), and the sanitizer switch.
"""

from __future__ import annotations

from .base import ArgparseCompatibleBaseModel as S
from .base import item as _


class ServeSettings(S):
    """Continuous-batching decode service over a trained run directory."""

    checkpoint_path: str = _(..., "run directory written by run.train")
    step: int = _(0, "checkpoint step to load (0 = newest)")
    ema: str = _("", "EMA rate to serve (e.g. 0.99); empty = raw params")

    decode_slots: int = _(8, "compiled decode batch size: decode always "
                             "runs at this many slots (inactive slots are "
                             "masked), so the executable never "
                             "re-specializes to occupancy")
    page_size: int = _(16, "tokens per KV-cache page")
    max_pages: int = _(0, "total pages in the per-layer KV pool (incl. the "
                          "reserved trash page); 0 = full residency "
                          "(decode_slots * ceil(max_len/page_size) + 1). "
                          "Smaller pools admit fewer concurrent long "
                          "requests instead of OOMing")
    max_prompt_len: int = _(0, "compiled prefill length — prompts pad up "
                               "to it (0 = max_len/2)")
    max_len: int = _(0, "longest prompt+generation per slot "
                        "(0 = the model's seq_len)")
    max_new_tokens: int = _(64, "generation budget per request")
    prefill_batch: int = _(0, "prompts prefilled per admission dispatch "
                              "(0 = min(decode_slots, 8))")
    decode_span: int = _(4, "tokens generated per decode dispatch (a "
                            "lax.scan inside the executable): amortizes "
                            "host dispatch over span tokens; admission "
                            "happens at span granularity and a request "
                            "ending mid-span wastes up to span-1 "
                            "slot-steps")
    dispatch_lag: int = _(2, "decode dispatches kept in flight before the "
                             "host fetches tokens: bookkeeping overlaps "
                             "device execution; EOS detection lags by "
                             "this many dispatches")

    temperature: float = _(0.0, "0 = greedy; > 0 samples")
    top_k: int = _(0, "restrict sampling to the k most likely tokens")
    top_p: float = _(0.0, "nucleus sampling mass (0 = off)")
    seed: int = _(0, "sampling seed")
    eos_id: int = _(-1, "finish a request early at this token id (-1 = "
                        "off; observed one lagged step late)")

    prompt_file: str = _("", "JSONL requests, one {\"prompt_ids\": [...]} "
                             "per line (optional \"max_new_tokens\"); "
                             "empty = synthetic workload")
    synthetic_requests: int = _(32, "synthetic workload: request count")
    synthetic_prompt_len: int = _(0, "synthetic prompt length "
                                     "(0 = max_prompt_len)")
    arrival_every_steps: int = _(0, "synthetic arrival process: enqueue "
                                    "one request every N scheduler steps "
                                    "(0 = all queued at start)")
    out: str = _("", "write per-request JSONL results here")
    sanitize: bool = _(False, "runtime sanitizer: count XLA compiles "
                              "(recompile_count must stay 0 in steady "
                              "state — prefill/decode compile exactly "
                              "once) and disallow implicit host<->device "
                              "transfers during dispatch")
