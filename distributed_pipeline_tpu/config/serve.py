"""Serving settings (``run/serve.py``).

Same declarative config surface as training (config/base.py): every field
is a ``--flag``, round-trips through JSON, and documents itself in
``--help``. The knobs mirror the serving stack's layers — engine geometry
(slots/pages/lengths), sampling, workload (prompt file or an arrival
process), the sanitizer switch, and (ISSUE 11) the multi-replica fleet:
traffic process, router health gates, per-replica supervision, and
checkpoint hot-swap.
"""

from __future__ import annotations

from typing import Literal

from .base import ArgparseCompatibleBaseModel as S
from .base import item as _


class ServeSettings(S):
    """Continuous-batching decode service over a trained run directory."""

    checkpoint_path: str = _(..., "run directory written by run.train")
    step: int = _(0, "checkpoint step to load (0 = newest)")
    ema: str = _("", "EMA rate to serve (e.g. 0.99); empty = raw params")

    decode_slots: int = _(8, "compiled decode batch size: decode always "
                             "runs at this many slots (inactive slots are "
                             "masked), so the executable never "
                             "re-specializes to occupancy")
    page_size: int = _(16, "tokens per KV-cache page")
    max_pages: int = _(0, "total pages in the per-layer KV pool (incl. the "
                          "reserved trash page); 0 = full residency "
                          "(decode_slots * ceil(max_len/page_size) + 1). "
                          "Smaller pools admit fewer concurrent long "
                          "requests instead of OOMing")
    max_prompt_len: int = _(0, "compiled prefill length — prompts pad up "
                               "to it (0 = max_len/2)")
    max_len: int = _(0, "longest prompt+generation per slot "
                        "(0 = the model's seq_len)")
    max_new_tokens: int = _(64, "generation budget per request")
    prefill_batch: int = _(0, "prompts prefilled per admission dispatch "
                              "(0 = min(decode_slots, 8))")
    decode_span: int = _(4, "tokens generated per decode dispatch (a "
                            "lax.scan inside the executable): amortizes "
                            "host dispatch over span tokens; admission "
                            "happens at span granularity and a request "
                            "ending mid-span wastes up to span-1 "
                            "slot-steps")
    dispatch_lag: int = _(2, "decode dispatches kept in flight before the "
                             "host fetches tokens: bookkeeping overlaps "
                             "device execution; EOS detection lags by "
                             "this many dispatches")

    temperature: float = _(0.0, "0 = greedy; > 0 samples")
    top_k: int = _(0, "restrict sampling to the k most likely tokens")
    top_p: float = _(0.0, "nucleus sampling mass (0 = off)")
    seed: int = _(0, "sampling seed")
    eos_id: int = _(-1, "finish a request early at this token id (-1 = "
                        "off; observed one lagged step late)")

    prompt_file: str = _("", "JSONL requests, one {\"prompt_ids\": [...]} "
                             "per line (optional \"max_new_tokens\"); "
                             "empty = synthetic workload")
    synthetic_requests: int = _(32, "synthetic workload: request count")
    synthetic_prompt_len: int = _(0, "synthetic prompt length "
                                     "(0 = max_prompt_len)")
    arrival_every_steps: int = _(0, "legacy step-cadence arrival knob "
                                    "(traffic='steps' only): enqueue one "
                                    "request every N scheduler steps "
                                    "(0 = all queued at start)")
    out: str = _("", "write per-request JSONL results here")
    cost_ledger: bool = _(False, "per-executable cost ledger (obs/"
                                 "ledger.py): extract FLOPs/bytes/"
                                 "collective accounting off the prefill/"
                                 "decode AOT executables and attach the "
                                 "decode roofline MFU-gap attribution "
                                 "(+ prompt-padding / slot-occupancy "
                                 "waste) to the summary JSON")
    sanitize: bool = _(False, "runtime sanitizer: count XLA compiles "
                              "(recompile_count must stay 0 in steady "
                              "state — prefill/decode compile exactly "
                              "once) and disallow implicit host<->device "
                              "transfers during dispatch")
    decode_impl: Literal["auto", "pallas", "xla"] = _(
        "auto", "decode-step attention kernel (ops/flash_decode.py): "
                "'pallas' streams K/V pages straight from the paged pool "
                "through a flash-decode kernel (no gathered copy); 'xla' "
                "is the gather+dot reference; 'auto' picks pallas on TPU "
                "and xla elsewhere")
    kv_quant: Literal["fp", "int8"] = _(
        "fp", "paged KV pool storage (ISSUE 20): 'int8' quantizes K/V at "
              "page granularity with [P] fp32 per-page scales — pool "
              "bytes drop ~4x (f32) / ~2x (bf16), so decode slots and "
              "prefix-cache capacity double at fixed HBM; decode logits "
              "carry the documented divergence bound instead of "
              "bit-identity (prefill logits are unchanged)")
    spec_tokens: int = _(0, "speculative decoding (ISSUE 20): draft K "
                            "tokens per round and verify them in ONE "
                            "target dispatch; greedy output is token-"
                            "identical to the non-speculative path. "
                            "0 = off")
    spec_draft: Literal["ngram", "model"] = _(
        "ngram", "draft source: 'ngram' = host-side prompt-lookup "
                 "(zero model flops — the CPU-friendly arm); 'model' = "
                 "early-exit engine over the target's first draft_layers "
                 "blocks (weights shared, no training)")
    draft_layers: int = _(2, "spec_draft='model': how many leading target "
                             "blocks the draft model keeps")
    serve_quant: Literal["off", "int8"] = _(
        "off", "quantize replica WEIGHTS at load and at every hot-swap "
               "restore (serving/quantize.py): int8 storage round-trip "
               "with per-channel scales and a round-trip error guard — "
               "a corrupt/pathological checkpoint raises inside the "
               "worker, so the hot-swap canary aborts instead of the "
               "fleet taking bad weights")
    prefix_cache: bool = _(False, "shared-prefix KV page reuse: requests "
                                  "whose prompts open with the same token "
                                  "run share the paged-KV pages holding "
                                  "that prefix (refcounted; evicted LRU "
                                  "under pool pressure)")
    trace: bool = _(False, "span tracing (obs/): replicas book per-request "
                           "serve spans (router-propagated trace ids), "
                           "engine prefill/decode spans, and hot-swap "
                           "drain/load windows into per-replica "
                           "trace_rank0.jsonl shards; export the whole "
                           "fleet as ONE Perfetto timeline with python -m "
                           "distributed_pipeline_tpu.obs.export "
                           "<fleet_dir>; DPT_TRACE arms it too; off = "
                           "zero-cost no-op")

    # ------------------------------------------------- traffic (ISSUE 11)
    traffic: Literal["steps", "poisson", "bursty", "diurnal"] = _(
        "steps", "arrival process: 'steps' keeps the legacy "
                 "scheduler-step cadence; poisson/bursty/diurnal are "
                 "seeded wall-clock processes (serving/traffic.py) — "
                 "same seed, same schedule, every process")
    rate_rps: float = _(8.0, "mean arrival rate (requests/second) for the "
                             "wall-clock traffic processes")
    burst_every_s: float = _(2.0, "bursty traffic: seconds between bursts")
    burst_size: int = _(8, "bursty traffic: arrivals per burst")
    diurnal_period_s: float = _(30.0, "diurnal traffic: ramp period "
                                      "(a compressed day/night cycle)")
    diurnal_floor: float = _(0.2, "diurnal traffic: trough rate as a "
                                  "fraction of rate_rps")
    shared_prefix_len: int = _(0, "synthetic prompts open with this many "
                                  "SHARED tokens (the prefix-cache "
                                  "workload; 0 = fully random prompts)")

    # --------------------------------------------------- fleet (ISSUE 11)
    replicas: int = _(0, "serve through a fleet of N replicas (each its "
                         "own supervised worker process behind the "
                         "request router) instead of one in-process "
                         "server; 0 = single-replica legacy path")
    fleet_dir: str = _("", "fleet working dir (journal + per-replica "
                           "run dirs); empty = <checkpoint_path>/fleet")
    fleet_worker_dir: str = _("", "INTERNAL: run as a fleet replica "
                                  "worker against this replica dir "
                                  "(set by the fleet supervisor)")
    replica_id: int = _(-1, "INTERNAL: this worker's replica index")
    replica_platform: str = _(
        "auto", "jax backend the replica workers pin (ISSUE 13 "
                "satellite): 'auto' inherits the PARENT's platform "
                "(JAX_PLATFORMS in the fleet parent's environment — cpu "
                "under the test/dev rings, unset on a TPU host so "
                "replicas see the real chips); 'cpu' forces the dev-ring "
                "behavior (fake devices, remote plugin disabled); any "
                "other value pins that platform; '' = never pin")
    hang_timeout_s: float = _(10.0, "per-replica hang watchdog: a replica "
                                    "whose beacons freeze this long is "
                                    "SIGKILLed and its in-flight requests "
                                    "replay on a sibling; must exceed the "
                                    "slowest legitimate tick + swap-"
                                    "restore gap. 0 disables")
    fleet_max_restarts: int = _(3, "per-replica restart budget (sliding "
                                   "window, launcher semantics)")
    fleet_backoff_s: float = _(0.25, "per-replica restart backoff base")
    stale_beacon_s: float = _(10.0, "router health gate: stop placing NEW "
                                    "requests on a replica whose newest "
                                    "beacon is older than this")
    fleet_deadline_s: float = _(300.0, "hard wall-clock cap on the fleet "
                                       "run; anything unfinished is "
                                       "reported dropped (acceptance "
                                       "is zero)")
    chaos_plan: str = _("", "serving chaos schedule (JSON / @file; kinds "
                            "kill_replica / stall_replica / "
                            "corrupt_swap_checkpoint); also honors the "
                            "DPT_CHAOS_PLAN env like training")
    serve_transport: Literal["file", "socket"] = _(
        "file", "replica data-plane transport (ISSUE 17): 'file' = "
                "atomic-rename mailboxes + beacon-mtime liveness (the "
                "proven single-host default); 'socket' = length-prefixed "
                "JSON frames over TCP + heartbeat liveness (replicas can "
                "live on other hosts). The ctrl plane (ready/swap/stop/"
                "beacons) stays file-based either way, so hot-swap, the "
                "hang watchdog and goodput accounting are identical")
    route_affinity: bool = _(
        False, "prefix-affinity routing: place each request on the "
               "replica whose advertised prefix-cache index matches the "
               "most leading page-aligned prompt blocks (falls back to "
               "least-loaded on ties/cold prefixes); pair with "
               "--prefix_cache for the fleet-wide cache win")

    # ----------------------------------------------- autoscale (ISSUE 17)
    autoscale: bool = _(
        False, "SLO-driven autoscaler (serving/autoscale.py): grow the "
               "replica set when backlog/TTFT breach the SLO, shrink it "
               "via the drain path when idle; --replicas is the "
               "INITIAL size")
    autoscale_min: int = _(1, "autoscaler floor (never drain below this "
                              "many active replicas)")
    autoscale_max: int = _(0, "autoscaler ceiling (0 = the initial "
                              "--replicas count, i.e. scale-down only)")
    autoscale_slo_ttft_s: float = _(
        10.0, "the TTFT SLO target: windowed p95 above this (or backlog "
              "above autoscale_up_backlog per ready replica) scales UP")
    autoscale_up_backlog: float = _(
        2.0, "scale-up pressure threshold: pending requests per ready "
             "replica")
    autoscale_down_frac: float = _(
        0.5, "hysteresis band: scale DOWN only when backlog is zero and "
             "windowed p95 TTFT sits below down_frac * slo (strictly "
             "below the up threshold, so bursts can't flap the fleet)")
    autoscale_cooldown_s: float = _(
        5.0, "minimum seconds between structural changes (either "
             "direction)")
    autoscale_window_s: float = _(
        30.0, "trailing window over completed requests feeding the "
              "p95-TTFT signal")

    # -------------------------------------------- disaggregation (ISSUE 16)
    disagg: int = _(0, "disaggregated prefill/decode serving (mpmd/"
                       "disagg.py): the --replicas workers become PREFILL-"
                       "only workers that stream each admitted request's "
                       "paged-KV pages + first token over a StageLink to a "
                       "separately supervised DECODE ring; requests still "
                       "enter through the router. Value = decode ring "
                       "count (only 1 is supported); 0 = colocated "
                       "(every replica prefills and decodes)")
    disagg_role: str = _("", "INTERNAL: 'prefill' or 'decode' — set on the "
                             "worker argv by the disaggregated fleet parent")
    disagg_links: str = _("", "INTERNAL: StageLink directory shared by the "
                              "prefill and decode workers")
    disagg_peers: int = _(0, "INTERNAL: number of prefill workers whose "
                             "kv/tok links the decode worker polls")

    # ------------------------------------------------ hot-swap (ISSUE 11)
    swap_after_requests: int = _(0, "trigger a zero-downtime checkpoint "
                                    "hot-swap once this many requests "
                                    "have completed (0 = no swap)")
    swap_step: int = _(0, "hot-swap target step (0 = newest finalized "
                          "checkpoint at swap time)")
    drain_timeout_s: float = _(60.0, "hot-swap: max wait for one "
                                     "replica's outstanding requests to "
                                     "finish before the swap aborts")
    swap_timeout_s: float = _(120.0, "hot-swap: max wait for one replica "
                                     "to load + ack the new checkpoint")
