"""Declarative, argparse-compatible settings on top of pydantic v2.

Capability parity with the reference config bridge
(``/root/reference/config/base.py:15-87``): settings are declared once as typed
pydantic fields and can then be

* rendered into an ``argparse.ArgumentParser`` (``to_argparse``) with
  defaults-in-help, nested models as argument groups, ``Literal`` types as
  ``choices``, and lenient bool coercion (``true/false/1/0/yes/no``);
* recovered from a parsed ``argparse.Namespace`` (``from_argparse``), strictly —
  unknown keys are an error (reference asserts no leftover keys at
  ``config/base.py:30``);
* parsed straight from an argv list (``from_argv``);
* round-tripped through JSON (pydantic native) for ``--config_json`` workflows.

The implementation is new (pydantic v2, no ``exec``-generated coercers), but the
public surface — ``ArgparseCompatibleBaseModel``, aliases ``S``/``Setting``,
helpers ``choice``/``C`` and ``item``/``_`` — matches the reference so user
settings classes written against the reference port unchanged.
"""

from __future__ import annotations

import argparse
import json
import typing
from typing import Any, Iterator, Literal, Optional, Sequence, Tuple, Type, TypeVar, Union

import pydantic
from pydantic import BaseModel, ConfigDict, Field
from pydantic.fields import FieldInfo

__all__ = [
    "ArgparseCompatibleBaseModel",
    "S",
    "Setting",
    "Validator",
    "choice",
    "C",
    "item",
    "_",
    "bool_from_string",
]

_TRUE = {"true", "t", "1", "yes", "y", "on"}
_FALSE = {"false", "f", "0", "no", "n", "off"}


def bool_from_string(value: Union[str, bool]) -> bool:
    """Lenient CLI bool coercion (reference ``bool_validator``, base.py:52-53)."""
    if isinstance(value, bool):
        return value
    v = str(value).strip().lower()
    if v in _TRUE:
        return True
    if v in _FALSE:
        return False
    raise argparse.ArgumentTypeError(f"expected a boolean, got {value!r}")


def _unwrap_optional(tp: Any) -> Tuple[Any, bool]:
    """Return (inner_type, is_optional) for Optional[T] / T | None annotations."""
    origin = typing.get_origin(tp)
    if origin is Union or origin is getattr(__import__("types"), "UnionType", None):
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0], True
    return tp, False


def _is_model(tp: Any) -> bool:
    return isinstance(tp, type) and issubclass(tp, BaseModel)


class ArgparseCompatibleBaseModel(BaseModel):
    """Base class for settings that bridge pydantic <-> argparse <-> JSON."""

    model_config = ConfigDict(extra="forbid", validate_assignment=True)

    # ----------------------------------------------------------- to_argparse
    @classmethod
    def to_argparse(
        cls,
        parser: Optional[argparse.ArgumentParser] = None,
        prefix: str = "",
        group: Optional[Any] = None,
    ) -> argparse.ArgumentParser:
        """Emit ``--<field>`` arguments for every field, recursively.

        Nested ``ArgparseCompatibleBaseModel`` fields become argument groups
        titled by the field name (reference base.py:38-40). ``Literal`` fields
        become ``choices`` (base.py:44-51); bools get lenient string coercion.
        """
        if parser is None:
            # allow_abbrev=False: prefix-abbreviated flags (--log_int) would
            # dodge the --config_json mutual-exclusivity scan, which matches
            # argv tokens against exact field names (config/train.py).
            parser = argparse.ArgumentParser(
                description=cls.__doc__,
                formatter_class=argparse.ArgumentDefaultsHelpFormatter,
                allow_abbrev=False,
            )
        target = group if group is not None else parser
        for name, field in cls.model_fields.items():
            tp, _optional = _unwrap_optional(field.annotation)
            if _is_model(tp):
                sub_group = parser.add_argument_group(title=name)
                tp.to_argparse(parser, prefix=prefix, group=sub_group)
                continue
            kwargs: dict = {}
            if field.description:
                kwargs["help"] = field.description
            elif field.default is not None:
                kwargs["help"] = " "  # force default-in-help rendering
            origin = typing.get_origin(tp)
            if origin is Literal:
                choices = list(typing.get_args(tp))
                kwargs["choices"] = choices
                kwargs["type"] = type(choices[0]) if choices else str
            elif tp is bool:
                kwargs["type"] = bool_from_string
                kwargs["metavar"] = "{true,false}"
            elif origin in (list, tuple, Sequence):
                inner = (typing.get_args(tp) or (str,))[0]
                kwargs["type"] = inner
                kwargs["nargs"] = "+"
            elif isinstance(tp, type):
                kwargs["type"] = tp
            if field.is_required():
                kwargs["required"] = True
            else:
                kwargs["default"] = field.get_default(call_default_factory=True)
            target.add_argument(f"--{prefix}{name}", **kwargs)
        return parser

    # --------------------------------------------------------- from_argparse
    @classmethod
    def from_argparse(cls, namespace: argparse.Namespace, _consume: bool = True):
        """Build an instance by (recursively) popping fields off a namespace.

        Mirrors the reference's recursive pop + "no leftover keys" assertion
        (base.py:20-31): after the outermost settings class consumes the
        namespace, any remaining attribute is a programming error.
        """
        ns = vars(namespace)
        ns.pop("_parsed_argv", None)  # bookkeeping from from_argv, not a field
        values = cls._pop_from_dict(ns)
        if _consume and ns:
            raise ValueError(
                f"unconsumed argparse keys for {cls.__name__}: {sorted(ns)}"
            )
        return cls(**values)

    @classmethod
    def _pop_from_dict(cls, ns: dict) -> dict:
        values: dict = {}
        for name, field in cls.model_fields.items():
            tp, _optional = _unwrap_optional(field.annotation)
            if _is_model(tp):
                values[name] = tp._pop_from_dict(ns)  # type: ignore[attr-defined]
            elif name in ns:
                values[name] = ns.pop(name)
        return values

    # ------------------------------------------------------------- from_argv
    @classmethod
    def from_argv(cls, argv: Optional[Sequence[str]] = None):
        parser = cls.to_argparse()
        import sys
        ns = parser.parse_args(argv)
        # Record which argv this namespace came from, so downstream checks
        # (e.g. TrainSettings' --config_json exclusivity) inspect the actual
        # parsed command line, not the hosting process's unrelated sys.argv.
        # (parse_args(None) consumed sys.argv itself, so there it IS the
        # parsed command line.)
        ns._parsed_argv = list(argv) if argv is not None else sys.argv[1:]
        return cls.from_argparse(ns)

    # ------------------------------------------------------------------ JSON
    @classmethod
    def parse_file(cls, path: str):
        """pydantic-v1-style JSON file loader (reference config/train.py:72-73)."""
        with open(path) as f:
            return cls.model_validate(json.load(f))

    def to_json(self, **kwargs: Any) -> str:
        return self.model_dump_json(indent=kwargs.pop("indent", 2), **kwargs)

    def save_json(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    # ------------------------------------------------------------ dict-likes
    def dict(self, *, flat: bool = False, **kwargs: Any) -> dict:
        """pydantic-v1-compatible ``.dict()`` (used as ``**args.dict()`` by the
        reference entry point, run/train.py:71). ``flat=True`` flattens nested
        settings one level, matching what a flat argparse namespace carries."""
        d = self.model_dump(**kwargs)
        if flat:
            flat_d: dict = {}
            for k, v in d.items():
                sub = getattr(self, k, None)
                if isinstance(sub, BaseModel):
                    flat_d.update(v)
                else:
                    flat_d[k] = v
            return flat_d
        return d

    def items(self) -> Iterator[Tuple[str, Any]]:
        return iter(self.dict().items())


# Short aliases, matching the reference's exports (base.py:82-87).
S = ArgparseCompatibleBaseModel
Setting = ArgparseCompatibleBaseModel

# Reference exports a ``Validator`` alias (base.py:80) so user settings
# classes can declare field validators without importing pydantic
# themselves; pydantic v2's field_validator is the equivalent surface.
Validator = pydantic.field_validator

T = TypeVar("T")


def choice(*options: T, default: Optional[T] = None, description: str = "") -> Any:
    """Declare a Literal-choices field: ``x: str = choice("a", "b", default="a")``.

    Reference helper ``choice``/``C`` (base.py:65-70). With pydantic v2 the
    Literal type itself lives in the annotation; this helper supplies the
    default + help text and is kept for API familiarity.
    """
    if default is None:
        default = options[0]
    return Field(default=default, description=description or None)


def item(default: Any = ..., description: str = "") -> Any:
    """Declare a documented field: ``lr: float = item(1e-4, "learning rate")``.

    Reference helper ``item``/``_`` (base.py:72-80).
    """
    return Field(default=default, description=description or None)


C = choice
_ = item


def compose_settings(name: str, *bases: Type[S]) -> Type[S]:
    """Create a settings class composed of several others as nested groups —
    the reference achieves this with reverse-MRO multiple inheritance
    (config/train.py:49-55); composition-by-fields is the explicit variant.
    """
    fields = {}
    for base in bases:
        for fname, finfo in base.model_fields.items():
            fields[fname] = (finfo.annotation, finfo)
    return pydantic.create_model(name, __base__=ArgparseCompatibleBaseModel, **fields)  # type: ignore[call-overload]


if __name__ == "__main__":  # self-demo, like reference base.py:90-107
    class Inner(S):
        alpha: float = item(0.5, "inner alpha")
        kind: Literal["a", "b"] = choice("a", "b", description="inner kind")

    class Demo(S):
        lr: float = item(1e-4, "learning rate")
        use_ema: bool = item(True, "enable EMA")
        inner: Inner = Inner()

    p = Demo.to_argparse()
    p.print_help()
    ns = p.parse_args(["--lr", "3e-4", "--alpha", "0.9", "--use_ema", "false"])
    cfg = Demo.from_argparse(ns)
    print(cfg.to_json())
