from .base import (
    ArgparseCompatibleBaseModel,
    S,
    Setting,
    C,
    choice,
    item,
    _,
    bool_from_string,
)
from .serve import ServeSettings
from .train import (
    DataSettings,
    GeneralSettings,
    MeshSettings,
    ModelSettings,
    TrainSettings,
    YourSettings,
)
