"""GPT-2-style causal language model.

The second workload family (BASELINE.md config 4): proves the framework's
model/loss plug-in surface (``create_model_from_config`` +
``compute_losses``) is model-agnostic, i.e. not welded to diffusion.
Reference stub being filled: ``/root/reference/utils/initialization.py:18-27``.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.xent import token_cross_entropy
from .backbone import EMBED, TransformerBackbone

__all__ = ["GPT2Model", "gpt2_losses"]


class GPT2Model(nn.Module):
    """Decoder-only causal LM with weight-tied output head.

    ``decode=True`` (via ``model.clone(decode=True)``) enables the KV-cache
    generation path: a full-length prefill call, then single-token calls
    with ``cache_index=i`` (position embedding taken at i) — see
    backbone.SelfAttention and models/sampling.py."""

    vocab_size: int
    seq_len: int
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    dtype: jnp.dtype = jnp.bfloat16
    remat: bool = False
    attention_impl: str = "auto"
    decode: bool = False
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    moe_no_drop: bool = False
    scan_layers: bool = False
    pp_chunks: int = 4
    pp_schedule: str = "1f1b"  # training schedule under a pipe > 1 mesh
    pp_virtual: int = 2  # virtual stages/device (pp_schedule="interleaved")
    scan_unroll: int = 0  # layer-scan unroll (pipeline.scan_unroll_for)
    paged_pages: int = 0  # serving: paged KV-cache pool size (0 = dense)
    page_size: int = 0
    decode_impl: str = "auto"  # paged decode-step kernel (flash-decode/xla)
    kv_quant: str = "fp"  # "int8": quantized page pool + per-page scales

    @nn.compact
    def __call__(self, ids: jnp.ndarray,
                 pad_mask: Optional[jnp.ndarray] = None,
                 cache_index: Optional[jnp.ndarray] = None,
                 block_table: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        B, L = ids.shape
        word_emb = nn.Embed(
            self.vocab_size, self.hidden_size,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab", EMBED)),
            param_dtype=jnp.float32, name="word_emb")
        # pos_emb stays replicated: like the table's hidden dim, sharding
        # it over fsdp would push fsdp onto h's hidden dim (it adds
        # directly into the activation) and fight the batch sharding
        pos_emb = self.param(
            "pos_emb", nn.with_logical_partitioning(
                nn.initializers.normal(0.02), (None, None)),
            (self.seq_len, self.hidden_size), jnp.float32)
        if cache_index is not None and L == 1:
            idx = jnp.asarray(cache_index, jnp.int32)
            if idx.ndim == 0:
                pos = jax.lax.dynamic_slice(
                    pos_emb, (idx, 0), (1, self.hidden_size))[None]
            else:
                # per-slot positions (continuous-batching decode): each
                # slot sits at its own depth, so the embedding is a gather
                pos = jnp.take(pos_emb, idx, axis=0)[:, None, :]
        elif cache_index is not None:
            # speculative-verify span: per-slot chains at idx..idx+L-1
            # (backbone span branch); budget-final overshoot clamps to
            # the table edge — those links' picks are discarded anyway
            idx = jnp.asarray(cache_index, jnp.int32)
            span = jnp.minimum(idx[:, None]
                               + jnp.arange(L, dtype=jnp.int32)[None, :],
                               self.seq_len - 1)
            pos = jnp.take(pos_emb, span, axis=0)        # [B, L, D]
        else:
            pos = pos_emb[None, :L]
        h = (word_emb(ids) + pos).astype(self.dtype)
        if pad_mask is None:
            pad_mask = jnp.ones_like(ids)
        h = TransformerBackbone(self.num_layers, self.num_heads, self.dtype,
                                self.remat, causal=True,
                                attention_impl=self.attention_impl,
                                decode=self.decode,
                                moe_experts=self.moe_experts,
                                moe_top_k=self.moe_top_k,
                                moe_every=self.moe_every,
                                moe_capacity_factor=self.moe_capacity_factor,
                                moe_no_drop=self.moe_no_drop,
                                scan_layers=self.scan_layers,
                                pp_chunks=self.pp_chunks,
                                scan_unroll=self.scan_unroll,
                                paged_pages=self.paged_pages,
                                page_size=self.page_size,
                                decode_impl=self.decode_impl,
                                kv_quant=self.kv_quant,
                                name="backbone")(h, pad_mask, cache_index,
                                                 block_table)
        # Tied LM head in compute dtype: bf16 [B, L, V] logits cost half the
        # HBM traffic of f32; softmax stats go to f32 downstream (ops/xent.py).
        return jnp.einsum("bld,vd->blv", h,
                          word_emb.embedding.astype(self.dtype))


def gpt2_losses(model: GPT2Model, params, batch: Dict[str, jnp.ndarray],
                rng: jax.Array) -> Dict[str, jnp.ndarray]:
    """Next-token cross-entropy over the loss span — the non-diffusion
    ``compute_losses`` path (reference hook, utils/trainer.py:23-25).
    ``rng`` is unused but kept for loss-fn signature uniformity."""
    del rng
    from ..parallel.ring import current_mesh

    mesh = current_mesh()
    if (mesh is not None and mesh.shape.get("pipe", 1) > 1
            and model.scan_layers and model.moe_experts == 0
            and mesh.shape.get("sequence", 1) == 1
            and model.pp_schedule in ("1f1b", "interleaved")):
        # (MoE and ring-in-stage pipe runs take the AD GPipe stream below
        # instead — the 1F1B engine has no MoE/sequence stage path)
        # training under a pipe mesh: the 1F1B streaming schedule computes
        # loss AND grads in one pass (models/schedule_1f1b.py)
        from .schedule_1f1b import gpt2_1f1b_losses
        return gpt2_1f1b_losses(model, params, batch)
    ids = batch["input_ids"]
    pad_mask = batch["pad_mask"]
    loss_mask = (batch["input_mask"] * pad_mask)[:, 1:].astype(jnp.float32)

    logits, mvars = model.apply(params, ids, pad_mask, mutable=["losses"])
    logits = logits[:, :-1]  # predict ids[:, 1:]
    targets = ids[:, 1:]
    nll = token_cross_entropy(logits, targets)
    denom = jnp.maximum(loss_mask.sum(), 1.0)
    loss = (nll * loss_mask).sum() / denom
    # Teacher-forced next-token accuracy: the right quality gauge when the
    # data has irreducible noise (greedy-decode-vs-gold caps out once the
    # gold draws its first unpredictable token and the histories fork).
    hit = (jnp.argmax(logits, axis=-1) == targets).astype(jnp.float32)
    acc = (hit * loss_mask).sum() / denom
    out = {"loss": loss, "nll": loss, "acc": acc,
           "ppl": jnp.exp(jnp.minimum(loss, 20.0))}
    if jax.tree_util.tree_leaves(mvars.get("losses", {})):  # static: MoE model
        from .moe import MOE_AUX_WEIGHT, moe_aux_from
        aux = moe_aux_from(mvars)
        out["moe_aux"] = aux
        out["loss"] = loss + MOE_AUX_WEIGHT * aux
    return out
