"""Interleaved 1F1B pipeline schedule: loss AND grads in one streaming pass.

The GPipe schedule in models/pipeline.py is forward-only — reverse-mode AD
replays it backwards, which works but makes activation memory scale with the
chunk count M: the scan's residuals hold every chunk's per-layer
intermediates until the whole forward finishes. 1F1B (PipeDream-flush /
Megatron's non-interleaved schedule) starts each chunk's backward as soon as
its forward clears the last stage, so a stage only ever holds the few chunks
in flight between its forward and its backward — letting M grow (which is
exactly the knob that shrinks the bubble) without growing memory.

AD cannot express that interleave from the outside: the backward of a
``shard_map``-ed forward runs strictly after the downstream loss. So this
module IS the backward — a ``jax.custom_vjp`` whose forward pass runs one
combined scan in which every tick does one Forward slot and one Backward
slot per stage, and whose vjp just scales the already-accumulated grads:

* tick ``t``, stage ``s`` **F slot**: forward chunk ``f = t - s`` (entering
  from the previous stage via ``ppermute``, or from ``pre_fn`` on stage 0)
  and stash the chunk's stage INPUT in a ring buffer.
* the LAST stage immediately closes the loop: ``head_fn`` (loss head) runs
  on the chunk it just finished, and its vjp seeds the cotangent stream.
* tick ``t``, stage ``s`` **B slot**: backward chunk ``b = t - 2(S-1) + s``
  — recompute the stage forward from the stashed input under ``jax.vjp``,
  apply the cotangent arriving from stage ``s+1`` (reverse ``ppermute``),
  accumulate weight grads, and send the input-cotangent upstream. Stage 0
  additionally backprops through ``pre_fn`` into the embedding weights and
  any differentiable data inputs.

Timing: chunk ``b``'s cotangent leaves stage ``s+1`` at tick ``t-1`` and is
consumed by stage ``s`` at tick ``t`` — the schedule is SPMD-lockstep, every
device runs the same program per tick. The run takes ``M + 2(S-1)`` ticks
(vs GPipe's ``M + S - 1`` forward-only ticks, but each tick here carries
both an F and a B compute slot, so total work matches forward+backward).
In-flight chunks at stage ``s``: ``f - b + 1 = 2(S-1-s) + 1 <= 2S - 1`` —
the stash ring holds ``min(M, 2S-1)`` chunk inputs, CONSTANT in M
(``stash_size``; the lockstep price vs the textbook per-stage ``S - s``).

ZeRO-3 composition: ``stage_fn`` all-gathers fsdp-sharded weights per layer
inside its scan body, so ``jax.vjp(stage_fn)`` emits the matching
reduce-scatter (``psum_scatter``) and weight grads come out fsdp-sharded
with no extra plumbing.

**Interleaved (virtual-stage) schedule** (``pp_schedule="interleaved"``,
r5): the same engine generalized over V slices per device — the model
splits into S*V virtual stages, stage ``j*S + s`` on device ``s``, so
activations/cotangents hop devices CYCLICALLY once per slot and each slot
runs one F and one B sub-slot of 1/V stage depth. Devices enter steady
state after S-1 slots of 1/V size instead of S-1 full-stage ticks:
bubble fraction ~(S-1)/(V*M + S-1). Costs: the stash grows to
V*min(M, 3S) chunk inputs (stash_size), the stacked weights take a
per-step virtual-stage permute (one weights-sized cross-shard collective,
whose AD transpose un-permutes the grads), and pp_chunks must divide by
S. The slot indexing is closed-form (_slot_indices) and reduces EXACTLY
to the plain schedule at V == 1 — one engine, both schedules.

No reference counterpart (the reference is DDP-only, SURVEY.md §2.2); the
spec is the 1F1B/interleaved schedule of the PipeDream/Megatron
literature, restated for SPMD + XLA collectives.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp

from ..parallel.ring import current_mesh

__all__ = ["pipelined_loss", "stash_size", "gpt2_1f1b_losses",
           "diffuseq_1f1b_losses"]


def stash_size(M: int, S: int, V: int = 1) -> int:
    """Ring-buffer slots needed PER VIRTUAL SLICE for stage-input stashes.

    V == 1 (plain 1F1B): the largest forward-to-backward distance in the
    lockstep schedule is 2(S-1) chunks (stage 0), +1 for the chunk
    entering this tick — capped at M.

    V > 1 (interleaved): virtual stage k's F->B slot distance is
    2(SV-1-k), and its chunks arrive in bursts of S per SV slots, so the
    ids in flight at one slice span < 3S — the ring needs min(M, 3S)
    slots per slice (total stash V*min(M, 3S) chunk inputs: interleaving
    trades some activation memory for the V-fold bubble reduction)."""
    if V <= 1:
        return min(M, 2 * S - 1)
    return min(M, 3 * S)


@jax.custom_vjp
def _sg_pmax(x):
    """pmax over ``tensor`` with a ZERO backward (pmax has no JAX
    differentiation rule, and every use here is gradient-free: logsumexp
    stabilization — where d logZ/d max is exactly 0 — and argmax merges)."""
    return jax.lax.pmax(x, "tensor")


_sg_pmax.defvjp(lambda x: (jax.lax.pmax(x, "tensor"), None),
                lambda _, ct: (jnp.zeros_like(ct),))


def _tree_add(a, b):
    return jax.tree_util.tree_map(jnp.add, a, b)


def _tree_where(pred, t):
    """NaN-safe masking: select, don't multiply (garbage ticks may produce
    non-finite values; 0 * nan would leak them into the accumulators)."""
    return jax.tree_util.tree_map(
        lambda g: jnp.where(pred, g, jnp.zeros_like(g)), t)


def _tree_zeros_of(struct):
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), struct)


def _take(tree, i):
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
        tree)


def pipelined_loss(mesh, lp, rest, diff, aux, scalars, *, pp_chunks: int,
                   stage_fn: Callable, pre_fn: Callable, mask_fn: Callable,
                   head_fn: Callable, lp_specs: Dict[str, Any],
                   rest_specs=None, pp_virtual: int = 1):
    """Run the 1F1B schedule; returns ``(loss, metrics)``, differentiable
    w.r.t. ``lp`` (stage weights), ``rest`` (embedding/head weights) and
    ``diff`` (differentiable per-sample data, e.g. DiffuSeq's x_t/x_start).

    * ``lp``: dict of stacked stage weights, sharded per ``lp_specs``
      (``pipe`` on dim 0, optionally ``fsdp`` on an embed dim).
    * ``rest``: pytree of replicated non-stage weights.
    * ``diff`` / ``aux``: pytrees of ``[B, ...]`` batch arrays —
      cotangents are produced for ``diff`` only. ``scalars``: replicated
      precomputed scalars (e.g. global mask denominators) — global
      reductions cannot be taken per-chunk, so the caller supplies them.
    * ``stage_fn(lp_local, h, mask) -> h`` — this stage's layer stack
      (collectives allowed: fsdp gathers live here).
    * ``pre_fn(rest, diff_c, aux_c, scalars) -> h0`` — embedding for one
      chunk. ``mask_fn(aux_c) -> pad-mask`` for the stage attention.
    * ``head_fn(rest, h_out, diff_c, aux_c, scalars) -> (loss_sum,
      metrics)`` — per-chunk LOSS CONTRIBUTION (a sum scaled by the global
      denominator from ``scalars``; chunk contributions are summed across
      chunks and devices). pre/mask/head run under ``lax.cond`` on the
      stage id, so collectives over any OTHER mesh axis are forbidden —
      EXCEPT the ``tensor`` axis: tensor peers share the same stage id,
      hence the same cond branch, so tensor-group collectives stay
      collectively consistent (the vocab-parallel loss head relies on
      this). Such collectives must use the f/g conjugate pair
      (pipeline._tp_ops "manual" mode) — a raw ``lax.psum`` would
      transpose to an overcounting psum under the engine's hand-rolled
      vjps.
    * ``rest_specs``: optional pytree of PartitionSpecs matching ``rest``
      for keys that enter (and whose grads leave) the engine SHARDED —
      e.g. the vocab-parallel head's ``word_emb`` split over ``tensor``.
      Defaults to fully replicated. Keys sharded over ``tensor`` get
      per-rank grads (never tensor-psummed — full_red excludes tensor).

    ``aux`` and ``scalars`` must not require gradients (they are closed
    over, not differentiated; integer ids/masks and mask-derived
    denominators qualify).
    """
    from ..utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    S = mesh.shape["pipe"]
    M = pp_chunks
    V = max(pp_virtual, 1)
    if S < 2:
        raise ValueError(f"1f1b schedule needs a pipe axis > 1, got {S}")
    if V > 1 and M % S:
        raise ValueError(
            f"interleaved 1F1B groups chunks in bursts of S: pp_chunks "
            f"{M} must divide by the pipe axis {S}")
    batch_axes = tuple(a for a in ("data", "fsdp", "expert")
                       if mesh.shape[a] > 1)
    n_b = 1
    for a in batch_axes:
        n_b *= mesh.shape[a]
    B = jax.tree_util.tree_leaves(aux)[0].shape[0]
    if B % n_b:
        raise ValueError(f"global batch {B} not divisible by data x fsdp x "
                         f"expert axes product {n_b}")
    if (B // n_b) % M:
        raise ValueError(f"per-shard batch {B // n_b} not divisible by "
                         f"pp_chunks {M}")
    K = stash_size(M, S, V)
    T = M * V + S * V + S - 2  # == M + 2(S-1) at V == 1

    bspec = P(batch_axes or None)
    rep = P()
    # Per-key grad reductions: a weight's cotangent must be psum'd over
    # every BATCH axis its spec does not shard it across (distinct data
    # shards on data/expert/fsdp). fsdp-gathered keys already
    # reduce-scattered inside the stage vjp. The tensor axis never needs
    # summing here: stage_fn runs in "manual" tp mode, whose f/g operator
    # pair (pipeline._tp_ops) keeps every non-sharded value AND cotangent
    # identical across tensor ranks (sharded keys hold per-shard grads).
    lp_reduce = {
        k: tuple(a for a in ("data", "expert", "fsdp")
                 if a not in tuple(spec))
        for k, spec in lp_specs.items()}
    body = functools.partial(
        _schedule_body, S=S, M=M, K=K, T=T, V=V, stage_fn=stage_fn,
        pre_fn=pre_fn, mask_fn=mask_fn, head_fn=head_fn,
        lp_reduce=lp_reduce)

    if rest_specs is None:
        rest_specs = jax.tree_util.tree_map(lambda _: rep, rest)
    fwd = shard_map(
        body, mesh=mesh,
        in_specs=(lp_specs, rest_specs, bspec, bspec, rep),
        out_specs=(rep, rep, lp_specs, rest_specs, bspec),
        check_vma=False)
    fwd_only = shard_map(
        functools.partial(_forward_body, S=S, M=M, V=V, stage_fn=stage_fn,
                          pre_fn=pre_fn, mask_fn=mask_fn, head_fn=head_fn),
        mesh=mesh,
        in_specs=(lp_specs, rest_specs, bspec, bspec, rep),
        out_specs=(rep, rep),
        check_vma=False)

    @jax.custom_vjp
    def run(lp_, rest_, diff_):
        # custom_vjp primal: runs only when the loss is NOT differentiated
        # (eval callbacks, compute_losses without grad) — a pure GPipe-style
        # forward stream, skipping the combined F+B scan's recompute/vjp/
        # grad-psum work entirely (r4 advisor: eval under pipe meshes paid
        # the whole gradient pass for values it discarded). Chunk loss
        # contributions accumulate in the same order as the F+B scan, so
        # the value is identical.
        return fwd_only(lp_, rest_, diff_, aux, scalars)

    def run_fwd(lp_, rest_, diff_):
        loss, metrics, d_lp, d_rest, d_diff = fwd(lp_, rest_, diff_, aux,
                                                  scalars)
        return (loss, metrics), (d_lp, d_rest, d_diff)

    def run_bwd(res, cts):
        d_lp, d_rest, d_diff = res
        ct_loss, _ct_metrics = cts  # metrics are reporting-only sums
        scale = lambda t: jax.tree_util.tree_map(
            lambda g: g * ct_loss, t)
        return scale(d_lp), scale(d_rest), scale(d_diff)

    run.defvjp(run_fwd, run_bwd)
    return run(lp, rest, diff)



def _slot_indices(t, sid, S, M, V):
    """Closed-form lockstep slot schedule, generalized over V virtual
    stages per device (Megatron's interleaved 1F1B restated for SPMD):
    virtual stage ``k = j*S + s`` lives on device ``s``; chunk ``c``'s F
    hits it at slot ``u = s + (c//S)*SV + j*S + (c%S)`` (bursts of S
    chunks per SV slots), and its B mirrors at
    ``u_b = u_f(SV-1, c) + (SV-1-k)`` — cotangents hop one virtual stage
    (one device, cyclically) per slot. Inverting both for a given
    (t, sid) yields the unique active F slice ``jf``/chunk ``cf`` and B
    slice ``jb``/chunk ``cb`` (unique because {z + j*S mod SV} meets
    [0, S) exactly once). At V == 1 this reduces EXACTLY to the plain
    engine: jf = jb = 0, cf = t - sid, cb = t - 2(S-1) + sid, so one
    engine serves both schedules.

    Returns (jf, cf, vf, jb, cb, vb) — slices, clipped-safe chunk ids
    (callers clip), and validity masks."""
    SV = S * V
    xf = t - sid
    qf = jnp.mod(xf, SV)
    jf = qf // S
    cf = (xf // SV) * S + jnp.mod(qf, S)
    vf = jnp.logical_and(xf >= 0,
                         jnp.logical_and(cf >= 0, cf < M))
    y0 = t + sid + 2 - 2 * SV
    z = jnp.mod(y0, SV)
    jb = jnp.mod(-(z // S), V)
    y = y0 + jb * S
    cb = (y // SV) * S + jnp.mod(y, SV)
    vb = jnp.logical_and(y >= 0,
                         jnp.logical_and(cb >= 0, cb < M))
    return jf, cf, vf, jb, cb, vb


def _slice_lp(lp_local, V, j):
    """Virtual slice j of this device's stacked weights: [V*per, ...]
    leaves viewed as [V, per, ...] and dynamically indexed (V == 1 is a
    no-op reshape of the whole stack)."""
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_index_in_dim(
            a.reshape((V, a.shape[0] // V) + a.shape[1:]), j, 0,
            keepdims=False),
        lp_local)


def _forward_body(lp_local, rest, diff_local, aux_local, scalars, *,
                  S, M, V, stage_fn, pre_fn, mask_fn, head_fn):
    """Forward-only streaming pass over the pipe axis: F slots + loss head,
    no stash, no vjp, no grad accumulators — the eval-time schedule
    (M*V + S - 1 slots; M + S - 1 at V == 1). Loss/metric chunk sums
    accumulate in the same chunk order as the F+B scan, so values match
    it exactly."""
    sid = jax.lax.axis_index("pipe")
    # V == 1 never reads the wrapped value (stage 0 takes pre_fn), so the
    # plain schedule keeps the cheaper non-cyclic shift
    perm_f = ([(i, (i + 1) % S) for i in range(S)] if V > 1
              else [(i, i + 1) for i in range(S - 1)])

    chunk = lambda a: a.reshape((M, a.shape[0] // M) + a.shape[1:])
    diff_c = jax.tree_util.tree_map(chunk, diff_local)
    aux_c = jax.tree_util.tree_map(chunk, aux_local)
    d0, a0 = _take(diff_c, jnp.int32(0)), _take(aux_c, jnp.int32(0))
    h_struct = jax.eval_shape(pre_fn, rest, d0, a0, scalars)
    zeros_h = jnp.zeros(h_struct.shape, h_struct.dtype)
    head_struct = jax.eval_shape(head_fn, rest, zeros_h, d0, a0, scalars)

    def tick(carry, t):
        recv_f, loss, metrics = carry
        jf, cf, vf, _, _, _ = _slot_indices(t, sid, S, M, V)
        fc = jnp.clip(cf, 0, M - 1)
        kf_first = jnp.logical_and(jnp.equal(sid, 0), jnp.equal(jf, 0))
        kf_last = jnp.logical_and(jnp.equal(sid, S - 1),
                                  jnp.equal(jf, V - 1))
        dfc, afc = _take(diff_c, fc), _take(aux_c, fc)
        h0_f = jax.lax.cond(
            kf_first,
            lambda ops: pre_fn(ops[0], ops[1], ops[2], scalars),
            lambda ops: zeros_h,
            (rest, dfc, afc))
        h_in = jnp.where(kf_first, h0_f, recv_f)
        h_out = stage_fn(_slice_lp(lp_local, V, jf), h_in, mask_fn(afc))
        lc, mc = jax.lax.cond(
            kf_last,
            lambda ops: head_fn(ops[0], ops[1], ops[2], ops[3], scalars),
            lambda ops: _tree_zeros_of(head_struct),
            (rest, h_out, dfc, afc))
        live = jnp.logical_and(vf, kf_last)
        loss = loss + jnp.where(live, lc, 0.0)
        metrics = _tree_add(metrics, _tree_where(live, mc))
        send_f = jax.lax.ppermute(h_out, "pipe", perm_f)
        return (send_f, loss, metrics), None

    carry0 = (zeros_h, jnp.zeros((), jnp.float32),
              _tree_zeros_of(head_struct[1]))
    (_, loss, metrics), _ = jax.lax.scan(tick, carry0,
                                         jnp.arange(M * V + S - 1))
    full_red = ("data", "fsdp", "expert", "pipe")
    return jax.lax.psum(loss, full_red), jax.lax.psum(metrics, full_red)


def _schedule_body(lp_local, rest, diff_local, aux_local, scalars, *,
                   S, M, K, T, V, stage_fn, pre_fn, mask_fn, head_fn,
                   lp_reduce):
    """Per-device combined F+B scan (module docstring), generalized over V
    virtual stages per device (_slot_indices): each slot runs one F
    sub-slot and one B sub-slot of 1/V stage depth, activations and
    cotangents hop one device (cyclically) per slot, and the stash ring is
    per-slice. At V == 1 every index reduces to the plain 1F1B schedule.
    Runs inside shard_map; ``lp_local`` is this device's (possibly
    fsdp-sharded) layer slice — for V > 1 in VIRTUAL-STAGE order (the
    family glue permutes the stack so slice j holds virtual stage
    j*S + sid; the permutation's AD transpose un-permutes the grads)."""
    sid = jax.lax.axis_index("pipe")
    # V == 1 never reads the wrapped values (stage 0 takes pre_fn, the
    # last stage seeds from its head vjp), so the plain schedule keeps
    # the cheaper non-cyclic shifts — interleaving needs the full cycle
    # (virtual stage j*S+S-1 feeds (j+1)*S+0)
    if V > 1:
        perm_f = [(i, (i + 1) % S) for i in range(S)]
        perm_b = [((i + 1) % S, i) for i in range(S)]
    else:
        perm_f = [(i, i + 1) for i in range(S - 1)]
        perm_b = [(i + 1, i) for i in range(S - 1)]

    chunk = lambda a: a.reshape((M, a.shape[0] // M) + a.shape[1:])
    diff_c = jax.tree_util.tree_map(chunk, diff_local)
    aux_c = jax.tree_util.tree_map(chunk, aux_local)

    d0, a0 = _take(diff_c, jnp.int32(0)), _take(aux_c, jnp.int32(0))
    h_struct = jax.eval_shape(pre_fn, rest, d0, a0, scalars)

    def head_and_vjp(rest_, h_, dc_, ac_):
        (lc, mc), hvjp = jax.vjp(
            lambda r, h, d: head_fn(r, h, d, ac_, scalars),
            rest_, h_, dc_)
        d_rest_h, d_h_out, d_diff_h = hvjp(
            (jnp.ones((), lc.dtype),
             jax.tree_util.tree_map(jnp.zeros_like, mc)))
        return lc, mc, d_rest_h, d_h_out, d_diff_h

    def pre_vjp(rest_, dc_, ac_, seed):
        _, pvjp = jax.vjp(
            lambda r, d: pre_fn(r, d, ac_, scalars), rest_, dc_)
        return pvjp(seed)

    zeros_h = jnp.zeros(h_struct.shape, h_struct.dtype)
    head_struct = jax.eval_shape(head_and_vjp, rest, zeros_h, d0, a0)
    pre_struct = jax.eval_shape(pre_vjp, rest, d0, a0, zeros_h)

    def tick(carry, t):
        recv_f, recv_b, stash, d_lp, d_rest, d_diff, loss, metrics = carry
        jf, cf, vf, jb, cb, vb = _slot_indices(t, sid, S, M, V)
        fc = jnp.clip(cf, 0, M - 1)
        bc = jnp.clip(cb, 0, M - 1)
        kf_first = jnp.logical_and(jnp.equal(sid, 0), jnp.equal(jf, 0))
        kf_last = jnp.logical_and(jnp.equal(sid, S - 1),
                                  jnp.equal(jf, V - 1))
        kb_last = jnp.logical_and(jnp.equal(sid, S - 1),
                                  jnp.equal(jb, V - 1))
        dfc, afc = _take(diff_c, fc), _take(aux_c, fc)
        dbc, abc = _take(diff_c, bc), _take(aux_c, bc)

        # ---- F slot: forward chunk cf through virtual slice jf (pre_fn
        # only feeds virtual stage 0 — cond skips its flops elsewhere;
        # collectives inside are legal over the tensor axis ONLY, whose
        # peers share (sid, t) and therefore this branch)
        h0_f = jax.lax.cond(
            kf_first,
            lambda ops: pre_fn(ops[0], ops[1], ops[2], scalars),
            lambda ops: zeros_h,
            (rest, dfc, afc))
        h_in = jnp.where(kf_first, h0_f, recv_f)
        h_out = stage_fn(_slice_lp(lp_local, V, jf), h_in, mask_fn(afc))
        slot_w = jf * K + jnp.mod(fc, K)
        prev = jax.lax.dynamic_index_in_dim(stash, slot_w, 0,
                                            keepdims=False)
        stash = jax.lax.dynamic_update_index_in_dim(
            stash, jnp.where(vf, h_in, prev), slot_w, 0)

        # Send the forward activation now and TIE the B slot behind it:
        # send_f has no data dependence on any B-slot work, so without the
        # barrier the runtime may race this pipe ppermute against the B
        # slot's fsdp/tensor collectives from OTHER cliques — on small
        # hosts the in-process CPU communicator then starves its rendezvous
        # and aborts. Tying lp_b (the weights the B-slot vjp re-gathers)
        # as well as h_out covers every B-slot collective: the fsdp
        # gathers inside svjp depend only on the weights, not on h_out.
        # (Best-effort mitigation — the fake-device runtime can still
        # abort under load; tests retry, real TPUs are in-order.)
        send_f = jax.lax.ppermute(h_out, "pipe", perm_f)
        send_f, h_out, lp_b = jax.lax.optimization_barrier(
            (send_f, h_out, lp_local))

        # ---- loss head: only the LAST VIRTUAL stage's value is real
        # (cb == cf there — its B slot shares the F slot, so h_out IS
        # chunk cb's final output); lax.cond skips the flops elsewhere.
        # Collectives inside are legal over the tensor axis only
        # (same-branch peers — the vocab-parallel head's psums/pmaxes).
        lc, mc, d_rest_h, d_h_out, d_diff_h = jax.lax.cond(
            kf_last,
            lambda ops: head_and_vjp(*ops),
            lambda ops: _tree_zeros_of(head_struct),
            (rest, h_out, dbc, abc))

        # ---- B slot: backward chunk cb through virtual slice jb —
        # recompute from the stashed slice input under vjp (activation
        # recompute: residual lifetime is one slot), consume the
        # cotangent, stream its input-cotangent back.
        cot_in = jnp.where(kb_last, d_h_out, recv_b)
        slot_r = jb * K + jnp.mod(bc, K)
        h_in_b = jax.lax.dynamic_index_in_dim(stash, slot_r, 0,
                                              keepdims=False)
        mask_b = mask_fn(abc)
        _, svjp = jax.vjp(lambda w, h: stage_fn(w, h, mask_b),
                          _slice_lp(lp_b, V, jb), h_in_b)
        d_lp_c, d_h_in = svjp(cot_in)

        d_rest_p, d_diff_p = jax.lax.cond(
            jnp.logical_and(jnp.equal(sid, 0), jnp.equal(jb, 0)),
            lambda ops: pre_vjp(*ops),
            lambda ops: _tree_zeros_of(pre_struct),
            (rest, dbc, abc, d_h_in))

        # scatter this slot's slice grads into the [V, per, ...] views
        d_lp = jax.tree_util.tree_map(
            lambda acc, g: jax.lax.dynamic_update_index_in_dim(
                acc,
                jax.lax.dynamic_index_in_dim(acc, jb, 0, keepdims=False)
                + jnp.where(vb, g, jnp.zeros_like(g)),
                jb, 0),
            d_lp, d_lp_c)
        d_rest = _tree_add(d_rest,
                           _tree_where(vb, _tree_add(d_rest_h, d_rest_p)))
        d_diff = jax.tree_util.tree_map(
            lambda buf, g: buf.at[bc].add(jnp.where(vb, g,
                                                    jnp.zeros_like(g))),
            d_diff, _tree_add(d_diff_h, d_diff_p))
        live = jnp.logical_and(vb, kb_last)
        loss = loss + jnp.where(live, lc, 0.0)
        metrics = _tree_add(metrics, _tree_where(live, mc))

        send_b = jax.lax.ppermute(d_h_in, "pipe", perm_b)
        return (send_f, send_b, stash, d_lp, d_rest, d_diff, loss,
                metrics), None

    # metrics carry structure: zeros of head_fn's metrics output
    metrics0 = _tree_zeros_of(
        jax.eval_shape(head_fn, rest, zeros_h, d0, a0, scalars)[1])
    view = lambda a: a.reshape((V, a.shape[0] // V) + a.shape[1:])
    carry0 = (
        zeros_h,                                          # recv_f
        zeros_h,                                          # recv_b
        jnp.zeros((V * K,) + h_struct.shape, h_struct.dtype),  # stash
        jax.tree_util.tree_map(
            lambda a: jnp.zeros_like(view(a)), lp_local),  # d_lp [V,per,..]
        jax.tree_util.tree_map(jnp.zeros_like, rest),      # d_rest
        jax.tree_util.tree_map(jnp.zeros_like, diff_c),    # d_diff [M,cb,..]
        jnp.zeros((), jnp.float32),                        # loss
        metrics0,
    )
    (_, _, _, d_lp, d_rest, d_diff, loss, metrics), _ = jax.lax.scan(
        tick, carry0, jnp.arange(T))
    # collapse the virtual-slice views back to the stacked layout
    d_lp = jax.tree_util.tree_map(
        lambda a: a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]),
        d_lp)

    # ---- cross-device reductions (outside lax.cond — collectives must run
    # on every device). Gathered weights' fsdp reduce-scatter already
    # happened inside svjp (the transpose of the per-layer all_gather);
    # everything else sums explicitly.
    d_lp = {k: (jax.lax.psum(g, lp_reduce[k]) if lp_reduce[k] else g)
            for k, g in d_lp.items()}
    full_red = ("data", "fsdp", "expert", "pipe")
    d_rest = jax.lax.psum(d_rest, full_red)
    loss = jax.lax.psum(loss, full_red)
    metrics = jax.lax.psum(metrics, full_red)
    # diff cotangents: batch-sharded; only one stage produced each side's
    # contribution (masked elsewhere) — psum over pipe merges pre+head parts
    B_local = jax.tree_util.tree_leaves(aux_local)[0].shape[0]
    d_diff = jax.tree_util.tree_map(
        lambda a: jax.lax.psum(a, "pipe").reshape((B_local,) + a.shape[2:]),
        d_diff)
    return loss, metrics, d_lp, d_rest, d_diff


# --------------------------------------------------------------------------
# Family glue: GPT-2 and DiffuSeq objectives on the 1F1B engine. These
# re-state each family's pre/head math as pure functions of the param trees
# (numerics pinned against the flax modules by tests/test_pipeline.py).
# --------------------------------------------------------------------------


def _interleave_stack(lp, S: int, V: int):
    """Reorder stacked layer weights into VIRTUAL-STAGE order: output
    block ``s*V + j`` holds virtual stage ``j*S + s``'s layers, so the
    pipe sharding (contiguous dim-0 blocks per device) gives device s its
    V non-contiguous slices. Runs OUTSIDE the engine's custom_vjp, so
    reverse-mode AD transposes the gather and un-permutes the returned
    grads automatically. On a pipe-sharded array this is a cross-shard
    permute (one weights-sized collective per step — the interleaving
    trade)."""
    import numpy as np

    idx = np.asarray([j * S + s for s in range(S) for j in range(V)])

    def pm(a):
        per = a.shape[0] // (S * V)
        return a.reshape((S * V, per) + a.shape[1:])[idx].reshape(a.shape)

    return jax.tree_util.tree_map(pm, lp)


def _virtual_stages(model, mesh, lp) -> int:
    """V for the engine: pp_virtual under the interleaved schedule, else
    1 — with the layer-divisibility check."""
    if getattr(model, "pp_schedule", "1f1b") != "interleaved":
        return 1
    V = max(int(getattr(model, "pp_virtual", 2)), 1)
    S = mesh.shape["pipe"]
    Lc = next(iter(lp.values())).shape[0]
    if Lc % (S * V):
        raise ValueError(
            f"interleaved 1F1B needs num_layers ({Lc}) divisible by "
            f"pipe axis x pp_virtual ({S} x {V})")
    return V


def _stage_fn_for(model, gather, causal: bool, tp: bool):
    """This stage's layer stack as a pure fn: pipeline.stage_apply (the
    same body the GPipe schedule uses — the gather/remat/impl/tp policy
    lives in ONE place) with the model's static attributes bound. The
    fsdp gathers and tp psums inside make jax.vjp emit the matching
    reduce-scatter / per-shard grads (ZeRO-3 + Megatron semantics)."""
    from .pipeline import stage_apply

    return functools.partial(
        stage_apply, num_heads=model.num_heads, dtype=model.dtype,
        causal=causal, attention_impl=model.attention_impl,
        remat=model.remat, gather=gather, tp=tp,
        scan_unroll=model.scan_unroll)


def _check_pipe_mesh(mesh):
    if mesh.shape["sequence"] > 1:
        raise ValueError(
            f"the 1F1B engine composes with data/fsdp/tensor/expert axes "
            f"only; mesh has sequence={mesh.shape['sequence']} — "
            f"ring-in-stage pipe runs route through the AD GPipe stream "
            f"(the family losses gate on sequence == 1)")


def gpt2_1f1b_losses(model, params, batch) -> Dict[str, jnp.ndarray]:
    """GPT-2 next-token CE through the 1F1B schedule — same objective and
    metrics as gpt2.gpt2_losses, computed per chunk at the last stage.

    Under ``tensor > 1`` (and vocab divisible by it) the tied embedding/
    loss head runs VOCAB-PARALLEL (Megatron's parallel cross-entropy,
    restated for the f/g manual-vjp calculus): each tensor rank holds a
    [V/t, d] slice of the tied table, the embedding lookup is a masked
    local gather all-reduced with ``_tp_f`` (psum forward / identity
    backward), and the head computes only its local [chunk, L, V/t] logit
    slice — cross-entropy via a distributed logsumexp (stop-gradient pmax
    for stabilization, ``_tp_f`` on the sum-exp and the target-logit
    pick) and accuracy via a pmax/pmin argmax merge that preserves
    XLA's lowest-index tie-breaking. No rank ever materializes a full
    [*, V] logit tensor (the r4 verdict's weak #3: at real vocabs the
    replicated head duplicated the most expensive matmul per rank).
    ``_tp_g`` on the final-layernorm output merges the per-rank partial
    cotangents flowing back from the local logit slices."""
    from .pipeline import _layernorm, _tp_f, _tp_g
    from ..ops.xent import token_cross_entropy

    mesh = current_mesh()
    _check_pipe_mesh(mesh)
    p = params["params"]
    lp = dict(p["backbone"]["blocks"])
    rest = {"word_emb": p["word_emb"]["embedding"],
            "pos_emb": p["pos_emb"],
            "ln_f_scale": p["backbone"]["ln_f"]["scale"],
            "ln_f_bias": p["backbone"]["ln_f"]["bias"]}
    ids = batch["input_ids"]
    pad_mask = batch["pad_mask"]
    loss_mask = (batch["input_mask"] * pad_mask)[:, 1:].astype(jnp.float32)
    inv_denom = 1.0 / jnp.maximum(loss_mask.sum(), 1.0)
    aux = {"ids": ids, "pad": pad_mask, "lm": loss_mask}
    dtype = model.dtype
    L = ids.shape[1]
    V = rest["word_emb"].shape[0]
    t = mesh.shape["tensor"]
    vocab_parallel = t > 1 and V % t == 0
    rest_specs = None

    if not vocab_parallel:
        def pre_fn(r, dc, ac, sc):
            del dc, sc
            return (r["word_emb"][ac["ids"]]
                    + r["pos_emb"][None, :L]).astype(dtype)

        def head_fn(r, h, dc, ac, sc):
            del dc
            h = _layernorm(h, r["ln_f_scale"], r["ln_f_bias"]).astype(dtype)
            logits = jnp.einsum("bld,vd->blv", h,
                                r["word_emb"].astype(dtype))[:, :-1]
            targets = ac["ids"][:, 1:]
            nll = token_cross_entropy(logits, targets)
            lm = ac["lm"]
            loss_sum = (nll * lm).sum() * sc["inv_denom"]
            hit = (jnp.argmax(logits, axis=-1) == targets)
            return loss_sum.astype(jnp.float32), {
                "acc": ((hit.astype(jnp.float32) * lm).sum()
                        * sc["inv_denom"]).astype(jnp.float32)}
    else:
        from jax.sharding import PartitionSpec as P
        rest_specs = {"word_emb": P("tensor"), "pos_emb": P(),
                      "ln_f_scale": P(), "ln_f_bias": P()}
        Vl = V // t

        def pre_fn(r, dc, ac, sc):
            del dc, sc
            v0 = jax.lax.axis_index("tensor") * Vl
            local = ac["ids"] - v0
            ok = jnp.logical_and(local >= 0, local < Vl)
            rows = r["word_emb"][jnp.clip(local, 0, Vl - 1)]
            emb = _tp_f(jnp.where(ok[..., None], rows, 0.0))
            return (emb + r["pos_emb"][None, :L]).astype(dtype)

        def head_fn(r, h, dc, ac, sc):
            del dc
            h = _layernorm(h, r["ln_f_scale"], r["ln_f_bias"]).astype(dtype)
            # per-rank partial paths start here: g merges their ln/h
            # cotangents on the way back
            h = _tp_g(h)
            logits_l = jnp.einsum("bld,vd->blv", h,
                                  r["word_emb"].astype(dtype))[:, :-1]
            logits_l = logits_l.astype(jnp.float32)
            targets = ac["ids"][:, 1:]
            v0 = jax.lax.axis_index("tensor") * Vl
            tl = targets - v0
            ok = jnp.logical_and(tl >= 0, tl < Vl)
            # distributed logsumexp: the max is stabilization only — its
            # zero backward (_sg_pmax) is exact, d logZ/d max == 0
            lmax_l = jnp.max(logits_l, axis=-1)
            lmax = _sg_pmax(lmax_l)
            se = jnp.sum(jnp.exp(logits_l - lmax[..., None]), axis=-1)
            logz = lmax + jnp.log(_tp_f(se))
            picked = jnp.take_along_axis(
                logits_l, jnp.clip(tl, 0, Vl - 1)[..., None], axis=-1)[..., 0]
            tgt_logit = _tp_f(jnp.where(ok, picked, 0.0))
            nll = logz - tgt_logit
            lm = ac["lm"]
            loss_sum = (nll * lm).sum() * sc["inv_denom"]
            # argmax across shards, preserving lowest-index tie-breaking:
            # min over ranks achieving the global max, as -pmax(-x)
            li = jnp.argmax(logits_l, axis=-1) + v0
            cand = jnp.where(lmax_l >= lmax, li, V).astype(jnp.float32)
            gi = (-_sg_pmax(-cand)).astype(jnp.int32)
            hit = (gi == targets)
            return loss_sum.astype(jnp.float32), {
                "acc": ((hit.astype(jnp.float32) * lm).sum()
                        * sc["inv_denom"]).astype(jnp.float32)}

    from .pipeline import stacked_specs
    lp_specs, gather, tp = stacked_specs(mesh, lp)
    V = _virtual_stages(model, mesh, lp)
    if V > 1:
        lp = _interleave_stack(lp, mesh.shape["pipe"], V)
    loss, metrics = pipelined_loss(
        mesh, lp, rest, {}, aux, {"inv_denom": inv_denom},
        pp_chunks=model.pp_chunks, pp_virtual=V,
        stage_fn=_stage_fn_for(model, gather, causal=True,
                               tp="manual" if tp else False),
        pre_fn=pre_fn, mask_fn=lambda ac: ac["pad"], head_fn=head_fn,
        lp_specs=lp_specs, rest_specs=rest_specs)
    return {"loss": loss, "nll": loss, "acc": metrics["acc"],
            "ppl": jnp.exp(jnp.minimum(loss, 20.0))}


def diffuseq_1f1b_losses(model, schedule, params, batch,
                         rng: jax.Array) -> Dict[str, jnp.ndarray]:
    """DiffuSeq objective with the denoiser trunk on the 1F1B schedule.

    Only the mse term runs through the blocks; tT and decoder_nll depend on
    the word embedding alone and stay on ordinary AD (diffuseq.py
    diffuseq_losses). x_t and x_start enter the engine as DIFFERENTIABLE
    data (``diff``) so the word-embedding gradient through the noising and
    the mse target is preserved."""
    from .diffuseq import DiffuSeqModel, _masked_mean, timestep_embedding
    from .pipeline import _layernorm
    from ..ops.xent import token_cross_entropy

    mesh = current_mesh()
    _check_pipe_mesh(mesh)
    ids = batch["input_ids"]
    tgt_mask = batch["input_mask"].astype(jnp.float32)
    pad_mask = batch["pad_mask"]
    B, L = ids.shape

    rng_t, rng_noise = jax.random.split(rng)
    x_start = model.apply(params, ids, method=DiffuSeqModel.embed)
    t = schedule.sample_t(rng_t, B)
    noise = jax.random.normal(rng_noise, x_start.shape, x_start.dtype)
    x_noisy = schedule.q_sample(x_start, t, noise)
    x_t = jnp.where(tgt_mask[..., None] > 0, x_noisy, x_start)

    p = params["params"]
    lp = dict(p["backbone"]["blocks"])
    rest = {"in_w": p["in_proj"]["kernel"], "in_b": p["in_proj"]["bias"],
            "t0_w": p["time_mlp"]["layers_0"]["kernel"],
            "t0_b": p["time_mlp"]["layers_0"]["bias"],
            "t1_w": p["time_mlp"]["layers_2"]["kernel"],
            "t1_b": p["time_mlp"]["layers_2"]["bias"],
            "pos_emb": p["pos_emb"],
            "ln_f_scale": p["backbone"]["ln_f"]["scale"],
            "ln_f_bias": p["backbone"]["ln_f"]["bias"],
            "out_w": p["out_proj"]["kernel"], "out_b": p["out_proj"]["bias"]}
    inv_tgt = 1.0 / jnp.maximum(tgt_mask.sum(), 1.0)
    dtype = model.dtype
    H = model.hidden_size

    def pre_fn(r, dc, ac, sc):
        del sc
        h = (jnp.einsum("ble,eh->blh", dc["x_t"].astype(dtype),
                        r["in_w"].astype(dtype)) + r["in_b"].astype(dtype))
        te = timestep_embedding(ac["t"], H)
        te = jax.nn.silu(te @ r["t0_w"] + r["t0_b"]) @ r["t1_w"] + r["t1_b"]
        h = h + te[:, None, :].astype(dtype)
        return h + r["pos_emb"][None, :L].astype(dtype)

    def head_fn(r, h, dc, ac, sc):
        h = _layernorm(h, r["ln_f_scale"], r["ln_f_bias"]).astype(dtype)
        x0_hat = (jnp.einsum("blh,he->ble", h, r["out_w"].astype(dtype))
                  + r["out_b"].astype(dtype)).astype(jnp.float32)
        per = jnp.mean((x0_hat - dc["x_start"]) ** 2, axis=-1)
        loss_sum = (per * ac["tm"]).sum() * sc["inv_tgt"]
        return loss_sum.astype(jnp.float32), {}

    from .pipeline import stacked_specs
    lp_specs, gather, tp = stacked_specs(mesh, lp)
    V = _virtual_stages(model, mesh, lp)
    if V > 1:
        lp = _interleave_stack(lp, mesh.shape["pipe"], V)
    mse, _ = pipelined_loss(
        mesh, lp, rest, {"x_t": x_t, "x_start": x_start},
        {"t": t, "pad": pad_mask, "tm": tgt_mask}, {"inv_tgt": inv_tgt},
        pp_chunks=model.pp_chunks, pp_virtual=V,
        stage_fn=_stage_fn_for(model, gather, causal=False,
                               tp="manual" if tp else False),
        pre_fn=pre_fn, mask_fn=lambda ac: ac["pad"], head_fn=head_fn,
        lp_specs=lp_specs)

    tT = _masked_mean(schedule.mean_flat_tT(x_start), tgt_mask)
    logits = model.apply(params, x_start, method=DiffuSeqModel.logits)
    decoder_nll = _masked_mean(token_cross_entropy(logits, ids), tgt_mask)
    loss = mse + tT + decoder_nll
    return {"loss": loss, "mse": mse, "tT": tT, "decoder_nll": decoder_nll}
