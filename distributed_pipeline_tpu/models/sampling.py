"""Inference: DiffuSeq reverse-process sampling and GPT-2 decoding.

The reference scaffold trains models but ships no way to USE a checkpoint
(no sampling/generation code anywhere in ``/root/reference``); this module
exceeds it so checkpoints are consumable artifacts:

* :func:`diffuseq_sample` — DDIM-style reverse diffusion over the target
  span with the source span anchored clean (the training-time "partial
  noising" mirrored at inference), with DiffuSeq's clamping trick (project
  each x0 estimate onto the nearest word embedding through the tied
  rounding head) and step-striding for fast sampling.
* :func:`diffuseq_sample_mbr` — minimum-Bayes-risk consensus decoding over
  S independent samples (the DiffuSeq paper's own scheme).
* :func:`gpt2_decode` — KV-cache autoregressive continuation of a prompt
  prefix: greedy by default, temperature / top-k / nucleus sampling
  optional; works for named-blocks and stacked (scan_layers) models.
* :func:`make_decode_callback` — wires either into ``TrainLoop``'s
  ``eval_callbacks`` hook (reference trainer.py:184-191 runs callbacks on
  rank 0 at eval intervals), logging ``decode_acc`` so training runs report
  end-task quality, not just loss.

Everything jits: samplers are ``lax.scan``/``fori_loop`` over static step
counts — no Python control flow on traced values.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .diffuseq import DiffuSeqModel

__all__ = [
    "diffuseq_sample",
    "diffuseq_sample_mbr",
    "gpt2_decode",
    "gpt2_greedy_decode",
    "gpt2_decode_and_score",
    "gpt2_decode_accuracy",
    "target_span_accuracy",
    "make_decode_callback",
]


def _truncate_logits(l: jnp.ndarray, top_k: int, top_p: float) -> jnp.ndarray:
    """Static top-k / nucleus truncation of f32 logits [..., V] — shared by
    the batch decode picker here and the per-slot picker in
    serving/engine.py (one implementation, so one-shot and served sampling
    truncate identically)."""
    if top_k > 0:
        # clamp: top_k >= vocab means "no truncation", not a trace error
        k = min(top_k, l.shape[-1])
        kth = jax.lax.top_k(l, k)[0][..., -1:]  # [..., 1]
        l = jnp.where(l < kth, -jnp.inf, l)
    if 0.0 < top_p < 1.0:
        sorted_l = jnp.sort(l, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_l, axis=-1)
        # smallest prefix with cumulative mass >= top_p; the token that
        # crosses the threshold stays in
        keep = jnp.cumsum(probs, axis=-1) - probs < top_p
        cutoff = jnp.min(jnp.where(keep, sorted_l, jnp.inf),
                         axis=-1, keepdims=True)
        l = jnp.where(l < cutoff, -jnp.inf, l)
    return l


def _next_token_fn(temperature: float, top_k: int, top_p: float,
                   rng: Optional[jax.Array]):
    """Token picker for one decode step: ``(logits [B, V], position) ->
    ids [B]``. ``temperature <= 0`` is exact greedy argmax; otherwise
    categorical sampling after temperature scaling with optional top-k
    truncation and nucleus (top-p) truncation — all static flags, so the
    whole picker traces into the decode loop."""
    if temperature <= 0.0:
        return lambda logits, i: jnp.argmax(logits, axis=-1)
    if rng is None:
        raise ValueError("stochastic decoding (temperature > 0) needs rng")

    def pick(logits: jnp.ndarray, i: jnp.ndarray) -> jnp.ndarray:
        l = _truncate_logits(logits.astype(jnp.float32) / temperature,
                             top_k, top_p)
        return jax.random.categorical(jax.random.fold_in(rng, i), l, axis=-1)

    return pick


def _sample_timesteps(T: int, sample_steps: int) -> np.ndarray:
    """Descending int32 subset of [0, T): full T when sample_steps<=0, else
    an evenly-strided subsequence ending at 0 (DDIM respacing)."""
    if sample_steps <= 0 or sample_steps >= T:
        return np.arange(T - 1, -1, -1, dtype=np.int32)
    return np.unique(np.linspace(0, T - 1, sample_steps).round()
                     .astype(np.int32))[::-1].copy()


def diffuseq_sample(workload, params, batch: Dict[str, jnp.ndarray],
                    rng: jax.Array, sample_steps: int = 0,
                    clamp: bool = True) -> jnp.ndarray:
    """Generate target-span token ids by reverse diffusion.

    ``batch`` uses the framework batch contract (data/dataset.py): the
    SOURCE span (``input_mask == 0``) conditions generation; whatever ids
    the batch carries in the target span are ignored (only the span's
    position/length is used), so evaluation can pass gold batches without
    leaking them. Returns int32 [B, L]: source ids untouched, target span
    replaced by generated tokens.

    DDIM (eta=0) update over a strided timestep subset; ``clamp=True``
    projects each x0 estimate to its nearest embedding (DiffuSeq's rounding
    trick — keeps the trajectory on the decodable manifold)."""
    # MoE models: exact per-token routing at inference (no capacity drops).
    model: DiffuSeqModel = workload.model.clone(moe_no_drop=True)
    sched = workload.schedule
    ids = batch["input_ids"]
    tgt = batch["input_mask"][..., None] > 0              # [B, L, 1]
    pad_mask = batch["pad_mask"]
    B = ids.shape[0]

    # Source anchor: target ids zeroed out BEFORE embedding (no leakage).
    ids_src = jnp.where(tgt[..., 0], 0, ids)
    x_src = model.apply(params, ids_src, method=DiffuSeqModel.embed)

    sa = jnp.asarray(sched.sqrt_alphas_cumprod)           # [T]
    ss = jnp.asarray(sched.sqrt_one_minus_alphas_cumprod)

    ts = _sample_timesteps(sched.num_steps, sample_steps)
    t_prev = np.concatenate([ts[1:], [0]]).astype(np.int32)

    noise = jax.random.normal(rng, x_src.shape, x_src.dtype)
    x = jnp.where(tgt, noise, x_src)

    def predict_x0(x, t):
        t_full = jnp.full((B,), t, jnp.int32)
        x0 = model.apply(params, x, t_full, pad_mask)
        if clamp:
            logits = model.apply(params, x0, method=DiffuSeqModel.logits)
            x0 = model.apply(params, jnp.argmax(logits, axis=-1),
                             method=DiffuSeqModel.embed)
        return jnp.where(tgt, x0, x_src)

    def step(x, t_pair):
        t, tp = t_pair
        x0 = predict_x0(x, t)
        eps = (x - sa[t] * x0) / jnp.maximum(ss[t], 1e-4)
        x_next = jnp.where(tgt, sa[tp] * x0 + ss[tp] * eps, x_src)
        return x_next, x0

    x, x0_all = jax.lax.scan(step, x, (jnp.asarray(ts), jnp.asarray(t_prev)))
    x0_final = x0_all[-1]
    logits = model.apply(params, x0_final, method=DiffuSeqModel.logits)
    gen = jnp.argmax(logits, axis=-1).astype(ids.dtype)
    return jnp.where(tgt[..., 0], gen, ids)


def _mbr_scores(cands: jnp.ndarray, tgt: jnp.ndarray) -> jnp.ndarray:
    """Per-candidate consensus score [S, B]: mean target-span token
    agreement of candidate s with the OTHER candidates (the diagonal
    self-agreement is the constant 1 — subtracted rather than masked)."""
    agree = (cands[:, None] == cands[None, :]).astype(jnp.float32)
    span = jnp.maximum(tgt.sum(-1), 1.0)                # [B]
    pair = (agree * tgt[None, None]).sum(-1) / span     # [S, S, B]
    return (pair.sum(0) - 1.0) / (cands.shape[0] - 1)


def diffuseq_sample_mbr(workload, params, batch: Dict[str, jnp.ndarray],
                        rng: jax.Array, num_candidates: int = 5,
                        sample_steps: int = 0,
                        clamp: bool = True) -> jnp.ndarray:
    """Minimum-Bayes-risk decoding: draw ``num_candidates`` independent
    reverse-diffusion samples (distinct noise keys) and keep, per example,
    the candidate with the highest mean target-span token agreement with
    the other candidates — the consensus sample. This is the decoding
    scheme of the DiffuSeq paper itself (Gong et al., ICLR 2023, "DiffuSeq:
    Sequence to Sequence Text Generation with Diffusion Models" — the paper
    the reference repo's README cites, /root/reference/README.md:31-40),
    here with token-level agreement as the risk proxy so the whole
    selection stays on-device and jittable."""
    if num_candidates <= 1:
        return diffuseq_sample(workload, params, batch, rng, sample_steps,
                               clamp=clamp)

    def one(key):
        return diffuseq_sample(workload, params, batch, key, sample_steps,
                               clamp=clamp)

    keys = jax.random.split(rng, num_candidates)
    cands = jax.lax.map(one, keys)                      # [S, B, L]
    tgt = (batch["input_mask"] * batch["pad_mask"]).astype(jnp.float32)
    best = jnp.argmax(_mbr_scores(cands, tgt), axis=0)  # [B]
    return jnp.take_along_axis(
        cands, best[None, :, None], axis=0)[0]          # [B, L]


def gpt2_decode(workload, params, ids: jnp.ndarray,
                prompt_len: int, use_cache: bool = True,
                temperature: float = 0.0, top_k: int = 0,
                top_p: float = 0.0,
                rng: Optional[jax.Array] = None) -> jnp.ndarray:
    """Continue ``ids[:, :prompt_len]`` out to the full seq_len; int32
    [B, L] out. ``temperature=0`` (default) is greedy argmax; > 0 samples
    from the temperature-scaled distribution, optionally truncated to the
    ``top_k`` highest-probability tokens and/or the ``top_p`` nucleus.
    Sampling is deterministic given ``rng`` (per-position fold_in), and
    identical between the cached and uncached paths (same logits, same
    per-position key).

    ``use_cache=True`` (default) runs the KV-cache path: one full-length
    prefill populates every layer's K/V cache (stale tail entries are
    overwritten before any step can read them — causality guarantees it),
    then each new token is one single-position forward, O(L) per token
    instead of a full O(L^2) re-forward. ``use_cache=False`` recomputes the
    full forward per position — the reference implementation the cache path
    is tested against."""
    pick = _next_token_fn(temperature, top_k, top_p, rng)
    if getattr(workload.model, "scan_layers", False):
        from ..parallel.ring import current_mesh
        mesh = current_mesh()
        if mesh is not None and mesh.shape.get("sequence", 1) > 1:
            # sequence-sharded activations cannot thread a single-token
            # cache step; the recompute forward (ring attention via
            # "auto") decodes identically
            use_cache = False
        if getattr(workload.model, "moe_experts", 0) > 0:
            # MoEScanBlocks has no KV cache either — same identical-output
            # full-recompute fallback
            use_cache = False
    # Inference never drops MoE tokens (capacity competition is a training
    # device; per-token top-k routing at decode time is exact and makes the
    # cached and uncached paths bit-identical — models/moe.py).
    model = workload.model.clone(moe_no_drop=True)
    B, L = ids.shape
    pad = jnp.ones_like(ids)

    if not use_cache:
        def body(i, ids):
            logits = model.apply(params, ids, pad)        # [B, L, V]
            nxt = pick(logits[:, i - 1], i).astype(ids.dtype)
            return ids.at[:, i].set(nxt)

        return jax.lax.fori_loop(prompt_len, L, body, ids)

    dm = model.clone(decode=True)
    logits, vars_ = dm.apply(params, ids, pad, mutable=["cache"])
    # position argument = the index being WRITTEN (prompt_len here), so the
    # cached and uncached paths fold the same key for the same position
    first = pick(logits[:, prompt_len - 1],
                 jnp.asarray(prompt_len)).astype(ids.dtype)
    ids = ids.at[:, prompt_len].set(first) if prompt_len < L else ids

    def body(i, carry):
        ids, cache = carry
        tok = jax.lax.dynamic_slice(ids, (0, i), (B, 1))
        logits, updated = dm.apply(
            {**params, "cache": cache}, tok, None, cache_index=i,
            mutable=["cache"])
        nxt = pick(logits[:, 0], i + 1).astype(ids.dtype)
        return ids.at[:, i + 1].set(nxt), updated["cache"]

    ids, _ = jax.lax.fori_loop(prompt_len, L - 1, body,
                               (ids, vars_["cache"]))
    return ids


def gpt2_greedy_decode(workload, params, ids: jnp.ndarray,
                       prompt_len: int, use_cache: bool = True) -> jnp.ndarray:
    """Greedy continuation (``gpt2_decode`` at temperature 0)."""
    return gpt2_decode(workload, params, ids, prompt_len,
                       use_cache=use_cache)


def target_span_accuracy(pred_ids: jnp.ndarray,
                         batch: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    """Token accuracy of ``pred_ids`` against the batch's gold ids over the
    target/loss span (``input_mask & pad_mask``) — scalar f32."""
    m = (batch["input_mask"] * batch["pad_mask"]).astype(jnp.float32)
    hit = (pred_ids == batch["input_ids"]).astype(jnp.float32)
    return (hit * m).sum() / jnp.maximum(m.sum(), 1.0)


def gpt2_decode_and_score(workload, params, batch: Dict[str, jnp.ndarray],
                          prompt_len: int = 0, temperature: float = 0.0,
                          top_k: int = 0, top_p: float = 0.0,
                          rng: Optional[jax.Array] = None):
    """Decode the suffix after ``prompt_len`` (default seq_len/2; greedy by
    default, stochastic with ``temperature``/``top_k``/``top_p``) and
    score it against the gold continuation — the one span-accounting used by
    both the eval callback and run.sample. Returns (pred_ids, accuracy)."""
    plen = prompt_len or workload.seq_len // 2
    pred = gpt2_decode(workload, params, batch["input_ids"], plen,
                       temperature=temperature, top_k=top_k, top_p=top_p,
                       rng=rng)
    gen_mask = jnp.broadcast_to(
        (jnp.arange(workload.seq_len) >= plen).astype(jnp.int32), pred.shape)
    acc = target_span_accuracy(
        pred, dict(batch, input_mask=gen_mask * batch["pad_mask"]))
    return pred, acc


def gpt2_decode_accuracy(workload, params, batch: Dict[str, jnp.ndarray],
                         prompt_len: int = 0) -> jnp.ndarray:
    return gpt2_decode_and_score(workload, params, batch, prompt_len)[1]


def make_decode_callback(data: Iterator[Dict[str, np.ndarray]],
                         sample_steps: int = 32,
                         prompt_len: Optional[int] = None,
                         use_ema: str = ""):
    """An ``eval_callbacks`` entry: decode one batch and log ``decode_acc``
    (plus ``decode_acc_ema_<rate>`` when ``use_ema`` names an EMA rate).
    The jitted sampler is built once on first call and reused.

    Guard-clean under ``--sanitize``'s ``jax.transfer_guard("disallow")``
    (TrainLoop runs eval callbacks inside the guard): the base RNG key is
    built here at wiring time, the batch/key/step land on the mesh via
    explicit ``jax.device_put`` with mesh-wide shardings (an off-mesh
    committed input would force a guarded implicit reshard at dispatch),
    ``fold_in`` runs inside the jitted fn, and the accuracy comes back via
    explicit ``jax.device_get``."""
    from ..parallel.sharding import replicated, shard_batch

    cache: Dict[str, Any] = {}
    base_key = jax.random.PRNGKey(0)  # eager seed transfer; must not run
    # under the sanitizer guard, so build it at wiring time, not in-call

    def callback(loop) -> None:
        from ..utils import logger

        wl = loop.workload
        if "batch" not in cache:  # NOT setdefault: its default arg would
            # pull + device-put a fresh batch on every call just to drop it
            cache["batch"] = shard_batch(loop.mesh, next(data))
            cache["key"] = jax.device_put(base_key, replicated(loop.mesh))
        batch = cache["batch"]
        if "fn" not in cache:
            if wl.family == "diffuseq":
                cache["fn"] = jax.jit(
                    lambda p, b, k, s: target_span_accuracy(
                        diffuseq_sample(wl, p, b, jax.random.fold_in(k, s),
                                        sample_steps), b))
            else:
                cache["fn"] = jax.jit(
                    lambda p, b, k, s: gpt2_decode_accuracy(wl, p, b,
                                                            prompt_len or 0))
        step = jax.device_put(np.uint32(loop.step), replicated(loop.mesh))
        key = "decode_acc"
        params = loop.state.params
        if use_ema and use_ema in loop.state.ema:
            params = loop.state.ema[use_ema]
            key = f"decode_acc_ema_{use_ema}"
        with loop.mesh:
            acc = cache["fn"](params, batch, cache["key"], step)
        logger.logkv(key, float(jax.device_get(acc)))

    return callback
