"""Pipeline parallelism: GPipe-style stage streaming over the ``pipe`` axis.

The reference has no pipeline parallelism (SURVEY.md §2.2 — DDP only); this
exceeds it with the TPU-native formulation, built on the same machinery as
ring attention (parallel/ring.py): ``shard_map`` + ``lax.ppermute`` +
``lax.scan``, fully differentiable (reverse-mode sends the cotangents back
around the reverse permutation automatically).

Design:

* **Stacked layer weights** — :class:`PipelinedBlocks` declares every block
  parameter once with a leading ``[num_layers]`` axis carrying the
  ``layers`` logical name, which parallel/sharding.py maps onto the mesh's
  ``pipe`` axis: stage s holds the contiguous layer slice
  ``[s*L/S, (s+1)*L/S)``. With ``pipe == 1`` this degrades to a plain
  ``lax.scan`` over layers — the "scan_layers" mode, which also collapses
  compile time for deep models (one traced block instead of num_layers).
* **GPipe schedule** (this module) — the per-device batch splits into
  ``pp_chunks`` equal microchunks; at tick t, stage 0 ingests chunk t
  while stage s applies its layers to the chunk received from stage s-1
  and forwards the result via a non-cyclic ``ppermute``. After
  ``pp_chunks + S - 1`` ticks the last stage holds every output chunk; one
  masked ``psum`` replicates them back across the pipe axis. Bubble ticks
  compute on clamped garbage and are masked out of the output — compute
  stays uniform across devices (SPMD cannot branch per stage). Bubble
  fraction (S-1)/(M+S-1): growing M shrinks it, but reverse-mode AD
  through this forward-only stream saves every tick's per-layer residuals,
  so activation memory GROWS with M. That tradeoff is why training under a
  pipe mesh defaults to the **1F1B schedule** (models/schedule_1f1b.py,
  ``pp_schedule="1f1b"``): a streaming custom_vjp whose stash holds
  min(M, 2S-1) chunk inputs — constant in M — so M can grow to shrink the
  bubble. This module remains the forward/eval path (sampling under a pipe
  mesh) and the ``pp_schedule="gpipe"`` training fallback.
* **Composition** — composes with ``data``/``expert`` batch sharding AND
  with ``fsdp`` (ZeRO-3-inside-PP: each stage's weight slice shards over
  the fsdp axis on its embed dim, is all-gathered before the stage's layer
  scan, and the gather's AD transpose reduce-scatters the weight grads back
  to the shard; fsdp ranks consume distinct batch shards) AND with
  ``tensor`` (Megatron in-stage TP: heads/mlp weight dims shard over the
  tensor axis and block_fwd all-reduces the two partial projections —
  ``tp=True``) AND with ``sequence`` (ring-in-stage, r5: stage
  activations/masks shard the L dim over the sequence axis and every
  stage's attention runs the in-shard_map ring — impl "ring_shard";
  training takes the AD GPipe stream, as the 1F1B engine has no
  sequence stage path); MoE composes with the scan path via
  :class:`MoEScanBlocks` (group scan) AND with ``pipe`` > 1 on a
  {data, pipe} mesh (group stages streamed by the MoE GPipe schedule;
  the 1F1B request falls back to this AD-differentiated stream for MoE).
  KV-cache decode works in stacked mode at ``pipe == 1`` (``decode=True``,
  mirroring backbone.SelfAttention's contract) AND under ``pipe > 1``
  (``_decode_pipe``: the prefill collects pipe-sharded per-stage caches
  inside the GPipe schedule, then each token takes S masked ring hops —
  O(L) per token), INCLUDING ``tensor > 1`` (head-sharded caches, psum'd
  out/mlp projections per token — r5).

The pure-function block forward here is numerically identical to
backbone.Block (same pre-LN residual structure, f32 layernorm statistics,
bf16 matmuls) — pinned by tests/test_pipeline.py's transplant parity test.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops import dot_product_attention
from .backbone import EMBED, HEADS, KV, MLP, _dense_init

LAYERS = "layers"

# Logical axes of every stacked block weight — single source of truth for
# the init-time with_logical_partitioning annotations AND the runtime
# shard_map specs in _gpipe (fsdp shards the EMBED dim, pipe the LAYERS dim).
STACKED_AXES = {
    "ln1_scale": (LAYERS, None),
    "ln1_bias": (LAYERS, None),
    "qkv": (LAYERS, EMBED, None, HEADS, KV),
    "out": (LAYERS, HEADS, KV, EMBED),
    "ln2_scale": (LAYERS, None),
    "ln2_bias": (LAYERS, None),
    "wi": (LAYERS, EMBED, MLP),
    "wo": (LAYERS, MLP, EMBED),
}

__all__ = ["PipelinedBlocks", "MoEScanBlocks", "block_fwd", "block_attn",
           "stage_apply", "stacked_specs"]


def _resolve_impl(attention_impl: str) -> str:
    """Attention impl for code INSIDE a shard_map body: "auto"/"ring"
    would consult the ambient mesh from a manual-sharding context, so they
    resolve to the dense kernel there; explicit "pallas"/"xla" choices are
    honored, as is "ring_shard" (the schedule requested in-stage ring
    attention over a live sequence axis). (Paths outside shard_map pass
    their impl through unclamped.)"""
    return (attention_impl
            if attention_impl in ("xla", "pallas", "ring_shard") else "xla")


def gpipe_stream(x_local, mask_local, M: int, apply_stage, extra0,
                 extra_update):
    """The GPipe tick skeleton, shared by the dense and MoE schedules (one
    copy of the streaming logic — chunk/bubble masking bugs cannot diverge
    between them): stream the per-device batch as M chunks over the pipe
    axis; at tick t, stage 0 ingests chunk t while stage s applies
    ``apply_stage`` to the chunk received from stage s-1 and forwards the
    result via a non-cyclic ppermute. ``apply_stage(chunk, mask) ->
    (out, payload)``; ``extra_update(extra, payload, cidx, valid)`` folds
    each tick's payload into the carried ``extra`` (KV collection, MoE
    stats — bubble ticks arrive with valid=False). Returns
    ``(outs [B_local, L, D] — last-stage results psum-replicated over
    pipe, extra)``."""
    S = jax.lax.psum(1, "pipe")
    sid = jax.lax.axis_index("pipe")
    B, L, D = x_local.shape
    cb = B // M
    chunks = x_local.reshape(M, cb, L, D)
    mask_chunks = mask_local.reshape(M, cb, L)
    perm = [(i, i + 1) for i in range(S - 1)]

    def tick(carry, t):
        recv, outs, extra = carry
        cidx = jnp.clip(t - sid, 0, M - 1)
        valid = jnp.logical_and(t - sid >= 0, t - sid < M)
        inp = jnp.where(sid == 0, chunks[jnp.clip(t, 0, M - 1)], recv)
        out, payload = apply_stage(inp, mask_chunks[cidx])
        extra = extra_update(extra, payload, cidx, valid)
        recv_next = jax.lax.ppermute(out, "pipe", perm)
        oidx = jnp.clip(t - (S - 1), 0, M - 1)
        live = jnp.logical_and(t >= S - 1, jnp.equal(sid, S - 1))
        prev = jax.lax.dynamic_index_in_dim(outs, oidx, 0, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(live, out, prev), oidx, 0)
        return (recv_next, outs, extra), None

    outs0 = jnp.zeros((M, cb, L, D), x_local.dtype)
    (_, outs, extra), _ = jax.lax.scan(
        tick, (jnp.zeros((cb, L, D), x_local.dtype), outs0, extra0),
        jnp.arange(M + S - 1))
    # Outputs live on the last stage; replicate them across the pipe axis
    # with one masked all-reduce.
    outs = jax.lax.psum(
        jnp.where(jnp.equal(sid, S - 1), outs, jnp.zeros_like(outs)),
        "pipe")
    return outs.reshape(B, L, D), extra


def _layernorm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
               eps: float = 1e-6) -> jnp.ndarray:
    """f32 layernorm matching nn.LayerNorm(dtype=jnp.float32) defaults."""
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * scale + bias


# --- in-stage tensor parallelism (Megatron recipe) -----------------------
# tp mode "ad": raw lax.psum after the row-parallel projections; reverse-
#   mode AD through shard_map (the GPipe path) transposes it correctly.
# tp mode "manual": the f/g conjugate operator pair for code whose backward
#   is written BY HAND against identical-per-rank cotangents (the 1F1B
#   engine's jax.vjp calls): f all-reduces forward and is identity
#   backward (the arriving cotangent already is the full dL/dy — the
#   replicated downstream is ONE computation, not t); g is identity
#   forward and all-reduces backward (the replicated ln output feeds t
#   per-rank partial paths whose cotangents must sum). With f/g, every
#   non-sharded value and cotangent in the engine is identical across
#   tensor ranks and no further tensor reductions are needed.


@jax.custom_vjp
def _tp_f(y):
    return jax.lax.psum(y, "tensor")


_tp_f.defvjp(lambda y: (jax.lax.psum(y, "tensor"), None),
             lambda _, ct: (ct,))


@jax.custom_vjp
def _tp_g(x):
    return x


_tp_g.defvjp(lambda x: (x, None),
             lambda _, ct: (jax.lax.psum(ct, "tensor"),))


def _tp_ops(tp):
    """(gate_in, reduce_out) for a column->row parallel pair."""
    if tp == "manual":
        return _tp_g, _tp_f
    if tp:  # "ad" (or legacy True)
        return (lambda x: x), (lambda y: jax.lax.psum(y, "tensor"))
    return (lambda x: x), (lambda y: y)


def _block_mlp(lp: Dict[str, jnp.ndarray], x: jnp.ndarray,
               dtype: jnp.dtype, tp=False) -> jnp.ndarray:
    gate, reduce_ = _tp_ops(tp)
    h = gate(_layernorm(x, lp["ln2_scale"], lp["ln2_bias"]).astype(dtype))
    h = jnp.einsum("bld,dm->blm", h, lp["wi"].astype(dtype))
    h = nn.gelu(h, approximate=True)
    y = reduce_(jnp.einsum("blm,md->bld", h, lp["wo"].astype(dtype)))
    return x + y


def block_attn(lp: Dict[str, jnp.ndarray], x: jnp.ndarray,
               pad_mask: Optional[jnp.ndarray], *, num_heads: int,
               dtype: jnp.dtype, causal: bool, attention_impl: str = "xla",
               tp=False):
    """The pre-LN attention half of a block (ln1 + self-attention +
    residual) as a pure function; returns ``(x, (k, v))``. ``tp`` (only
    valid inside a shard_map body with a live ``tensor`` axis, see
    ``_tp_ops``) runs Megatron-style: ``lp``'s heads dim holds this
    rank's H/t heads and the out-projection's partial sums are
    all-reduced over ``tensor``."""
    gate, reduce_ = _tp_ops(tp)
    h = gate(_layernorm(x, lp["ln1_scale"], lp["ln1_bias"]).astype(dtype))
    qkv = jnp.einsum("bld,dthk->tbhlk", h, lp["qkv"].astype(dtype))
    o = dot_product_attention(qkv[0], qkv[1], qkv[2], pad_mask,
                              causal=causal, impl=attention_impl)
    y = reduce_(jnp.einsum("bhlk,hkd->bld", o, lp["out"].astype(dtype)))
    return x + y, (qkv[1], qkv[2])


def block_fwd(lp: Dict[str, jnp.ndarray], x: jnp.ndarray,
              pad_mask: Optional[jnp.ndarray], *, num_heads: int,
              dtype: jnp.dtype, causal: bool,
              attention_impl: str = "xla", return_kv: bool = False,
              tp=False):
    """One pre-LN transformer block as a pure function of its param dict
    (the stacked-per-layer slice) — the math of backbone.Block.
    ``return_kv=True`` also returns this layer's (k, v) [B, H, L, Dh]
    (the KV-cache prefill path); ``tp`` see :func:`block_attn`."""
    x, kv = block_attn(lp, x, pad_mask, num_heads=num_heads, dtype=dtype,
                       causal=causal, attention_impl=attention_impl, tp=tp)
    out = _block_mlp(lp, x, dtype, tp=tp)
    if return_kv:
        return out, kv
    return out


def block_decode_step(lp: Dict[str, jnp.ndarray], x: jnp.ndarray,
                      ck: jnp.ndarray, cv: jnp.ndarray, idx: jnp.ndarray,
                      live: jnp.ndarray, *, num_heads: int,
                      dtype: jnp.dtype, tp=False):
    """Single-token step of one block against its KV cache: write position
    ``idx`` of ``ck``/``cv`` [B, H, Lmax, Dh], attend the one query to the
    live prefix (``live`` [B, Lmax] — causality IS this mask for one query
    row), return (out [B, 1, D], ck, cv). Mirrors
    backbone.SelfAttention._cached_attention for stacked weights. ``tp``
    (Megatron in-stage TP inside a shard_map body): ``lp`` holds H/t
    heads and M/t mlp columns, the cache is head-sharded alike, and the
    out/mlp partial projections all-reduce over ``tensor`` (decode has
    no backward, so the raw-psum "ad" mode is the right one)."""
    gate, reduce_ = _tp_ops(tp)
    h = gate(_layernorm(x, lp["ln1_scale"], lp["ln1_bias"]).astype(dtype))
    qkv = jnp.einsum("bld,dthk->tbhlk", h, lp["qkv"].astype(dtype))
    q, k, v = qkv[0], qkv[1], qkv[2]                  # [B, H, 1, Dh]
    ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, 0, idx, 0))
    cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, 0, idx, 0))
    o = dot_product_attention(q, ck, cv, live, causal=False, impl="xla")
    x = x + reduce_(jnp.einsum("bhlk,hkd->bld", o, lp["out"].astype(dtype)))
    return _block_mlp(lp, x, dtype, tp=tp), ck, cv


class MoEScanBlocks(nn.Module):
    """Stacked (scan_layers) blocks where every ``moe_every``-th block's
    MLP is a top-k routed mixture of experts: one scan over
    ``G = num_layers / moe_every`` GROUPS, each group tracing
    ``moe_every - 1`` dense blocks (inner scan) plus one MoE block —
    the static branch pattern that makes MoE-every-k expressible under a
    layer scan (a single homogeneous stack cannot alternate MLP kinds).

    Expert parallelism composes: the stacked expert weights carry the
    ``expert`` logical dim (-> mesh expert axis) exactly like the
    named-blocks MoEMlp, and the MoE math IS moe_mlp_fwd — the same pure
    function named blocks call, so parity holds by construction (pinned
    by tests/test_pipeline.py's transplant test). ``pipe > 1`` streams
    the G groups as pipeline stages over a {data, pipe} mesh (``_gpipe``
    below; fsdp/tensor/expert inside MoE stages are future work) and
    there is no KV-cache decode path (sampling falls back to the
    full-recompute forward, models/sampling.py)."""

    num_layers: int
    num_heads: int
    hidden_size: int
    dtype: jnp.dtype = jnp.bfloat16
    causal: bool = False
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_every: int = 2
    moe_no_drop: bool = False
    capacity_factor: float = 1.25  # MoEMlp's default — parity
    remat: bool = False
    attention_impl: str = "auto"
    scan_unroll: int = 0  # layer-scan unroll knob (scan_unroll_for)
    pp_chunks: int = 4  # GPipe microchunks under a pipe > 1 mesh

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 pad_mask: Optional[jnp.ndarray] = None,
                 cache_index: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        from .moe import EXPERT

        if cache_index is not None:
            raise ValueError("MoE scan blocks have no KV-cache decode "
                             "path; sample with use_cache=False")
        from ..parallel.ring import current_mesh
        mesh = current_mesh()
        Lc, D, H = self.num_layers, self.hidden_size, self.num_heads
        assert D == x.shape[-1], (D, x.shape)
        Dh, M, E = D // H, 4 * D, self.moe_experts
        me = self.moe_every
        if Lc % me:
            raise ValueError(f"num_layers {Lc} not divisible by moe_every "
                             f"{me} (scan groups must be uniform)")
        G, nd = Lc // me, me - 1

        per_layer = {
            "ln1_scale": (nn.initializers.ones, (D,)),
            "ln1_bias": (nn.initializers.zeros, (D,)),
            "qkv": (_dense_init(D), (D, 3, H, Dh)),
            "out": (_dense_init(D), (H, Dh, D)),
            "ln2_scale": (nn.initializers.ones, (D,)),
            "ln2_bias": (nn.initializers.zeros, (D,)),
        }
        dense_shapes = {**per_layer,
                        "wi": (_dense_init(D), (D, M)),
                        "wo": (_dense_init(M), (M, D))}
        dense_lp = {
            name: self.param(
                f"dense_{name}", nn.with_logical_partitioning(
                    init, (LAYERS, None) + STACKED_AXES[name][1:]),
                (G, nd) + shape, jnp.float32)
            for name, (init, shape) in dense_shapes.items()} if nd else {}
        moe_lp = {
            name: self.param(
                f"moe_{name}", nn.with_logical_partitioning(
                    init, STACKED_AXES[name]),
                (G,) + shape, jnp.float32)
            for name, (init, shape) in per_layer.items()}
        moe_lp["router"] = self.param(
            "moe_router", nn.with_logical_partitioning(
                _dense_init(D), (LAYERS, EMBED, None)),
            (G, D, E), jnp.float32)
        moe_lp["wi"] = self.param(
            "moe_wi", nn.with_logical_partitioning(
                _dense_init(D), (LAYERS, EXPERT, EMBED, MLP)),
            (G, E, D, M), jnp.float32)
        moe_lp["wo"] = self.param(
            "moe_wo", nn.with_logical_partitioning(
                _dense_init(M), (LAYERS, EXPERT, MLP, EMBED)),
            (G, E, M, D), jnp.float32)

        S = mesh.shape.get("pipe", 1) if mesh is not None else 1
        lp = {f"dense_{k}": v for k, v in dense_lp.items()}
        lp.update({f"moe_{k}": v for k, v in moe_lp.items()})
        if S > 1 and not self.is_initializing():
            if G % S:
                raise ValueError(f"MoE group count {G} (num_layers "
                                 f"{Lc} / moe_every {me}) not divisible "
                                 f"by pipe axis {S}")
            x, aux = self._gpipe(mesh, S, lp, x, pad_mask)
        else:
            # pipe == 1: the SAME stage function over the whole stack
            # (impl passed through unclamped — "auto"/"ring" are valid
            # outside shard_map), aux from its raw stats with no psums
            x, stats = moe_stage_apply(
                lp, x, pad_mask, num_heads=H, dtype=self.dtype,
                causal=self.causal, attention_impl=self.attention_impl,
                remat=self.remat, moe_top_k=self.moe_top_k,
                capacity_factor=self.capacity_factor,
                moe_no_drop=self.moe_no_drop,
                scan_unroll=self.scan_unroll)
            aux = moe_aux_from_stats(stats, ())
        self.sow("losses", "moe_aux", aux,
                 init_fn=lambda: jnp.zeros(()), reduce_fn=jnp.add)
        return x

    def _gpipe(self, mesh, S, lp, x, pad_mask):
        """GPipe streaming of MoE group stages over the pipe axis (forward;
        reverse-mode AD differentiates through, same contract as
        PipelinedBlocks._gpipe). Returns ``(out, aux)`` where ``aux`` is
        the Switch load-balance loss formed from GLOBAL statistics: raw
        (F, P, n) sums accumulate across chunks in the schedule carry
        (differentiable — AD owns the whole stream), are psum'd over the
        data axis after the scan, and the per-stage group terms sum over
        pipe — so the value (and its router gradient) is identical to a
        pure-DP run over the same global batch, independent of the
        chunking. fsdp/tensor/expert/sequence axes are rejected by
        moe_stacked_specs (v1 composes {data, pipe} only)."""
        from ..utils.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P

        pspec = moe_stacked_specs(mesh, lp)
        batch_axes = ("data",) if mesh.shape["data"] > 1 else ()
        B = x.shape[0]
        n_b = mesh.shape["data"]
        if B % n_b:
            raise ValueError(f"global batch {B} not divisible by data "
                             f"axis {n_b}")
        M = self.pp_chunks
        if (B // n_b) % M:
            raise ValueError(
                f"per-shard batch {B // n_b} not divisible by pp_chunks "
                f"{M}")
        x3 = P(batch_axes or None, None, None)
        m2 = P(batch_axes or None, None)
        fn = shard_map(
            functools.partial(self._moe_schedule, M=M,
                              batch_axes=batch_axes),
            mesh=mesh,
            in_specs=(pspec, x3, m2),
            out_specs=(x3, P()),
            check_vma=False)
        if pad_mask is None:
            pad_mask = jnp.ones(x.shape[:2], jnp.int32)
        return fn(lp, x, pad_mask)

    def _moe_schedule(self, lp_local, x_local, mask_local, *, M: int,
                      batch_axes):
        """Per-device MoE GPipe schedule body (shard_map): the shared
        gpipe_stream skeleton with a stats-accumulation payload."""
        def apply_stage(h, mask):
            return moe_stage_apply(
                lp_local, h, mask, num_heads=self.num_heads,
                dtype=self.dtype, causal=self.causal,
                attention_impl=_resolve_impl(self.attention_impl),
                remat=self.remat, moe_top_k=self.moe_top_k,
                capacity_factor=self.capacity_factor,
                moe_no_drop=self.moe_no_drop,
                scan_unroll=self.scan_unroll)

        def accumulate(st_acc, stats, cidx, valid):
            del cidx
            return jax.tree_util.tree_map(
                lambda acc, s: acc + jnp.where(valid, s, 0.0), st_acc,
                stats)

        Gl = next(iter(lp_local.values())).shape[0]
        E = self.moe_experts
        st0 = (jnp.zeros((Gl, E), jnp.float32),
               jnp.zeros((Gl, E), jnp.float32),
               jnp.zeros((), jnp.float32))
        outs, st_acc = gpipe_stream(x_local, mask_local, M, apply_stage,
                                    st0, accumulate)
        # each stage accumulated ITS groups' raw stats over every chunk;
        # psum over data makes them global, the pipe psum completes the
        # sum over groups
        aux = jax.lax.psum(moe_aux_from_stats(st_acc, batch_axes), "pipe")
        return outs, aux


def scan_unroll_for(n_steps: int, knob: int = 0,
                    total: Optional[int] = None) -> int:
    """Resolve the unroll factor for a stacked-layer scan of ``n_steps``.

    A true ``lax.scan`` backward materializes every residual crossing the
    loop boundary as stacked HBM buffers — XLA cannot rematerialize or
    fuse across a while-loop, so the scanned step pays ~1.6x the unrolled
    backward at the bench shape (measured v5e, 12-layer diffuseq-base
    seq128: 40.9 ms vs 25.6 ms fwd+bwd; the fwd is equal). Full unroll
    inside the scan restores the unrolled graph's fusion/remat freedom
    while KEEPING the stacked weight layout pipe/fsdp sharding needs —
    at 6x the compile time (18.7 s vs 3.0 s at 12 layers).

    ``knob`` semantics (the ``scan_unroll`` config): 0 = auto — fully
    unroll stacks of <= 16 steps, keep longer stacks as true scans (their
    compile time is the reason scan mode exists); explicit values clamp
    to the stack length. ``total`` overrides the auto threshold's measure
    of stack depth when one scan step traces MORE than one layer (the MoE
    group scan: G groups x moe_every layers each must compare total
    traced layers, not G, or deep MoE stacks would fully unroll).
    NOTE: partial factors measured PATHOLOGICAL on
    TPU (unroll 2/4: 80-94 ms at the same shape — the multi-slice gathers
    copy the stacked buffers per iteration); prefer 1 or full."""
    if knob <= 0:
        return n_steps if (total if total is not None else n_steps) <= 16 \
            else 1
    return min(knob, n_steps)


def stacked_specs(mesh, lp: Dict[str, jnp.ndarray]):
    """shard_map PartitionSpecs for stacked stage weights, plus the fsdp
    gather map and the in-stage-TP flag: ``pipe`` on the layers dim,
    ``fsdp`` on the embed dim (when divisible — mirroring
    sharding.param_shardings' fallback), and ``tensor`` on the heads/mlp
    dims (Megatron in-stage TP; tensor > 1 demands exact divisibility —
    silently replicating would make block_fwd's tp psums double-count).
    Shared by the GPipe schedule and the 1F1B engine so the weight layout
    rules exist once."""
    from jax.sharding import PartitionSpec as P

    F, T = mesh.shape["fsdp"], mesh.shape["tensor"]
    gather = {k: d for k, d in PipelinedBlocks._FSDP_DIM.items()
              if F > 1 and lp[k].shape[d] % F == 0}
    if T > 1:
        H, M = lp["qkv"].shape[3], lp["wi"].shape[2]
        if H % T or M % T:
            raise ValueError(
                f"in-stage tensor parallelism needs heads ({H}) and the "
                f"mlp width ({M}) divisible by the tensor axis ({T})")

    def wspec(name):
        axes = STACKED_AXES[name]
        dims = ["pipe"] + [None] * (len(axes) - 1)
        if name in gather:
            dims[gather[name]] = "fsdp"
        if T > 1:
            for i, ax in enumerate(axes):
                if ax in (HEADS, MLP):
                    dims[i] = "tensor"
        return P(*dims)

    return {k: wspec(k) for k in lp}, gather, T > 1


def stage_apply(lp_local, h, mask, *, num_heads: int, dtype, causal: bool,
                attention_impl: str, remat: bool, gather: Dict[str, int],
                tp=False, return_kv: bool = False, scan_unroll: int = 0):
    """Apply one pipeline stage's stacked layer slice to ``h``:
    ``block_fwd`` scanned over the leading layers dim. ``gather`` maps
    weight names to their fsdp-sharded dim (STACKED_AXES embed dims);
    non-remat gathers the whole stage stack once up front, remat gathers
    per-layer INSIDE the checkpointed body so gathered weights are
    rematerialized in the backward instead of saved as residuals. Shared
    by the GPipe schedule below and the 1F1B schedule
    (models/schedule_1f1b.py) so the two paths cannot diverge.
    ``return_kv=True`` additionally returns this stage's per-layer
    (k, v) stacks [L_loc, B, H, L, Dh] — the pipe-sharded KV-cache
    prefill (``_decode_pipe``)."""
    impl = _resolve_impl(attention_impl)
    if gather and not remat:
        lp_local = {
            k: (jax.lax.all_gather(v, "fsdp", axis=gather[k], tiled=True)
                if k in gather else v)
            for k, v in lp_local.items()}
        gather = {}

    def layer(h, one):
        if gather:
            # per-layer slices lost the leading layers dim -> axis-1
            one = {
                k: (jax.lax.all_gather(v, "fsdp", axis=gather[k] - 1,
                                       tiled=True) if k in gather else v)
                for k, v in one.items()}
        out = block_fwd(one, h, mask, num_heads=num_heads, dtype=dtype,
                        causal=causal, attention_impl=impl, tp=tp,
                        return_kv=return_kv)
        return out if return_kv else (out, None)

    if remat:
        layer = jax.checkpoint(layer, prevent_cse=False)
    n_loc = next(iter(lp_local.values())).shape[0]
    h, kv = jax.lax.scan(layer, h, lp_local,
                         unroll=scan_unroll_for(n_loc, scan_unroll))
    return (h, kv) if return_kv else h


def moe_stacked_specs(mesh, lp: Dict[str, jnp.ndarray]):
    """shard_map PartitionSpecs for stacked MoE group weights under a pipe
    mesh: ``pipe`` on the groups dim (dim 0) of every leaf. Composition
    v1 is {data, pipe} only — fsdp/tensor/expert inside MoE stages
    (ZeRO-3 gathers, Megatron expert TP, all-to-all expert dispatch
    across shard_map ranks) are rejected loudly rather than silently
    computed wrong."""
    from jax.sharding import PartitionSpec as P

    for ax in ("fsdp", "tensor", "expert", "sequence"):
        if mesh.shape[ax] > 1:
            raise ValueError(
                f"MoE x pipe composes with the data axis only (v1); mesh "
                f"has {ax}={mesh.shape[ax]}")
    return {k: P(*(["pipe"] + [None] * (v.ndim - 1))) for k, v in lp.items()}


def moe_stage_apply(lp_local, h, mask, *, num_heads: int, dtype,
                    causal: bool, attention_impl: str, remat: bool,
                    moe_top_k: int, capacity_factor: float,
                    moe_no_drop: bool, scan_unroll: int = 0):
    """Apply one MoE GROUP slice to ``h``: ``lp_local`` holds ``dense_*``
    [Gl, nd, ...] and ``moe_*`` [Gl, ...] stacked weights (the
    MoEScanBlocks layout; under pipe, this stage's pipe-shard of the
    groups dim). Returns ``(h, (F [Gl, E], P [Gl, E], n))`` — the RAW
    per-group load-balance sums over the LOCAL batch (moe_mlp_fwd
    return_stats contract: only P differentiable). Shared by the pipe==1
    group scan and the MoE GPipe schedule, so the two paths cannot
    diverge (the 1F1B request falls back to that AD GPipe stream for
    MoE — there is no manual-vjp MoE stage_fn). ``attention_impl`` must
    arrive pre-resolved: shard_map callers clamp "auto"/"ring" to the
    dense kernel, the pipe==1 path passes its impl through unclamped.
    The auto-unroll threshold measures THIS CALL's traced depth
    (Gl * (nd + 1) layers — per stage under pipe, the whole stack at
    pipe==1)."""
    from .moe import moe_mlp_fwd

    dense_loc = {k[len("dense_"):]: v for k, v in lp_local.items()
                 if k.startswith("dense_")}
    moe_loc = {k[len("moe_"):]: v for k, v in lp_local.items()
               if k.startswith("moe_")}
    nd = next(iter(dense_loc.values())).shape[1] if dense_loc else 0
    Gl = next(iter(moe_loc.values())).shape[0]
    traced = Gl * (nd + 1)

    def group(h, xs):
        dlp, mlp_ = xs

        def dense_layer(h, one):
            return block_fwd(one, h, mask, num_heads=num_heads, dtype=dtype,
                             causal=causal,
                             attention_impl=attention_impl), None

        def moe_block(h):
            h, _ = block_attn(mlp_, h, mask, num_heads=num_heads,
                              dtype=dtype, causal=causal,
                              attention_impl=attention_impl)
            hh = _layernorm(h, mlp_["ln2_scale"],
                            mlp_["ln2_bias"]).astype(dtype)
            y, stats, _ = moe_mlp_fwd(
                {"router": mlp_["router"], "wi": mlp_["wi"],
                 "wo": mlp_["wo"]}, hh, mask, top_k=moe_top_k,
                capacity_factor=capacity_factor, dtype=dtype,
                no_drop=moe_no_drop, return_stats=True)
            return h + y, stats

        if remat:
            dense_layer = jax.checkpoint(dense_layer, prevent_cse=False)
            moe_block = jax.checkpoint(moe_block, prevent_cse=False)
        if nd:
            h, _ = jax.lax.scan(
                dense_layer, h, dlp,
                unroll=scan_unroll_for(nd, scan_unroll, total=traced))
        h, stats = moe_block(h)
        return h, stats

    h, (F, P, n) = jax.lax.scan(
        group, h, (dense_loc, moe_loc),
        unroll=scan_unroll_for(Gl, scan_unroll, total=traced))
    return h, (F, P, n[0])  # n identical per group (same chunk mask)


def moe_aux_from_stats(stats, batch_axes):
    """Switch load-balance loss from raw (possibly chunk-accumulated)
    stats, GLOBAL over the mesh's batch shards: psum (F, P, n) over
    ``batch_axes``, then ``E * sum_(g,e) (F/n)(P/n)`` — this stage's
    groups' contribution (sum over pipe happens once per schedule)."""
    F, P, n = stats
    if batch_axes:
        F, P, n = jax.lax.psum((F, P, n), batch_axes)
    E = F.shape[-1]
    n = jnp.maximum(n, 1.0)
    return E * jnp.sum((F / n) * (P / n))


class PipelinedBlocks(nn.Module):
    """num_layers pre-LN blocks with stacked weights; sequential layer scan
    at ``pipe == 1``, GPipe streaming at ``pipe > 1`` (module docstring)."""

    num_layers: int
    num_heads: int
    hidden_size: int
    dtype: jnp.dtype = jnp.bfloat16
    causal: bool = False
    pp_chunks: int = 4
    attention_impl: str = "xla"
    remat: bool = False
    decode: bool = False  # KV-cache generation (scan_layers, pipe == 1)
    scan_unroll: int = 0  # layer-scan unroll knob (scan_unroll_for)

    # NOTE: the pipe == 1 scan path runs OUTSIDE shard_map and passes
    # self.attention_impl through unclamped, so "auto" still picks flash
    # at long context / ring under a sequence mesh; shard_map bodies
    # resolve via _resolve_impl.

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 pad_mask: Optional[jnp.ndarray] = None,
                 cache_index: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        Lc, D, H = self.num_layers, self.hidden_size, self.num_heads
        assert D == x.shape[-1], (D, x.shape)
        Dh = D // H
        shapes = {
            "ln1_scale": (nn.initializers.ones, (Lc, D)),
            "ln1_bias": (nn.initializers.zeros, (Lc, D)),
            "qkv": (_dense_init(D), (Lc, D, 3, H, Dh)),
            "out": (_dense_init(D), (Lc, H, Dh, D)),
            "ln2_scale": (nn.initializers.ones, (Lc, D)),
            "ln2_bias": (nn.initializers.zeros, (Lc, D)),
            "wi": (_dense_init(D), (Lc, D, 4 * D)),
            "wo": (_dense_init(4 * D), (Lc, 4 * D, D)),
        }
        lp = {
            name: self.param(name, nn.with_logical_partitioning(
                init, STACKED_AXES[name]), shape, jnp.float32)
            for name, (init, shape) in shapes.items()}

        from ..parallel.ring import current_mesh
        mesh = current_mesh()
        S = mesh.shape.get("pipe", 1) if mesh is not None else 1
        if self.decode and not self.is_initializing():
            if S > 1:
                return self._decode_pipe(mesh, S, lp, x, pad_mask,
                                         cache_index)
            return self._decode(lp, x, pad_mask, cache_index)
        if S <= 1 or self.is_initializing():
            # init traces with a tiny dummy batch that can't be chunked;
            # param shapes are identical either way.
            # scan_layers mode: one traced block, sequential over the stack.
            def layer(h, one):
                return block_fwd(one, h, pad_mask, num_heads=H,
                                 dtype=self.dtype, causal=self.causal,
                                 attention_impl=self.attention_impl), None

            if self.remat:
                layer = jax.checkpoint(layer, prevent_cse=False)
            x, _ = jax.lax.scan(layer, x, lp,
                                unroll=scan_unroll_for(Lc, self.scan_unroll))
            return x
        return self._gpipe(mesh, S, lp, x, pad_mask)

    def _check_prefill_len(self, L: int) -> None:
        if self.has_variable("cache", "key"):
            # the named-blocks contract (backbone.py): full length is
            # prefill, one token is a step — anything else is a bug;
            # silently re-prefilling at a shorter L would clamp later
            # cache writes into garbage continuations
            Lmax = self.get_variable("cache", "key").shape[3]
            if L != Lmax:
                raise ValueError(
                    f"decode calls take the full length ({Lmax}, "
                    f"prefill) or a single token, got {L}")

    def _cache_step_inputs(self, B, pad_mask, cache_index):
        """Shared single-token contract for BOTH decode paths (pipe == 1
        and _decode_pipe): the cache variables, the int32 write index, and
        the live-prefix mask (causality for one query row, intersected
        with padding)."""
        if cache_index is None:
            raise ValueError("single-token decode needs cache_index")

        def _no_prefill():
            raise ValueError("single-token decode before prefill: call the "
                             "model once at full length first")

        ck = self.variable("cache", "key", _no_prefill)
        cv = self.variable("cache", "value", _no_prefill)
        Lmax = ck.value.shape[3]
        idx = jnp.asarray(cache_index, jnp.int32)
        live = jnp.broadcast_to(
            (jnp.arange(Lmax) <= idx).astype(jnp.int32)[None], (B, Lmax))
        if pad_mask is not None:
            live = live * pad_mask
        return ck, cv, idx, live

    def _decode(self, lp, x, pad_mask, cache_index):
        """KV-cache generation over the stacked layers: a full-length call
        is the PREFILL (normal causal scan that also stores every layer's
        K/V, [Lc, B, H, Lmax, Dh]); an L==1 call writes position
        ``cache_index`` in every layer's cache and attends the single
        query to the live prefix — mirroring backbone.SelfAttention's
        decode contract for named blocks."""
        B, L, D = x.shape
        H = self.num_heads

        if L > 1:  # prefill
            self._check_prefill_len(L)

            def layer(h, one):
                out, kv = block_fwd(one, h, pad_mask, num_heads=H,
                                    dtype=self.dtype, causal=True,
                                    attention_impl=self.attention_impl,
                                    return_kv=True)
                return out, kv

            x, (ks, vs) = jax.lax.scan(layer, x, lp)
            self.variable("cache", "key", lambda: ks).value = ks
            self.variable("cache", "value", lambda: vs).value = vs
            return x
        ck, cv, idx, live = self._cache_step_inputs(B, pad_mask, cache_index)

        def layer(h, xs):
            one, k_l, v_l = xs
            out, k_l, v_l = block_decode_step(
                one, h, k_l, v_l, idx, live, num_heads=H, dtype=self.dtype)
            return out, (k_l, v_l)

        x, (ks, vs) = jax.lax.scan(layer, x, (lp, ck.value, cv.value))
        ck.value, cv.value = ks, vs
        return x

    def _decode_pipe(self, mesh, S, lp, x, pad_mask, cache_index):
        """KV-cache generation under a ``pipe > 1`` mesh.

        PREFILL (full-length call): the GPipe schedule runs with
        ``collect_kv`` — each stage stores its OWN layers' K/V for every
        chunk it streams, so the cache comes out naturally pipe-sharded
        on its layers dim ([Lc, B, H, Lmax, Dh] globally, the same layout
        as the pipe == 1 path). STEP (single-token call): the token takes
        S masked hops around the pipe ring — every stage runs its local
        cached-decode layer scan each hop, a ``where`` on stage == hop
        keeps only the active stage's result, and a cyclic ``ppermute``
        advances the activation; after S hops the final hidden state is
        broadcast back with one masked psum. O(L) per token instead of
        the O(L^2) full-recompute fallback. Under ``tensor > 1`` the
        caches are additionally head-sharded (each rank stores its H/t
        heads) and every decode step all-reduces the out/mlp partial
        projections (block_decode_step tp mode)."""
        B, L, D = x.shape

        if L > 1:  # prefill
            self._check_prefill_len(L)
            out, ks, vs = self._gpipe(mesh, S, lp, x, pad_mask,
                                      collect_kv=True)
            self.variable("cache", "key", lambda: ks).value = ks
            self.variable("cache", "value", lambda: vs).value = vs
            return out
        ck, cv, idx, live = self._cache_step_inputs(B, pad_mask, cache_index)
        out, ck.value, cv.value = self._pipe_step(
            mesh, S, lp, x, ck.value, cv.value, live, idx)
        return out

    def _pipe_step(self, mesh, S, lp, x, ck, cv, live, idx):
        """One decode token through the pipe ring (docstring above)."""
        from ..utils.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P

        pspec, gather, tp = stacked_specs(mesh, lp)
        tp = "ad" if tp else False  # decode has no backward: raw psums
        batch_axes = tuple(a for a in ("data", "fsdp", "expert")
                           if mesh.shape[a] > 1)
        x3 = P(batch_axes or None, None, None)
        # the cache is pipe-sharded on its layers dim AND (under TP)
        # head-sharded on dim 2 — each tensor rank stores only its heads
        kv5 = P("pipe", batch_axes or None,
                "tensor" if tp else None, None, None)
        m2 = P(batch_axes or None, None)
        H = self.num_heads
        perm = [(i, (i + 1) % S) for i in range(S)]

        def body(lp_local, h, ck_l, cv_l, live_l, idx_):
            sid = jax.lax.axis_index("pipe")
            if gather:  # fsdp-sharded weights: gather the stage stack once
                lp_local = {
                    k: (jax.lax.all_gather(v, "fsdp", axis=gather[k],
                                           tiled=True)
                        if k in gather else v)
                    for k, v in lp_local.items()}

            def hop(carry, s):
                h, ck_h, cv_h = carry

                def lstep(hh, xs):
                    one, k_l, v_l = xs
                    out, k_l, v_l = block_decode_step(
                        one, hh, k_l, v_l, idx_, live_l, num_heads=H,
                        dtype=self.dtype, tp=tp)
                    return out, (k_l, v_l)

                h2, (ck2, cv2) = jax.lax.scan(lstep, h, (lp_local, ck_h,
                                                         cv_h))
                act = jnp.equal(sid, s)
                h = jnp.where(act, h2, h)
                ck_h = jnp.where(act, ck2, ck_h)
                cv_h = jnp.where(act, cv2, cv_h)
                # cyclic shift: stage s's processed activation lands on
                # stage s+1 for the next hop
                h = jax.lax.ppermute(h, "pipe", perm)
                return (h, ck_h, cv_h), None

            (h, ck_l, cv_l), _ = jax.lax.scan(
                hop, (h, ck_l, cv_l), jnp.arange(S))
            # after S cyclic shifts the last stage's output sits on stage 0
            h = jax.lax.psum(
                jnp.where(jnp.equal(sid, 0), h, jnp.zeros_like(h)), "pipe")
            return h, ck_l, cv_l

        fn = shard_map(
            body, mesh=mesh,
            in_specs=(pspec, x3, kv5, kv5, m2, P()),
            out_specs=(x3, kv5, kv5),
            check_vma=False)
        return fn(lp, x, ck, cv, live, idx)

    # Which dim of each stacked weight carries the EMBED logical name —
    # the dim FSDP shards (parallel/sharding.py LOGICAL_RULES: embed->fsdp).
    # LayerNorm params have no embed dim and stay replicated over fsdp.
    _FSDP_DIM = {k: axes.index(EMBED) for k, axes in STACKED_AXES.items()
                 if EMBED in axes}

    def _gpipe(self, mesh, S, lp, x, pad_mask, collect_kv: bool = False):
        from ..utils.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P

        seq = mesh.shape["sequence"] > 1
        if seq and collect_kv:
            raise ValueError(
                "KV-cache decode does not compose with sequence "
                "parallelism; the sampler falls back to the recompute "
                "forward")
        if seq and x.shape[1] % mesh.shape["sequence"]:
            raise ValueError(
                f"seq_len {x.shape[1]} not divisible by sequence axis "
                f"{mesh.shape['sequence']} (ring attention needs equal "
                f"L shards)")
        if self.num_layers % S:
            raise ValueError(f"num_layers {self.num_layers} not divisible "
                             f"by pipe axis {S}")
        B = x.shape[0]
        batch_axes = tuple(a for a in ("data", "fsdp", "expert")
                           if mesh.shape[a] > 1)
        n_b = 1
        for a in batch_axes:
            n_b *= mesh.shape[a]
        if B % n_b:
            # raising beats silently replicating the batch over a dropped
            # axis (which would hide the misconfiguration as 1/n throughput)
            raise ValueError(
                f"global batch {B} not divisible by data x fsdp x expert "
                f"axes product {n_b}")
        M = self.pp_chunks
        if (B // n_b) % M:
            raise ValueError(
                f"per-shard batch {B // n_b} not divisible by pp_chunks {M}")
        # ZeRO-3-inside-PP + Megatron-in-stage-TP: each stage's weight
        # slice additionally shards over fsdp on its embed dim (gathered
        # before the layer scan; AD's transpose reduce-scatters the weight
        # grads) and over tensor on its heads/mlp dims (block_fwd's tp
        # psums all-reduce the partial projections). FSDP ranks consume
        # distinct batch shards; tensor ranks share one.
        pspec, gather, tp = stacked_specs(mesh, lp)
        tp = "ad" if tp else False  # shard_map AD transposes raw psums
        # ring-in-stage: the sequence axis shards the L dim of
        # activations and masks; each stage's attention rings over it
        sq = "sequence" if seq else None
        x3 = P(batch_axes or None, sq, None)
        m2 = P(batch_axes or None, sq)

        kv5 = P("pipe", batch_axes or None,
                "tensor" if tp else None, None, None)
        fn = shard_map(
            functools.partial(self._schedule, M=M, gather=gather, tp=tp,
                              collect_kv=collect_kv, seq=seq),
            mesh=mesh,
            in_specs=(pspec, x3, m2),
            out_specs=(x3, kv5, kv5) if collect_kv else x3,
            check_vma=False)
        if pad_mask is None:
            pad_mask = jnp.ones(x.shape[:2], jnp.int32)
        return fn(lp, x, pad_mask)

    def _schedule(self, lp_local, x_local, mask_local, *, M: int,
                  gather: Dict[str, int], tp=False,
                  collect_kv: bool = False, seq: bool = False):
        # tp domain: False | "ad" | "manual" — see _tp_ops
        """Per-device GPipe schedule (the shared gpipe_stream skeleton
        with an optional KV-collection payload); lp_local holds THIS
        stage's layers (fsdp-sharded weights are all-gathered before use;
        the transpose of the gather reduce-scatters their grads — ZeRO-3
        semantics).

        Gather placement: without remat, the whole stage stack is gathered
        once up front — OUTSIDE the tick scan, one gather for all ticks
        (stage_apply's own stage-wide gather would re-run per tick). With
        remat, stage_apply gathers per-layer INSIDE the checkpointed scan
        body so the fully-gathered weights are rematerialized rather than
        saved as residuals: peak resident weight memory stays at the 1/F
        shard, at the price of re-gathering each layer in the backward."""
        if not self.remat:
            lp_local = {
                k: (jax.lax.all_gather(v, "fsdp", axis=gather[k], tiled=True)
                    if k in gather else v)
                for k, v in lp_local.items()}
            gather = {}
        B, L, D = x_local.shape

        impl = "ring_shard" if seq else _resolve_impl(self.attention_impl)

        def apply_stage(h, mask):
            out = stage_apply(lp_local, h, mask, num_heads=self.num_heads,
                              dtype=self.dtype, causal=self.causal,
                              attention_impl=impl,
                              remat=self.remat, gather=gather, tp=tp,
                              return_kv=collect_kv,
                              scan_unroll=self.scan_unroll)
            return out if collect_kv else (out, None)

        def update_kv(extra, payload, cidx, valid):
            if not collect_kv:
                return extra
            ckb, cvb = extra
            ks, vs = payload
            # this stage's layers' K/V for chunk cidx (bubble ticks keep
            # the previous slot contents)
            pk = jax.lax.dynamic_index_in_dim(ckb, cidx, 1, keepdims=False)
            pv = jax.lax.dynamic_index_in_dim(cvb, cidx, 1, keepdims=False)
            ckb = jax.lax.dynamic_update_index_in_dim(
                ckb, jnp.where(valid, ks, pk), cidx, 1)
            cvb = jax.lax.dynamic_update_index_in_dim(
                cvb, jnp.where(valid, vs, pv), cidx, 1)
            return ckb, cvb

        L_loc = jax.tree_util.tree_leaves(lp_local)[0].shape[0]
        Dh = D // self.num_heads
        cb = B // M
        # under in-stage TP each rank produces/stores only its H/t heads
        H_loc = lp_local["qkv"].shape[3]
        kv0 = (jnp.zeros((L_loc, M, cb, H_loc, L, Dh), self.dtype)
               if collect_kv else jnp.zeros((), x_local.dtype))
        outs, (ckb, cvb) = gpipe_stream(x_local, mask_local, M,
                                        apply_stage, (kv0, kv0), update_kv)
        if collect_kv:
            kvshape = (L_loc, B, H_loc, L, Dh)
            return outs, ckb.reshape(kvshape), cvb.reshape(kvshape)
        return outs
