"""Mixture-of-Experts MLP with top-k routing and expert parallelism.

The reference has no MoE (SURVEY.md §2.2 — no parallelism beyond DDP at
all); this exceeds it with the TPU-native formulation (GShard / Switch
Transformer recipe, reimplemented from the algorithm):

* **Dense dispatch**: routing is expressed as einsums against one-hot
  dispatch/combine tensors ``[B, L, E, C]`` — no ragged shapes, no gather
  loops, everything tiles onto the MXU and jits with static shapes.
* **Expert parallelism as sharding**: expert weights carry a leading
  ``expert`` logical axis mapped to the mesh's ``expert`` axis
  (parallel/sharding.py); activations are batch-sharded. XLA derives the
  dispatch/combine all-to-alls from those shardings — no hand-written
  collectives, same philosophy as the rest of the framework.
* **Capacity + residual overflow**: each expert processes at most
  ``C = ceil(L/E * capacity_factor * k)`` tokens per sequence; overflow
  tokens fall through on the residual path (standard Switch behavior).
* **Load-balancing aux loss** (Switch eq. 4): ``E * sum_e f_e * p_e``,
  sowed into the ``"losses"`` variable collection; the workload losses
  (diffuseq_losses / gpt2_losses) pick it up and add
  ``moe_aux_weight * aux`` to the objective.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from .backbone import EMBED, MLP, _dense_init

EXPERT = "expert"

MOE_AUX_WEIGHT = 0.01  # Switch Transformer's load-balance loss coefficient

__all__ = ["MoEMlp", "moe_mlp_fwd", "EXPERT", "MOE_AUX_WEIGHT",
           "moe_aux_from"]


def moe_aux_from(variables: Dict) -> jnp.ndarray:
    """Sum the MoE load-balance terms sowed into the "losses" collection
    (zero-leaf list for dense models — callers gate on the STATIC structure)."""
    leaves = jax.tree_util.tree_leaves(variables.get("losses", {}))
    return sum(leaves) if leaves else jnp.zeros(())


class MoEMlp(nn.Module):
    """Top-k routed mixture of GELU MLP experts (drop-in for backbone.Mlp).

    Routing, dispatch, expert compute, and combine are all einsums over
    statically-shaped one-hot tensors; see module docstring.

    Capacity slots are claimed in STRICT positional priority — position j's
    k-th choice outranks everything at positions > j — so whether a token is
    dropped depends only on earlier positions. That keeps routing causal
    (safe under a causal LM: future tokens cannot change position j's
    output) at the cost of interleaving the two top-k claim orders.

    ``no_drop=True`` (inference: models get there via
    ``model.clone(moe_no_drop=True)`` in models/sampling.py) bypasses
    capacity entirely and computes the exact per-token top-k mixture — the
    standard train-with-capacity / infer-without-dropping split, and what
    makes cached and uncached decoding bit-identical."""

    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.bfloat16
    expand: int = 4
    no_drop: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 pad_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        D = x.shape[-1]
        E = self.num_experts

        router_w = self.param(
            "router", nn.with_logical_partitioning(
                _dense_init(D), (EMBED, None)),
            (D, E), jnp.float32)
        wi = self.param(
            "wi", nn.with_logical_partitioning(
                _dense_init(D), (EXPERT, EMBED, MLP)),
            (E, D, self.expand * D), jnp.float32)
        wo = self.param(
            "wo", nn.with_logical_partitioning(
                _dense_init(self.expand * D), (EXPERT, MLP, EMBED)),
            (E, self.expand * D, D), jnp.float32)

        y, aux, dispatch = moe_mlp_fwd(
            {"router": router_w, "wi": wi, "wo": wo}, x, pad_mask,
            top_k=self.top_k, capacity_factor=self.capacity_factor,
            dtype=self.dtype, no_drop=self.no_drop)
        self.sow("losses", "moe_aux", aux,
                 init_fn=lambda: jnp.zeros(()), reduce_fn=jnp.add)
        if dispatch is not None:
            # Observable for tests (materializes only under mutable=
            # ["intermediates"]): the [B, L, E, C] one-hot routing plan.
            self.sow("intermediates", "dispatch", dispatch)
        return y


def moe_mlp_fwd(mp: Dict[str, jnp.ndarray], x: jnp.ndarray,
                pad_mask: Optional[jnp.ndarray], *, top_k: int,
                capacity_factor: float, dtype: jnp.dtype,
                no_drop: bool = False, return_stats: bool = False):
    """The MoE MLP as a pure function of its param dict ``{"router":
    [D, E] f32, "wi": [E, D, M], "wo": [E, M, D]}`` — the single
    implementation behind :class:`MoEMlp` (named blocks) AND the stacked
    scan-layers path (pipeline.MoEScanBlocks), which slices per-group
    weights out of a leading layers axis. Returns ``(y, aux_loss,
    dispatch-or-None)``; the caller owns sowing.

    ``return_stats=True`` returns the RAW load-balance sums instead of the
    finished aux scalar: ``(F [E], P [E], n)`` with ``F`` the top-1
    dispatch counts, ``P`` the router-prob sums over live tokens, ``n``
    the live-token count — so a sharded caller (the pipeline stages,
    whose batch is a shard_map-local chunk) can psum them over its batch
    axes and form ``aux = E * sum_e (F/n)(P/n)`` from GLOBAL statistics.
    Only ``P`` is differentiable (``F``/``n`` come from argmax one-hots
    and the pad mask); manual-vjp callers seed its cotangent with
    ``E * F/n^2`` accordingly."""
    B, L, D = x.shape
    E = mp["wi"].shape[0]
    K = min(top_k, E)
    C = max(1, math.ceil(L / E * capacity_factor * K))
    router_w, wi, wo = mp["router"], mp["wi"], mp["wo"]

    # Router in f32 (tiny op; softmax statistics want the precision).
    logits = jnp.einsum("bld,de->ble", x.astype(jnp.float32), router_w)
    probs = jax.nn.softmax(logits, axis=-1)              # [B, L, E]

    # Pad tokens must neither claim expert capacity nor steer the
    # load-balance statistics (seq2seq batches pad heavily; all pads
    # share one embedding and would pile onto one expert).
    live = (jnp.ones((B, L), jnp.float32) if pad_mask is None
            else pad_mask.astype(jnp.float32))

    # Iterative top-k: pick, mask out, repeat (K is tiny and static).
    remaining = probs
    gates, masks = [], []
    for _ in range(K):
        idx = jnp.argmax(remaining, axis=-1)             # [B, L]
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [B, L, E]
        remaining = remaining * (1.0 - mask)
        mask = mask * live[..., None]  # pads claim nothing
        gates.append((probs * mask).sum(-1))             # [B, L]
        masks.append(mask)

    # Switch load-balancing loss: E * sum_e (token fraction to e) *
    # (mean router prob of e), over the k=0 assignment — masked means
    # over REAL tokens only.
    n_live = jnp.maximum(live.sum(), 1.0)
    F_sum = masks[0].sum(axis=(0, 1))                    # [E]
    P_sum = (probs * live[..., None]).sum(axis=(0, 1))   # [E]
    # stats carry the RAW live count — accumulating callers sum counts
    # across chunks/shards before the aux division, and a per-chunk clamp
    # would inflate the global denominator for all-pad chunks (the final
    # max(n, 1) belongs to moe_aux_from_stats, applied once)
    aux = ((F_sum, P_sum, live.sum()) if return_stats
           else E * jnp.sum(F_sum / n_live * (P_sum / n_live)))

    if no_drop:
        # Exact per-token mixture: every expert computed for every
        # token, combined by normalized top-k gates. E x the MLP FLOPs,
        # used on (cheap) inference paths only.
        gate_mat = sum(g[..., None] * m for g, m in zip(gates, masks))
        denom_all = jnp.maximum(sum(gates), 1e-9)        # [B, L]
        w = gate_mat / denom_all[..., None]              # [B, L, E]
        h = jnp.einsum("bld,edm->belm", x.astype(dtype),
                       wi.astype(dtype))
        h = nn.gelu(h, approximate=True)
        out = jnp.einsum("belm,emd->beld", h, wo.astype(dtype))
        y = jnp.einsum("ble,beld->bld", w.astype(dtype), out)
        return y.astype(x.dtype), aux, None

    # Capacity: interleave the K claim streams in (position, k) order —
    # [B, L, K, E] -> [B, L*K, E] position-major — so slot occupancy at
    # position j counts ONLY claims from positions <= j (causality).
    claims = jnp.stack(masks, axis=2).reshape(B, L * K, E)
    pos = jnp.cumsum(claims, axis=1) - claims            # [B, L*K, E]
    keep_flat = claims * (pos < C)
    slot_idx = (pos * keep_flat).sum(-1).astype(jnp.int32)
    slot_flat = jax.nn.one_hot(slot_idx, C, dtype=jnp.float32)
    keep = keep_flat.reshape(B, L, K, E)
    slot = slot_flat.reshape(B, L, K, C)

    # Normalize kept gates so the combine weights sum to <= 1.
    kept_gate = [g * keep[:, :, k].sum(-1) for k, g in enumerate(gates)]
    denom = jnp.maximum(sum(kept_gate), 1e-9)
    combine = jnp.zeros((B, L, E, C), jnp.float32)
    for k, g in enumerate(gates):
        w = (g / denom)[..., None] * keep[:, :, k]       # [B, L, E]
        combine = combine + w[..., None] * slot[:, :, k][:, :, None, :]
    dispatch = (combine > 0).astype(x.dtype)

    # Dispatch -> expert MLPs -> combine. The expert (e) dim of wi/wo is
    # sharded over the mesh's expert axis; ein-summing it against
    # batch-sharded activations is what makes XLA emit the all-to-alls.
    xin = jnp.einsum("blec,bld->ebcd", dispatch, x.astype(dtype))
    h = jnp.einsum("ebcd,edm->ebcm", xin, wi.astype(dtype))
    h = nn.gelu(h, approximate=True)
    out = jnp.einsum("ebcm,emd->ebcd", h, wo.astype(dtype))
    y = jnp.einsum("blec,ebcd->bld", combine.astype(dtype), out)
    return y.astype(x.dtype), aux, dispatch
