"""Model factory and workload plug-in surface.

Fills the reference's empty model layer (``/root/reference/models/__init__.py``
is 0 bytes; ``utils/initialization.py:18-27`` ``create_model_from_config`` is a
stub) with two concrete families behind the same factory call the reference
entry point makes (``run/train.py:71`` passes ``**args.dict()``):

* ``diffuseq`` — seq2seq embedding diffusion (base/large/xl presets);
* ``gpt2``     — causal LM (base/medium/large/xl presets).

The factory returns a :class:`Workload`: the flax module plus pure
``init_params`` / ``compute_losses`` functions — the reference's user-hook
trio (``compute_losses``/``backward_from_losses``/``log_loss_dict``,
``utils/trainer.py:19-31``) collapsed into one functional object that the
jitted trainer consumes.
"""

from __future__ import annotations

import dataclasses
import random as _random
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .backbone import TransformerBackbone
from .diffuseq import DiffuSeqModel, diffuseq_losses
from .diffusion import DiffusionSchedule, make_schedule
from .gpt2 import GPT2Model, gpt2_losses

__all__ = [
    "Workload", "create_model_from_config", "seed_all", "PRESETS",
    "DiffuSeqModel", "GPT2Model", "TransformerBackbone",
    "make_schedule", "DiffusionSchedule",
]

# (hidden, layers, heads) per family/size.
PRESETS: Dict[str, Dict[str, Tuple[int, int, int]]] = {
    "diffuseq": {
        "base": (768, 12, 12),    # BASELINE.md config 1/2
        "large": (1024, 24, 16),  # config 3
        "xl": (1600, 32, 25),     # config 5
    },
    "gpt2": {
        "base": (768, 12, 12),
        "medium": (1024, 24, 16),  # config 4
        "large": (1280, 36, 20),
        "xl": (1600, 48, 25),
    },
}
DIFFUSEQ_EMB_DIM = 128  # DiffuSeq uses a low-dim embedding space


@dataclasses.dataclass(frozen=True)
class Workload:
    """A model family bound to its pure loss function.

    ``compute_losses(params, batch, rng) -> {"loss": scalar, ...metrics}`` is
    jit-safe; the trainer differentiates it directly (the reference's separate
    ``backward_from_losses`` hook disappears — grad is a transform, not a
    method).
    """

    model: Any
    family: str
    seq_len: int
    hidden_size: int
    num_layers: int
    compute_losses: Callable[[Any, Dict[str, jnp.ndarray], jax.Array],
                             Dict[str, jnp.ndarray]]
    example_batch: Callable[[int], Dict[str, np.ndarray]]
    schedule: Optional[DiffusionSchedule] = None
    # Declared sharding (parallel/partition.py): ordered (path-regex,
    # PartitionSpec) rules the trainer resolves into NamedShardings. None
    # falls back to the family's built-in table (rules_for_workload), and
    # unknown families to the flax logical-metadata compat path — a new
    # model declares a table here instead of editing the engine.
    partition_rules: Optional[Tuple[Tuple[str, Any], ...]] = None

    def init_params(self, rng: jax.Array) -> Any:
        """Initialize parameters from a dummy batch (shapes only)."""
        batch = jax.tree_util.tree_map(jnp.asarray, self.example_batch(1))
        if self.family == "diffuseq":
            t = jnp.zeros((1,), jnp.int32)
            variables = self.model.init(rng, batch["input_ids"], t,
                                        batch["pad_mask"],
                                        method=DiffuSeqModel.init_variables)
        else:
            variables = self.model.init(rng, batch["input_ids"],
                                        batch["pad_mask"])
        # init() materializes every collection; only "params" is trainable
        # state ("losses" holds MoE aux sows — per-step outputs, not state).
        return {k: v for k, v in variables.items() if k != "losses"}

    def param_count(self, params: Any) -> int:
        return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def _example_batch_fn(seq_len: int) -> Callable[[int], Dict[str, np.ndarray]]:
    def fn(batch_size: int) -> Dict[str, np.ndarray]:
        ones = np.ones((batch_size, seq_len), np.int32)
        ids = np.arange(batch_size * seq_len, dtype=np.int32).reshape(
            batch_size, seq_len) % 7 + 4
        mask = np.zeros_like(ones)
        mask[:, seq_len // 2:] = 1
        return {"input_ids": ids, "input_mask": mask, "pad_mask": ones}
    return fn


def create_model_from_config(*, model_family: str = "diffuseq",
                             model_size: str = "base",
                             vocab_size: int = 8192, seq_len: int = 128,
                             hidden_size: int = 0, num_layers: int = 0,
                             num_heads: int = 0,
                             diffusion_steps: int = 2000,
                             noise_schedule: str = "sqrt",
                             dtype: str = "bfloat16", remat: bool = False,
                             attention_impl: str = "auto",
                             moe_experts: int = 0, moe_top_k: int = 2,
                             moe_every: int = 2,
                             moe_capacity_factor: float = 1.25,
                             scan_layers: bool = False,
                             pp_chunks: int = 4, pp_schedule: str = "1f1b",
                             pp_virtual: int = 2, scan_unroll: int = 0,
                             **_unused: Any) -> Workload:
    """Build a :class:`Workload` from (a superset of) ``TrainSettings`` fields
    — callable as ``create_model_from_config(**settings.dict())`` exactly like
    the reference entry point (``run/train.py:71``). Preset dims can be
    overridden individually via nonzero hidden/layers/heads."""
    if model_family not in PRESETS:
        raise ValueError(f"unknown model family: {model_family!r}; "
                         f"available: {sorted(PRESETS)}")
    if moe_experts > 0 and moe_every < 1:
        raise ValueError(f"moe_every must be >= 1, got {moe_every}")
    preset = PRESETS[model_family].get(model_size)
    if preset is None:
        raise ValueError(f"no preset {model_size!r} for family {model_family!r}; "
                         f"available: {sorted(PRESETS[model_family])}")
    hidden = hidden_size or preset[0]
    layers = num_layers or preset[1]
    if scan_layers and moe_experts > 0 and layers % moe_every:
        raise ValueError(
            f"scan_layers MoE scans uniform groups of moe_every blocks: "
            f"num_layers {layers} must divide by moe_every {moe_every}")
    heads = num_heads or preset[2]
    jdtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32

    # Declared sharding: the family's partition-rule table rides the
    # Workload (parallel/partition.py; function-level import keeps the
    # models layer import-light for tools that only build modules).
    from ..parallel.partition import DIFFUSEQ_RULES, GPT2_RULES
    rules = DIFFUSEQ_RULES if model_family == "diffuseq" else GPT2_RULES

    if model_family == "diffuseq":
        model = DiffuSeqModel(
            vocab_size=vocab_size, seq_len=seq_len, hidden_size=hidden,
            num_layers=layers, num_heads=heads, emb_dim=DIFFUSEQ_EMB_DIM,
            dtype=jdtype, remat=remat, attention_impl=attention_impl,
            moe_experts=moe_experts, moe_top_k=moe_top_k,
            moe_every=moe_every, moe_capacity_factor=moe_capacity_factor,
            scan_layers=scan_layers,
            pp_chunks=pp_chunks, pp_schedule=pp_schedule,
            pp_virtual=pp_virtual, scan_unroll=scan_unroll)
        schedule = make_schedule(noise_schedule, diffusion_steps)

        def compute_losses(params, batch, rng):
            return diffuseq_losses(model, schedule, params, batch, rng)

        return Workload(model=model, family="diffuseq", seq_len=seq_len,
                        hidden_size=hidden, num_layers=layers,
                        compute_losses=compute_losses,
                        example_batch=_example_batch_fn(seq_len),
                        schedule=schedule, partition_rules=rules)

    else:  # "gpt2" — PRESETS membership was validated above
        model = GPT2Model(
            vocab_size=vocab_size, seq_len=seq_len, hidden_size=hidden,
            num_layers=layers, num_heads=heads, dtype=jdtype, remat=remat,
            attention_impl=attention_impl, moe_experts=moe_experts,
            moe_top_k=moe_top_k, moe_every=moe_every,
            moe_capacity_factor=moe_capacity_factor,
            scan_layers=scan_layers, pp_chunks=pp_chunks,
            pp_schedule=pp_schedule, pp_virtual=pp_virtual,
            scan_unroll=scan_unroll)

        def compute_losses(params, batch, rng):
            return gpt2_losses(model, params, batch, rng)

        return Workload(model=model, family="gpt2", seq_len=seq_len,
                        hidden_size=hidden, num_layers=layers,
                        compute_losses=compute_losses,
                        example_batch=_example_batch_fn(seq_len),
                        partition_rules=rules)


def seed_all(seed: int, deterministic: bool = False) -> jax.Array:
    """Global seeding with per-process offset (reference
    ``utils/initialization.py:1-15``: non-deterministic mode offsets the seed
    by rank so hosts draw different data/noise; deterministic mode keeps all
    hosts identical). Returns the root JAX PRNG key — JAX's counter-based
    PRNG replaces torch's stateful seeding."""
    from ..parallel import dist

    offset = 0 if deterministic else dist.get_rank()
    _random.seed(seed + offset)
    np.random.seed((seed + offset) % (2 ** 32))
    return jax.random.PRNGKey(seed + offset)
