"""Gaussian diffusion machinery for embedding-space text diffusion.

Implements the DiffuSeq-style continuous diffusion the reference scaffold was
built to train (its README cites the DiffuSeq ICLR 2023 paper,
``/root/reference/README.md:31-40``, and credits its trainer to DiffuSeq's
``train_util.py``) but never ships: noise schedules, forward process
``q(x_t | x_0)``, and the simplified x0-prediction training objective with
*partial noising* (only the target span is diffused; source tokens stay
clean as conditioning anchors).

Everything is a pure function over precomputed schedule arrays — jit-safe,
no Python control flow on traced values.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["DiffusionSchedule", "make_schedule"]


def _betas_for_alpha_bar(T: int, alpha_bar_fn, max_beta: float = 0.999) -> np.ndarray:
    betas = []
    for i in range(T):
        t1, t2 = i / T, (i + 1) / T
        betas.append(min(1 - alpha_bar_fn(t2) / alpha_bar_fn(t1), max_beta))
    return np.asarray(betas, dtype=np.float64)


def named_beta_schedule(name: str, T: int) -> np.ndarray:
    """Noise schedules: "sqrt" (DiffuSeq's default for text embeddings),
    "cosine" (Nichol & Dhariwal), "linear" (DDPM)."""
    if name == "sqrt":
        return _betas_for_alpha_bar(T, lambda t: 1 - math.sqrt(t + 0.0001))
    if name == "cosine":
        return _betas_for_alpha_bar(
            T, lambda t: math.cos((t + 0.008) / 1.008 * math.pi / 2) ** 2)
    if name == "linear":
        scale = 1000 / T
        return np.linspace(scale * 1e-4, scale * 0.02, T, dtype=np.float64)
    raise ValueError(f"unknown noise schedule: {name}")


@dataclasses.dataclass(frozen=True)
class DiffusionSchedule:
    """Precomputed schedule tensors, all shape [T] f32 (kept as numpy until
    traced so they constant-fold into the jitted step)."""

    num_steps: int
    betas: np.ndarray
    alphas_cumprod: np.ndarray
    sqrt_alphas_cumprod: np.ndarray
    sqrt_one_minus_alphas_cumprod: np.ndarray

    def q_sample(self, x_start: jnp.ndarray, t: jnp.ndarray,
                 noise: jnp.ndarray) -> jnp.ndarray:
        """Sample ``x_t ~ q(x_t | x_0)``; ``t`` is int32 [B], broadcast over
        trailing dims of ``x_start`` [B, L, E]."""
        shape = (-1,) + (1,) * (x_start.ndim - 1)
        a = jnp.asarray(self.sqrt_alphas_cumprod, x_start.dtype)[t].reshape(shape)
        s = jnp.asarray(self.sqrt_one_minus_alphas_cumprod,
                        x_start.dtype)[t].reshape(shape)
        return a * x_start + s * noise

    def sample_t(self, rng: jax.Array, batch: int) -> jnp.ndarray:
        """Uniform timestep sampling, int32 [batch]."""
        return jax.random.randint(rng, (batch,), 0, self.num_steps)

    def mean_flat_tT(self, x_start: jnp.ndarray) -> jnp.ndarray:
        """Per-example ||sqrt(abar_T) x_0||^2 regularizer (pushes the final
        latent toward the N(0, I) prior), [B, L]."""
        aT = float(self.sqrt_alphas_cumprod[-1])
        return jnp.mean((aT * x_start) ** 2, axis=-1)


def make_schedule(name: str = "sqrt", num_steps: int = 2000) -> DiffusionSchedule:
    betas = named_beta_schedule(name, num_steps)
    alphas_cumprod = np.cumprod(1.0 - betas)
    return DiffusionSchedule(
        num_steps=num_steps,
        betas=betas.astype(np.float32),
        alphas_cumprod=alphas_cumprod.astype(np.float32),
        sqrt_alphas_cumprod=np.sqrt(alphas_cumprod).astype(np.float32),
        sqrt_one_minus_alphas_cumprod=np.sqrt(1 - alphas_cumprod).astype(np.float32),
    )
