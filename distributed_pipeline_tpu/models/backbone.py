"""Shared transformer backbone (flax.linen).

The reference leaves the model entirely to the user
(``/root/reference/models/__init__.py`` is empty;
``utils/initialization.py:18-27`` is a stub). This backbone powers both
concrete workloads that fill those stubs: the DiffuSeq denoiser
(bidirectional) and the GPT-2 causal LM.

TPU-first choices:
* bf16 activations / f32 params, f32 softmax and layernorm statistics;
* all matmuls batched [B, L, D] x [D, *] so XLA tiles them on the MXU;
* attention via ops.dot_product_attention (XLA / pallas-flash / ring);
* optional ``jax.checkpoint`` (remat) per block to trade FLOPs for HBM;
* logical sharding annotations (``nn.with_logical_partitioning``) on every
  weight, mapped to mesh axes by parallel/sharding.py — the same model
  definition runs DP, FSDP, and TP without code changes.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops import dot_product_attention

__all__ = ["TransformerBackbone", "Block", "Mlp", "SelfAttention"]

# Logical axis names; parallel/sharding.py maps them onto mesh axes
# ("embed" -> fsdp, "mlp"/"heads"/"kv" -> tensor, etc.).
EMBED = "embed"
MLP = "mlp"
HEADS = "heads"
KV = "kv"


def _dense_init(fan_in: int):
    return nn.initializers.normal(stddev=fan_in ** -0.5)


class SelfAttention(nn.Module):
    """Multi-head self-attention. QKV fused into one [D, 3, H, Dh] matmul
    (one MXU pass instead of three).

    ``decode=True`` adds an autoregressive KV cache (the "cache" variable
    collection): a full-length call is the PREFILL (runs normal causal
    attention and writes every position's K/V), and a single-token call with
    ``cache_index=i`` writes position i and attends to cache[0..i] — O(L)
    work per generated token instead of a full O(L^2) re-forward. The
    caller threads ``cache_index``; no mutable step counter hides in the
    module (jit/scany-friendly).

    ``paged_pages > 0`` (with ``decode=True``) switches the cache to the
    PAGED layout behind the serving layer (serving/paged_kv.py): K/V live in
    a shared pool of fixed-size pages (``pages_k``/``pages_v`` variables,
    [paged_pages, page_size, H, Dh]) indirected through a per-slot
    ``block_table`` [B, pages_per_slot] argument, and ``cache_index`` is a
    PER-SLOT position vector [B] — each decode slot sits at its own depth,
    which is what continuous batching needs. Page 0 is the trash page:
    writes from padded/inactive slots land there and are never read (reads
    are masked to each slot's live prefix)."""

    num_heads: int
    dtype: jnp.dtype = jnp.bfloat16
    causal: bool = False
    attention_impl: str = "auto"
    decode: bool = False
    paged_pages: int = 0
    page_size: int = 0
    # Paged DECODE-step kernel (ops/flash_decode.py): "auto" -> flash-decode
    # on TPU / XLA gather elsewhere; "pallas"/"xla" force. Distinct from
    # attention_impl, which picks the full-sequence (train/prefill) kernel.
    decode_impl: str = "auto"
    # "int8": store the paged pool quantized per page with [P] fp32 scale
    # sidecars (serving/paged_kv.py q8 writers) — halves pool bytes; decode
    # reads dequantize per page. Prefill attention still runs on the local
    # fp k/v, so prefill logits are unchanged; decode logits carry the
    # documented quantization divergence instead of bit-identity.
    kv_quant: str = "fp"

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 pad_mask: Optional[jnp.ndarray],
                 cache_index: Optional[jnp.ndarray] = None,
                 block_table: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        B, L, D = x.shape
        H = self.num_heads
        assert D % H == 0, f"hidden {D} not divisible by heads {H}"
        Dh = D // H
        qkv_w = self.param(
            "qkv", nn.with_logical_partitioning(_dense_init(D), (EMBED, None, HEADS, KV)),
            (D, 3, H, Dh), jnp.float32)
        out_w = self.param(
            "out", nn.with_logical_partitioning(_dense_init(D), (HEADS, KV, EMBED)),
            (H, Dh, D), jnp.float32)
        qkv = jnp.einsum("bld,dthk->tbhlk", x, qkv_w.astype(self.dtype))
        q, k, v = qkv[0], qkv[1], qkv[2]
        if self.decode and self.paged_pages > 0:
            if block_table is None:
                raise ValueError("paged decode (paged_pages > 0) needs a "
                                 "block_table")
            o = self._paged_attention(q, k, v, pad_mask, cache_index,
                                      block_table)
        elif self.decode:
            o = self._cached_attention(q, k, v, pad_mask, cache_index)
        else:
            if block_table is not None:
                raise ValueError("block_table is only meaningful for paged "
                                 "decode (decode=True, paged_pages > 0)")
            o = dot_product_attention(q, k, v, pad_mask, causal=self.causal,
                                      impl=self.attention_impl)
        return jnp.einsum("bhlk,hkd->bld", o, out_w.astype(self.dtype))

    def _paged_attention(self, q, k, v, pad_mask, cache_index, block_table):
        # function-level import: paged_kv is a leaf module (jax-only), so
        # models <- serving here is a cycle-free convenience, same pattern
        # as Block's moe import
        from ..ops.flash_decode import (paged_decode_attention,
                                        paged_span_attention)
        from ..serving.paged_kv import (write_prompt_kv, write_prompt_kv_q8,
                                        write_span_kv, write_span_kv_q8,
                                        write_token_kv, write_token_kv_q8)
        B, H, L, Dh = q.shape
        quant = self.kv_quant == "int8"
        pool_dtype = jnp.int8 if quant else k.dtype
        pk = self.variable("cache", "pages_k", jnp.zeros,
                           (self.paged_pages, self.page_size, H, Dh),
                           pool_dtype)
        pv = self.variable("cache", "pages_v", jnp.zeros,
                           (self.paged_pages, self.page_size, H, Dh),
                           pool_dtype)
        sk = sv = None
        if quant:  # [P] per-page fp32 scale sidecars
            sk = self.variable("cache", "scales_k", jnp.zeros,
                               (self.paged_pages,), jnp.float32)
            sv = self.variable("cache", "scales_v", jnp.zeros,
                               (self.paged_pages,), jnp.float32)
        if L > 1 and cache_index is None:
            # prefill: write the prompt's K/V into its slots' pages;
            # attention itself runs on the local (contiguous) k/v — exactly
            # the dense prefill computation, so logits match it bitwise
            # (int8 included: quantization touches only the POOL copy)
            valid = pad_mask if pad_mask is not None else jnp.ones(
                (B, L), jnp.int32)
            if quant:
                pk.value, sk.value = write_prompt_kv_q8(
                    pk.value, sk.value, block_table, k, valid)
                pv.value, sv.value = write_prompt_kv_q8(
                    pv.value, sv.value, block_table, v, valid)
            else:
                pk.value = write_prompt_kv(pk.value, block_table, k, valid)
                pv.value = write_prompt_kv(pv.value, block_table, v, valid)
            return dot_product_attention(q, k, v, pad_mask, causal=True,
                                         impl=self.attention_impl)
        if cache_index is None or jnp.ndim(cache_index) != 1:
            raise ValueError("paged decode needs a per-slot cache_index "
                             "vector [B]")
        idx = jnp.asarray(cache_index, jnp.int32)
        if L > 1:
            # speculative-verify span (serving/engine.verify_fn): each
            # slot's L chain links occupy positions idx..idx+L-1. Write
            # every link's K/V first (span writers clamp budget-final
            # overshoot to the last addressable cell), then one span
            # attention dispatch: link j's query sits at position idx+j
            # and its position mask reads the live prefix PLUS the
            # earlier links — exactly the rows a sequential K+1-step
            # replay would read, at the op count of ONE decode step.
            if quant:
                pk.value, sk.value = write_span_kv_q8(
                    pk.value, sk.value, block_table, k, idx)
                pv.value, sv.value = write_span_kv_q8(
                    pv.value, sv.value, block_table, v, idx)
            else:
                pk.value = write_span_kv(pk.value, block_table, k, idx)
                pv.value = write_span_kv(pv.value, block_table, v, idx)
            addr = block_table.shape[1] * self.page_size
            pos = jnp.minimum(idx[:, None]
                              + jnp.arange(L, dtype=jnp.int32)[None, :],
                              addr - 1)                          # [B, L]
            return paged_span_attention(
                q, pk.value, pv.value, block_table, pos,
                impl=self.decode_impl,
                scales_k=sk.value if quant else None,
                scales_v=sv.value if quant else None)
        if quant:
            pk.value, sk.value = write_token_kv_q8(
                pk.value, sk.value, block_table, k[:, :, 0], idx)
            pv.value, sv.value = write_token_kv_q8(
                pv.value, sv.value, block_table, v[:, :, 0], idx)
        else:
            pk.value = write_token_kv(pk.value, block_table, k[:, :, 0], idx)
            pv.value = write_token_kv(pv.value, block_table, v[:, :, 0], idx)
        # The decode_step seam: positions beyond each slot's own depth hold
        # trash/stale pages and are masked (causality IS this mask for one
        # query row). The XLA path gathers a dense [B, H, Lmax, Dh] view
        # and masks it — bit-identical to the dense cache path at equal
        # padded length; the pallas path (ops/flash_decode.py) reads live
        # pages straight from the pool, matching to float tolerance
        # (greedy-token identical — tests/test_kernels.py).
        o = paged_decode_attention(
            q[:, :, 0], pk.value, pv.value, block_table, idx,
            impl=self.decode_impl,
            scales_k=sk.value if quant else None,
            scales_v=sv.value if quant else None)
        return o[:, :, None]

    def _cached_attention(self, q, k, v, pad_mask, cache_index):
        B, H, L, Dh = q.shape
        # Cache dims come from the first (prefill, full-length) call.
        ck = self.variable("cache", "key", jnp.zeros, k.shape, k.dtype)
        cv = self.variable("cache", "value", jnp.zeros, v.shape, v.dtype)
        Lmax = ck.value.shape[2]
        if L == Lmax:  # prefill: populate the whole cache
            ck.value, cv.value = k, v
            return dot_product_attention(q, k, v, pad_mask, causal=True,
                                         impl=self.attention_impl)
        if L != 1:
            raise ValueError(
                f"decode calls take the full length ({Lmax}, prefill) or a "
                f"single token, got {L}")
        if cache_index is None:
            raise ValueError("single-token decode needs cache_index")
        idx = jnp.asarray(cache_index, jnp.int32)
        ck.value = jax.lax.dynamic_update_slice(
            ck.value, k, (0, 0, idx, 0))
        cv.value = jax.lax.dynamic_update_slice(
            cv.value, v, (0, 0, idx, 0))
        # Positions beyond idx hold stale/unwritten entries; mask them.
        # (Causality IS this mask — no triangle needed for one query row.)
        live = (jnp.arange(Lmax) <= idx).astype(jnp.int32)[None, :]
        live = jnp.broadcast_to(live, (B, Lmax))
        if pad_mask is not None:
            live = live * pad_mask
        return dot_product_attention(q, ck.value, cv.value, live,
                                     causal=False, impl="xla")


class Mlp(nn.Module):
    """GELU MLP, expansion 4x."""

    dtype: jnp.dtype = jnp.bfloat16
    expand: int = 4

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        D = x.shape[-1]
        wi = self.param("wi", nn.with_logical_partitioning(_dense_init(D), (EMBED, MLP)),
                        (D, self.expand * D), jnp.float32)
        wo = self.param("wo", nn.with_logical_partitioning(
            _dense_init(self.expand * D), (MLP, EMBED)),
            (self.expand * D, D), jnp.float32)
        h = jnp.einsum("bld,dm->blm", x, wi.astype(self.dtype))
        h = nn.gelu(h, approximate=True)
        return jnp.einsum("blm,md->bld", h, wo.astype(self.dtype))


class Block(nn.Module):
    """Pre-LN transformer block (LN in f32 for stability).

    ``moe_experts > 0`` swaps the dense MLP for a top-k routed
    mixture-of-experts (models/moe.py) — expert weights shard over the
    mesh's ``expert`` axis."""

    num_heads: int
    dtype: jnp.dtype = jnp.bfloat16
    causal: bool = False
    attention_impl: str = "auto"
    decode: bool = False
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_no_drop: bool = False
    paged_pages: int = 0
    page_size: int = 0
    decode_impl: str = "auto"
    kv_quant: str = "fp"

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 pad_mask: Optional[jnp.ndarray],
                 cache_index: Optional[jnp.ndarray] = None,
                 block_table: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x).astype(self.dtype)
        x = x + SelfAttention(self.num_heads, self.dtype, self.causal,
                              self.attention_impl, self.decode,
                              paged_pages=self.paged_pages,
                              page_size=self.page_size,
                              decode_impl=self.decode_impl,
                              kv_quant=self.kv_quant,
                              name="attn")(h, pad_mask, cache_index,
                                           block_table)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x).astype(self.dtype)
        if self.moe_experts > 0:
            from .moe import MoEMlp  # function-level: moe imports backbone
            x = x + MoEMlp(self.moe_experts, self.moe_top_k,
                           capacity_factor=self.moe_capacity_factor,
                           dtype=self.dtype, no_drop=self.moe_no_drop,
                           name="moe")(h, pad_mask)
        else:
            x = x + Mlp(self.dtype, name="mlp")(h)
        return x


class TransformerBackbone(nn.Module):
    """Stack of pre-LN blocks over already-embedded inputs [B, L, D].

    Token/position/time embedding is workload-specific and lives in the
    concrete models (diffuseq.py / gpt2.py); the backbone is the shared
    FLOPs-dominant trunk.
    """

    num_layers: int
    num_heads: int
    dtype: jnp.dtype = jnp.bfloat16
    remat: bool = False
    causal: bool = False
    attention_impl: str = "auto"
    decode: bool = False
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_every: int = 2  # MoE replaces the MLP in every moe_every-th block
    moe_capacity_factor: float = 1.25
    moe_no_drop: bool = False
    scan_layers: bool = False  # stacked weights: lax.scan over layers, and
    # GPipe pipeline streaming when the mesh has a pipe axis > 1
    pp_chunks: int = 4
    scan_unroll: int = 0  # layer-scan unroll (pipeline.scan_unroll_for)
    paged_pages: int = 0  # serving: paged KV cache pool size (0 = dense)
    page_size: int = 0
    decode_impl: str = "auto"  # paged decode-step kernel (SelfAttention)
    kv_quant: str = "fp"  # "int8": quantized page pool + per-page scales

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 pad_mask: Optional[jnp.ndarray] = None,
                 cache_index: Optional[jnp.ndarray] = None,
                 block_table: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        if self.scan_layers:
            if block_table is not None or self.paged_pages > 0:
                raise NotImplementedError(
                    "paged decode needs per-layer named blocks; stacked "
                    "(scan_layers) models use the dense cache path")
            if self.moe_experts > 0:
                from .pipeline import MoEScanBlocks
                x = MoEScanBlocks(
                    self.num_layers, self.num_heads, x.shape[-1],
                    dtype=self.dtype, causal=self.causal,
                    moe_experts=self.moe_experts, moe_top_k=self.moe_top_k,
                    moe_every=self.moe_every,
                    capacity_factor=self.moe_capacity_factor,
                    moe_no_drop=self.moe_no_drop, remat=self.remat,
                    attention_impl=self.attention_impl,
                    scan_unroll=self.scan_unroll,
                    pp_chunks=self.pp_chunks,
                    name="blocks")(x, pad_mask, cache_index)
            else:
                from .pipeline import PipelinedBlocks
                x = PipelinedBlocks(
                    self.num_layers, self.num_heads, x.shape[-1],
                    dtype=self.dtype, causal=self.causal, remat=self.remat,
                    pp_chunks=self.pp_chunks,
                    attention_impl=self.attention_impl,
                    decode=self.decode,
                    scan_unroll=self.scan_unroll,
                    name="blocks")(x, pad_mask, cache_index)
            return nn.LayerNorm(dtype=jnp.float32,
                                name="ln_f")(x).astype(self.dtype)
        block_cls = Block
        if self.remat:
            block_cls = nn.remat(Block, prevent_cse=False,
                                 static_argnums=())  # save HBM: recompute in bwd
        for i in range(self.num_layers):
            is_moe = (self.moe_experts > 0
                      and i % self.moe_every == self.moe_every - 1)
            x = block_cls(self.num_heads, self.dtype, self.causal,
                          self.attention_impl, self.decode,
                          moe_experts=self.moe_experts if is_moe else 0,
                          moe_top_k=self.moe_top_k,
                          moe_capacity_factor=self.moe_capacity_factor,
                          moe_no_drop=self.moe_no_drop,
                          paged_pages=self.paged_pages,
                          page_size=self.page_size,
                          decode_impl=self.decode_impl,
                          kv_quant=self.kv_quant,
                          name=f"block_{i}")(x, pad_mask, cache_index,
                                             block_table)
        return nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x).astype(self.dtype)
