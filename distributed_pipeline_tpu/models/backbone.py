"""Shared transformer backbone (flax.linen).

The reference leaves the model entirely to the user
(``/root/reference/models/__init__.py`` is empty;
``utils/initialization.py:18-27`` is a stub). This backbone powers both
concrete workloads that fill those stubs: the DiffuSeq denoiser
(bidirectional) and the GPT-2 causal LM.

TPU-first choices:
* bf16 activations / f32 params, f32 softmax and layernorm statistics;
* all matmuls batched [B, L, D] x [D, *] so XLA tiles them on the MXU;
* attention via ops.dot_product_attention (XLA / pallas-flash / ring);
* optional ``jax.checkpoint`` (remat) per block to trade FLOPs for HBM;
* logical sharding annotations (``nn.with_logical_partitioning``) on every
  weight, mapped to mesh axes by parallel/sharding.py — the same model
  definition runs DP, FSDP, and TP without code changes.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops import dot_product_attention

__all__ = ["TransformerBackbone", "Block", "Mlp", "SelfAttention"]

# Logical axis names; parallel/sharding.py maps them onto mesh axes
# ("embed" -> fsdp, "mlp"/"heads"/"kv" -> tensor, etc.).
EMBED = "embed"
MLP = "mlp"
HEADS = "heads"
KV = "kv"


def _dense_init(fan_in: int):
    return nn.initializers.normal(stddev=fan_in ** -0.5)


class SelfAttention(nn.Module):
    """Multi-head self-attention. QKV fused into one [D, 3, H, Dh] matmul
    (one MXU pass instead of three)."""

    num_heads: int
    dtype: jnp.dtype = jnp.bfloat16
    causal: bool = False
    attention_impl: str = "auto"

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 pad_mask: Optional[jnp.ndarray]) -> jnp.ndarray:
        B, L, D = x.shape
        H = self.num_heads
        assert D % H == 0, f"hidden {D} not divisible by heads {H}"
        Dh = D // H
        qkv_w = self.param(
            "qkv", nn.with_logical_partitioning(_dense_init(D), (EMBED, None, HEADS, KV)),
            (D, 3, H, Dh), jnp.float32)
        out_w = self.param(
            "out", nn.with_logical_partitioning(_dense_init(D), (HEADS, KV, EMBED)),
            (H, Dh, D), jnp.float32)
        qkv = jnp.einsum("bld,dthk->tbhlk", x, qkv_w.astype(self.dtype))
        q, k, v = qkv[0], qkv[1], qkv[2]
        o = dot_product_attention(q, k, v, pad_mask, causal=self.causal,
                                  impl=self.attention_impl)
        return jnp.einsum("bhlk,hkd->bld", o, out_w.astype(self.dtype))


class Mlp(nn.Module):
    """GELU MLP, expansion 4x."""

    dtype: jnp.dtype = jnp.bfloat16
    expand: int = 4

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        D = x.shape[-1]
        wi = self.param("wi", nn.with_logical_partitioning(_dense_init(D), (EMBED, MLP)),
                        (D, self.expand * D), jnp.float32)
        wo = self.param("wo", nn.with_logical_partitioning(
            _dense_init(self.expand * D), (MLP, EMBED)),
            (self.expand * D, D), jnp.float32)
        h = jnp.einsum("bld,dm->blm", x, wi.astype(self.dtype))
        h = nn.gelu(h, approximate=True)
        return jnp.einsum("blm,md->bld", h, wo.astype(self.dtype))


class Block(nn.Module):
    """Pre-LN transformer block (LN in f32 for stability)."""

    num_heads: int
    dtype: jnp.dtype = jnp.bfloat16
    causal: bool = False
    attention_impl: str = "auto"

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 pad_mask: Optional[jnp.ndarray]) -> jnp.ndarray:
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x).astype(self.dtype)
        x = x + SelfAttention(self.num_heads, self.dtype, self.causal,
                              self.attention_impl, name="attn")(h, pad_mask)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x).astype(self.dtype)
        x = x + Mlp(self.dtype, name="mlp")(h)
        return x


class TransformerBackbone(nn.Module):
    """Stack of pre-LN blocks over already-embedded inputs [B, L, D].

    Token/position/time embedding is workload-specific and lives in the
    concrete models (diffuseq.py / gpt2.py); the backbone is the shared
    FLOPs-dominant trunk.
    """

    num_layers: int
    num_heads: int
    dtype: jnp.dtype = jnp.bfloat16
    remat: bool = False
    causal: bool = False
    attention_impl: str = "auto"

    @nn.compact
    def __call__(self, x: jnp.ndarray,
                 pad_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        block_cls = Block
        if self.remat:
            block_cls = nn.remat(Block, prevent_cse=False,
                                 static_argnums=())  # save HBM: recompute in bwd
        for i in range(self.num_layers):
            x = block_cls(self.num_heads, self.dtype, self.causal,
                          self.attention_impl, name=f"block_{i}")(x, pad_mask)
        return nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x).astype(self.dtype)
