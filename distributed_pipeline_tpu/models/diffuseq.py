"""DiffuSeq: seq2seq text diffusion in embedding space.

The concrete implementation of the workload the reference scaffold targets
(its trainer derives from DiffuSeq's ``train_util.py``,
``/root/reference/utils/trainer.py:1-4``; model/loss left as user stubs at
``utils/initialization.py:18-27`` and ``utils/trainer.py:23-31``).

Training scheme (DiffuSeq, ICLR 2023 — reimplemented TPU-first, not copied):
tokens embed into a low-dim continuous space; the TARGET span is diffused
with Gaussian noise at a sampled timestep while the SOURCE span stays clean
("partial noising" — the source conditions the denoiser through full
bidirectional attention); a transformer predicts x_0; the objective is
x0-MSE on the target span + a decodability NLL through the weight-tied
rounding head + a prior-matching ||sqrt(abar_T) x_0||^2 term.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.xent import token_cross_entropy
from .backbone import EMBED, TransformerBackbone, _dense_init
from .diffusion import DiffusionSchedule

__all__ = ["DiffuSeqModel", "diffuseq_losses", "timestep_embedding"]


def _pin_batch(x: jnp.ndarray) -> jnp.ndarray:
    """Pin an activation to pure batch sharding (data x fsdp on dim 0, every
    other dim replicated). The backbone kernels ZeRO-shard their EMBED input
    dims over fsdp; left to propagation, GSPMD pushes that hidden-dim
    sharding back onto the residual stream where it collides with the batch
    sharding and the partitioner falls back to "Involuntary full
    rematerialization" on every LayerNorm broadcast (dp x fsdp x tp meshes).
    Pinning the stream keeps activations batch-sharded and turns the weight
    shards into per-layer all-gathers instead. No-op without a mesh."""
    from jax.interpreters import pxla

    mesh = pxla.thread_resources.env.physical_mesh
    if mesh.empty or "data" not in mesh.shape or "fsdp" not in mesh.shape:
        return x
    spec = jax.sharding.PartitionSpec(("data", "fsdp"))
    return jax.lax.with_sharding_constraint(x, spec)


def timestep_embedding(t: jnp.ndarray, dim: int,
                       max_period: float = 10_000.0) -> jnp.ndarray:
    """Sinusoidal timestep features [B, dim] (f32; tiny op, precision cheap)."""
    half = dim // 2
    freqs = jnp.exp(-jnp.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    emb = jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


class DiffuSeqModel(nn.Module):
    """Denoiser: (x_t [B,L,E], t [B], pad_mask [B,L]) -> x0_hat [B,L,E].

    The word embedding doubles as the rounding head (weight tying), so the
    embedding space stays decodable — the core DiffuSeq trick.
    """

    vocab_size: int
    seq_len: int
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    emb_dim: int = 128
    dtype: jnp.dtype = jnp.bfloat16
    remat: bool = False
    attention_impl: str = "auto"
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    moe_no_drop: bool = False
    scan_layers: bool = False
    pp_chunks: int = 4
    pp_schedule: str = "1f1b"  # training schedule under a pipe > 1 mesh
    pp_virtual: int = 2  # virtual stages/device (pp_schedule="interleaved")
    scan_unroll: int = 0  # layer-scan unroll (pipeline.scan_unroll_for)

    def setup(self) -> None:
        # dim1 is the low-dim diffusion embedding SPACE (emb_dim), not the
        # model hidden dim — annotating it EMBED would shard it over fsdp
        # and every [B, L, emb] activation (x_start/x_t/noise) would inherit
        # a last-dim fsdp sharding that fights their batch sharding
        # (data x fsdp on dim0): the SPMD partitioner then falls back to
        # "Involuntary full rematerialization" (full replication) on every
        # reshard. The table still shards over vocab -> tensor.
        self.word_emb = nn.Embed(
            self.vocab_size, self.emb_dim,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab", None)),
            param_dtype=jnp.float32, name="word_emb")
        self.in_proj = nn.Dense(
            self.hidden_size, kernel_init=nn.with_logical_partitioning(
                _dense_init(self.emb_dim), (None, EMBED)),
            param_dtype=jnp.float32, dtype=self.dtype, name="in_proj")
        self.time_mlp = nn.Sequential([
            nn.Dense(4 * self.hidden_size, param_dtype=jnp.float32,
                     dtype=jnp.float32),
            nn.silu,
            nn.Dense(self.hidden_size, param_dtype=jnp.float32,
                     dtype=jnp.float32),
        ])
        self.pos_emb = self.param(
            "pos_emb", nn.with_logical_partitioning(
                nn.initializers.normal(0.02), (None, EMBED)),
            (self.seq_len, self.hidden_size), jnp.float32)
        self.backbone = TransformerBackbone(
            self.num_layers, self.num_heads, self.dtype, self.remat,
            causal=False, attention_impl=self.attention_impl,
            moe_experts=self.moe_experts, moe_top_k=self.moe_top_k,
            moe_every=self.moe_every,
            moe_capacity_factor=self.moe_capacity_factor,
            moe_no_drop=self.moe_no_drop,
            scan_layers=self.scan_layers, pp_chunks=self.pp_chunks,
            scan_unroll=self.scan_unroll,
            name="backbone")
        self.out_proj = nn.Dense(
            self.emb_dim, kernel_init=nn.with_logical_partitioning(
                _dense_init(self.hidden_size), (EMBED, None)),
            param_dtype=jnp.float32, dtype=self.dtype, name="out_proj")

    def embed(self, ids: jnp.ndarray) -> jnp.ndarray:
        """Token ids -> embedding-space points x_0, f32 [B, L, E]."""
        return self.word_emb(ids)

    def logits(self, x: jnp.ndarray) -> jnp.ndarray:
        """Rounding head: embedding-space points -> vocab logits via the tied
        embedding matrix. The matmul runs in the model compute dtype (bf16 on
        TPU — MXU accumulates in f32 internally) so the [B, L, V] output
        costs half the HBM traffic of an f32 head; softmax statistics are
        taken in f32 downstream (ops/xent.py)."""
        emb = self.word_emb.embedding
        return jnp.einsum("...e,ve->...v", x.astype(self.dtype),
                          emb.astype(self.dtype))

    def init_variables(self, ids: jnp.ndarray, t: jnp.ndarray,
                       pad_mask: jnp.ndarray) -> jnp.ndarray:
        """Init-time entry touching every submodule (``__call__`` alone never
        reaches ``word_emb``, so ``model.init`` must trace through here)."""
        x = self.embed(ids)
        return self.logits(self(x, t, pad_mask))

    def __call__(self, x_t: jnp.ndarray, t: jnp.ndarray,
                 pad_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        B, L, _ = x_t.shape
        h = self.in_proj(x_t.astype(self.dtype))
        h = h + self.time_mlp(timestep_embedding(t, self.hidden_size))[:, None, :].astype(self.dtype)
        h = h + self.pos_emb[None, :L].astype(self.dtype)
        h = _pin_batch(h)
        h = self.backbone(h, pad_mask)  # bidirectional, pad-masked
        h = _pin_batch(h)
        return self.out_proj(h).astype(jnp.float32)


def _masked_mean(x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Mean of per-position values [B, L] over mask==1 positions."""
    m = mask.astype(x.dtype)
    return jnp.sum(x * m) / jnp.maximum(jnp.sum(m), 1.0)


def diffuseq_losses(model: DiffuSeqModel, schedule: DiffusionSchedule,
                    params, batch: Dict[str, jnp.ndarray],
                    rng: jax.Array) -> Dict[str, jnp.ndarray]:
    """The DiffuSeq training objective as a pure function — this is the
    concrete ``compute_losses`` the reference declares as a user hook
    (``utils/trainer.py:23-25``). Returns a dict whose ``"loss"`` entry is
    optimized; the rest are logged (reference ``log_loss_dict`` hook)."""
    from ..parallel.ring import current_mesh

    mesh = current_mesh()
    if (mesh is not None and mesh.shape.get("pipe", 1) > 1
            and model.scan_layers and model.moe_experts == 0
            and mesh.shape.get("sequence", 1) == 1
            and model.pp_schedule in ("1f1b", "interleaved")):
        # (MoE and ring-in-stage pipe runs take the AD GPipe stream below
        # instead — the 1F1B engine has no MoE/sequence stage path)
        # training under a pipe mesh: the 1F1B streaming schedule computes
        # loss AND grads in one pass (models/schedule_1f1b.py)
        from .schedule_1f1b import diffuseq_1f1b_losses
        return diffuseq_1f1b_losses(model, schedule, params, batch, rng)
    ids = batch["input_ids"]
    tgt_mask = batch["input_mask"].astype(jnp.float32)   # diffused span
    pad_mask = batch["pad_mask"]
    B = ids.shape[0]

    rng_t, rng_noise = jax.random.split(rng)
    x_start = model.apply(params, ids, method=DiffuSeqModel.embed)  # [B,L,E] f32
    t = schedule.sample_t(rng_t, B)
    noise = jax.random.normal(rng_noise, x_start.shape, x_start.dtype)
    x_noisy = schedule.q_sample(x_start, t, noise)
    # Partial noising: target span diffuses, source span anchors.
    x_t = jnp.where(tgt_mask[..., None] > 0, x_noisy, x_start)

    x0_hat, mvars = model.apply(params, x_t, t, pad_mask,
                                mutable=["losses"])

    mse = _masked_mean(jnp.mean((x0_hat - x_start) ** 2, axis=-1), tgt_mask)
    tT = _masked_mean(schedule.mean_flat_tT(x_start), tgt_mask)
    logits = model.apply(params, x_start, method=DiffuSeqModel.logits)
    decoder_nll = _masked_mean(token_cross_entropy(logits, ids), tgt_mask)

    loss = mse + tT + decoder_nll
    out = {"loss": loss, "mse": mse, "tT": tT, "decoder_nll": decoder_nll}
    if jax.tree_util.tree_leaves(mvars.get("losses", {})):  # static: MoE model
        from .moe import MOE_AUX_WEIGHT, moe_aux_from
        aux = moe_aux_from(mvars)
        out["moe_aux"] = aux
        out["loss"] = loss + MOE_AUX_WEIGHT * aux
    return out
