"""Version bridges for the JAX surface this package targets.

The code is written against the current stable JAX API (``jax.shard_map``
with ``check_vma=``); some deployment images pin an older jax (0.4.x) where
shard_map still lives in ``jax.experimental.shard_map`` and the kwarg is
``check_rep=``. Importing ``shard_map`` from here gives every call site one
spelling that works on both — the alternative (per-site try/except and kwarg
probing) would smear version logic across five modules.
"""

from __future__ import annotations

__all__ = ["shard_map"]

try:
    from jax import shard_map  # jax >= 0.6: the stable top-level export
except ImportError:  # pragma: no cover - exercised only on old-jax images
    import functools

    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, /, *args, **kwargs):  # type: ignore[no-redef]
        # jax 0.4.x spells the replication/varying-manual-axes check
        # ``check_rep``; the semantics match what callers mean by
        # ``check_vma`` here (all call sites pass False).
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(f, *args, **kwargs)
