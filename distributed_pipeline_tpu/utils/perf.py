"""Performance accounting: step timing, tokens/sec, and MFU.

The reference's only profiling is the logger's wall-time context manager
(``/root/reference/basic_utils/logger.py:296-320``) plus a grad-norm metric
that forces a device->host sync every step (``utils/trainer.py:265-271``).
Here the north-star metric (BASELINE.md: tokens/sec/chip + MFU) gets
first-class gauges, and nothing in the hot path blocks on the device.
"""

from __future__ import annotations

import time
from typing import Optional

import jax

__all__ = ["device_peak_flops", "transformer_train_flops_per_token",
           "StepTimer", "mfu"]

# Peak dense bf16 FLOP/s per chip (public spec sheets), matched IN ORDER
# against jax's device_kind strings — real hardware reports e.g.
# "TPU v5 lite" (v5e) and "TPU v5p", so specific patterns come first.
# CPU entry keeps the gauge meaningful in tests.
_PEAK_FLOPS = (
    ("v6 lite", 918e12), ("v6e", 918e12),
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
    ("cpu", 1e11),
)


def device_peak_flops(device: Optional[jax.Device] = None) -> float:
    d = device or jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    for key, flops in _PEAK_FLOPS:
        if key in kind:
            return flops
    if d.platform == "tpu":  # unknown TPU generation: assume v4-class
        return 275e12
    return 1e11


def transformer_train_flops_per_token(n_params: int, n_layers: int,
                                      hidden: int, seq_len: int) -> float:
    """fwd+bwd FLOPs per trained token: the 6N weight-matmul term plus the
    12*l*h*s attention term (score + value matmuls, forward 4lhs, x3 with
    backward) — the standard accounting (e.g. PaLM appendix B)."""
    return 6.0 * n_params + 12.0 * n_layers * hidden * seq_len


def mfu(tokens_per_sec: float, flops_per_token: float,
        n_devices: Optional[int] = None) -> float:
    n = n_devices if n_devices is not None else jax.device_count()
    return tokens_per_sec * flops_per_token / (device_peak_flops() * n)


class StepTimer:
    """Wall-clock step timing with warmup skip (first steps compile).

    ``lap()`` returns (steps/sec, tokens/sec) over the window since the last
    call. Async-dispatch friendly: call it right after a ``block_until_ready``
    on the step output (or accept one-step skew).
    """

    def __init__(self, tokens_per_step: float, warmup: int = 2):
        self.tokens_per_step = tokens_per_step
        self.warmup = warmup
        self._steps = 0
        self._t0: Optional[float] = None
        self._window_steps = 0

    def tick(self) -> None:
        self._steps += 1
        if self._steps == self.warmup:
            self._t0 = time.perf_counter()
            self._window_steps = 0
        elif self._steps > self.warmup:
            self._window_steps += 1

    def lap(self):
        if self._t0 is None or self._window_steps == 0:
            return 0.0, 0.0
        dt = time.perf_counter() - self._t0
        sps = self._window_steps / max(dt, 1e-9)
        self._t0 = time.perf_counter()
        self._window_steps = 0
        return sps, sps * self.tokens_per_step
