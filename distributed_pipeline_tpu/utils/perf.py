"""Performance accounting: step timing, tokens/sec, and MFU.

The reference's only profiling is the logger's wall-time context manager
(``/root/reference/basic_utils/logger.py:296-320``) plus a grad-norm metric
that forces a device->host sync every step (``utils/trainer.py:265-271``).
Here the north-star metric (BASELINE.md: tokens/sec/chip + MFU) gets
first-class gauges, and nothing in the hot path blocks on the device.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

__all__ = ["device_peak_flops", "transformer_train_flops_per_token",
           "transformer_decode_flops_per_token", "active_param_count",
           "StepTimer", "mfu", "enable_persistent_compilation_cache",
           "timed_lower_compile", "AOTStep", "RecompileMonitor",
           "SanitizeReport", "SANITIZE_REPORT_NAME",
           "StallBreakdown", "EventStats", "GoodputTracker",
           "tree_bytes", "tree_bytes_per_replica", "peak_live_bytes"]

# Peak dense bf16 FLOP/s per chip (public spec sheets), matched IN ORDER
# against jax's device_kind strings — real hardware reports e.g.
# "TPU v5 lite" (v5e) and "TPU v5p", so specific patterns come first.
# CPU entry keeps the gauge meaningful in tests.
_PEAK_FLOPS = (
    ("v6 lite", 918e12), ("v6e", 918e12),
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
    ("cpu", 1e11),
)


def device_peak_flops(device: Optional[jax.Device] = None) -> float:
    d = device or jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    for key, flops in _PEAK_FLOPS:
        if key in kind:
            return flops
    if d.platform == "tpu":  # unknown TPU generation: assume v4-class
        return 275e12
    return 1e11


def transformer_train_flops_per_token(n_params: int, n_layers: int,
                                      hidden: int, seq_len: int) -> float:
    """fwd+bwd FLOPs per trained token: the 6N weight-matmul term plus the
    12*l*h*s attention term (score + value matmuls, forward 4lhs, x3 with
    backward) — the standard accounting (e.g. PaLM appendix B)."""
    return 6.0 * n_params + 12.0 * n_layers * hidden * seq_len


def transformer_decode_flops_per_token(n_params: int) -> float:
    """Forward-only FLOPs per DECODED token: the 2N weight-matmul term
    (each param participates in one multiply-add). The per-token
    attention share during cached decode is position-dependent and small
    next to the weight streaming that actually bounds decode — the 2N
    figure is the standard serving roofline numerator."""
    return 2.0 * n_params


def mfu(tokens_per_sec: float, flops_per_token: float,
        n_devices: Optional[int] = None) -> float:
    n = n_devices if n_devices is not None else jax.device_count()
    return tokens_per_sec * flops_per_token / (device_peak_flops() * n)


def active_param_count(params: Any, n_params: int, *, moe_experts: int = 0,
                       moe_top_k: int = 2) -> int:
    """Params ACTIVE per token: a top-k routed MoE block only runs top_k
    of its ``moe_experts`` expert MLPs, so counting every expert's
    weights would overstate the model FLOPs. Inactive mass is derived
    from the actual expert weight shapes (leading dim == moe_experts
    under a "moe" module — or dim 1 under a scan-group stack) so it
    tracks models/moe.py by construction. Dense models (or top_k >=
    experts) return ``n_params`` unchanged. One owner for the FLOPs-side
    param accounting (graftlint GL010): MFU numerators derive from THIS
    count, here or in obs/ledger.py."""
    if moe_experts <= moe_top_k:
        return n_params
    import numpy as np
    from jax.tree_util import tree_flatten_with_path

    leaves, _ = tree_flatten_with_path(params)
    # expert dim position differs by layout: named blocks stack experts
    # on dim 0 ([experts, ...]); MoEScanBlocks prepends a scan-group dim
    # ([groups, experts, ...]) — accept either.
    expert_params = sum(
        int(np.prod(leaf.shape))
        for path, leaf in leaves
        if any("moe" in str(getattr(k, "key", k)) for k in path)
        and leaf.ndim >= 2
        and (leaf.shape[0] == moe_experts
             or (leaf.ndim >= 3 and leaf.shape[1] == moe_experts)))
    return n_params - round(expert_params
                            * (moe_experts - moe_top_k) / moe_experts)


def tree_bytes(tree: Any) -> int:
    """Logical (global, unsharded) bytes of a pytree of arrays/abstract
    values — the model-size side of the HBM footprint gauges."""
    import numpy as np

    return sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
               for l in jax.tree_util.tree_leaves(tree)
               if hasattr(l, "shape") and hasattr(l, "dtype"))


def tree_bytes_per_replica(tree: Any) -> int:
    """Bytes of ONE device's shard of each leaf — what a replica actually
    holds. For ZeRO-1-sharded optimizer/EMA state this is the number that
    drops by ~dp vs :func:`tree_bytes`; unsharded leaves count in full."""
    import numpy as np

    total = 0
    for l in jax.tree_util.tree_leaves(tree):
        if not (hasattr(l, "shape") and hasattr(l, "dtype")):
            continue
        sharding = getattr(l, "sharding", None)
        shape = (sharding.shard_shape(l.shape) if sharding is not None
                 else l.shape)
        total += int(np.prod(shape)) * np.dtype(l.dtype).itemsize
    return total


def peak_live_bytes() -> int:
    """Peak live device allocation summed over local devices, from the
    backend's memory stats (``peak_bytes_in_use``); 0 where the backend
    reports none (CPU) — the gauge is then "unavailable", not "empty"."""
    total = 0
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            return 0
        if not stats:
            return 0
        total += int(stats.get("peak_bytes_in_use", 0))
    return total


def enable_persistent_compilation_cache(flag: str = "auto",
                                        run_dir: str = "") -> str:
    """Turn on JAX's on-disk compilation cache and return the directory
    (\"\" = disabled).

    Compile time is itself a hot path: a cold bench run pays a full XLA
    compile per leg, and a restarted/resumed elastic run pays the whole
    model compile again before its first step. Pointing
    ``jax_compilation_cache_dir`` at a stable directory makes every one of
    those a cache hit (arxiv 2204.06514 treats compile/dispatch setup as a
    first-class throughput concern at scale; so do we).

    ``flag`` semantics (the ``--compilation_cache_dir`` contract):

    * ``"off"`` / ``"none"`` / ``"0"`` — disabled;
    * ``"auto"`` / ``""`` — ``<run_dir>/compile_cache`` (restarts and
      resumes of the same run share it); disabled if no run dir is known;
    * anything else — an explicit directory, shareable across runs.

    The min-compile-time/entry-size gates are zeroed so the cache works for
    small CPU graphs too (tests, dev rings). The resolved dir is exported as
    ``JAX_COMPILATION_CACHE_DIR`` so spawned worker processes (the
    launcher's dev ring) inherit the same cache.

    JAX initializes its cache object at most once per process and then
    ignores config-dir changes, so both re-pointing at a new dir and
    ``"off"`` must go through ``compilation_cache.reset_cache()`` — without
    it a second enable() (or a disable) in the same process is silently a
    no-op against the first dir.
    """

    def _reset_initialized_cache() -> None:
        try:
            from jax._src import compilation_cache as _cc
            if getattr(_cc, "_cache_initialized", False):
                _cc.reset_cache()
        except Exception:
            pass  # private API drift: worst case is the once-only behavior

    if str(flag).lower() in ("off", "none", "0"):
        _reset_initialized_cache()
        jax.config.update("jax_compilation_cache_dir", None)
        os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
        return ""
    cache_dir = flag if flag and flag != "auto" else (
        os.path.join(run_dir, "compile_cache") if run_dir else "")
    if not cache_dir:
        return ""
    os.makedirs(cache_dir, exist_ok=True)
    _reset_initialized_cache()
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    return cache_dir


def timed_lower_compile(jitted: Any, *args: Any) -> Tuple[Any, float]:
    """Explicit AOT ``lower()``/``compile()`` of a jitted callable against
    concrete example args. Returns ``(compiled_executable, seconds)``.

    Dispatch-time compilation hides the (often dominant) compile cost inside
    the first call, where no one can measure it; lowering ahead of time puts
    a number on it — ``compile_time_s`` — and a persistent-cache hit shows
    up as that number collapsing."""
    t0 = time.perf_counter()
    compiled = jitted.lower(*args).compile()
    return compiled, time.perf_counter() - t0


class AOTStep:
    """Lazily AOT-compiled wrapper around a jitted step function.

    First call (or any call whose arg shapes/dtypes changed) runs an
    explicit ``lower()/compile()`` through :func:`timed_lower_compile` and
    reports the duration to ``on_compile(name, seconds)``; subsequent calls
    dispatch straight to the compiled executable. Shape changes fall back to
    a fresh compile rather than erroring, so callers keep jit's flexibility
    while gaining the timing split.

    ``pin_signature=True`` skips the per-call signature walk once compiled:
    for a large pytree argument (a params tree) the tree_map costs real
    host time on a hot sub-millisecond path (serving decode dispatches one
    step per generated token). Only for callers whose arg shapes are
    invariant by construction — a drifted shape then surfaces as the AOT
    executable's own mismatch error instead of a silent recompile."""

    def __init__(self, jitted: Any, name: str = "step",
                 on_compile: Optional[Callable[[str, float], None]] = None,
                 pin_signature: bool = False):
        self._jitted = jitted
        self.name = name
        self._on_compile = on_compile
        self._compiled: Any = None
        self._sig: Any = None
        self._pin = pin_signature
        self.compile_time_s = 0.0

    @property
    def compiled(self) -> Any:
        """The live compiled executable (``jax.stages.Compiled``), or
        None before the first call builds it — the handle the cost
        ledger (obs/ledger.py) extracts ``cost_analysis()``/
        ``memory_analysis()``/HLO text from."""
        return self._compiled

    @staticmethod
    def _signature(args: Any) -> Any:
        return jax.tree_util.tree_map(
            lambda a: (getattr(a, "shape", None), getattr(a, "dtype", None)),
            args)

    def __call__(self, *args: Any) -> Any:
        if self._pin and self._compiled is not None:
            return self._compiled(*args)
        sig = self._signature(args)
        if self._compiled is None or sig != self._sig:
            self._compiled, dt = timed_lower_compile(self._jitted, *args)
            self._sig = sig
            self.compile_time_s += dt
            if self._on_compile is not None:
                self._on_compile(self.name, dt)
        return self._compiled(*args)


class RecompileMonitor(logging.Handler):
    """Counts XLA compilations as they happen — the ``recompile_count``
    gauge behind sanitizer mode (``--sanitize``) and the bench leg rows.

    The static pass (analysis/, rule GL005) can only point at *patterns*
    that tend to recompile; this monitor observes the ground truth. It
    turns on ``jax_log_compiles`` and attaches itself as a logging
    handler on the ``jax`` logger: every backend compile emits exactly
    one ``"Compiling <name> ..."`` record (verified against this image's
    jax 0.4.37 dispatch AND the AOT lower()/compile() path; persistent-
    cache *hits* don't emit, so a warm restart legitimately counts 0).
    A steady-state training loop should stop counting after its step
    functions are built — growth after that is a silent retrace burning
    the accelerator.

    Use as a context manager or install()/uninstall(). ``count`` is the
    total since install; ``last`` keeps the most recent compile's name
    line for diagnostics."""

    _MARKER = "Compiling "
    _MAX_SITES = 16

    def __init__(self, capture_sites: bool = False) -> None:
        super().__init__(level=logging.NOTSET)
        self.count = 0
        self.last: str = ""
        self.sites: List[Dict[str, Any]] = []
        self._capture_sites = capture_sites
        self._prev_flag: Optional[bool] = None

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # pragma: no cover - malformed record
            return
        if msg.startswith(self._MARKER):
            self.count += 1
            self.last = msg.split("\n", 1)[0][:200]
            if self._capture_sites and len(self.sites) < self._MAX_SITES:
                # the compile log fires synchronously under the user's
                # dispatch — the deepest non-library frame on the stack
                # right now IS the host-side call that triggered it
                site = _user_site(traceback.extract_stack())
                if site is not None:
                    site["detail"] = self.last
                    site["ordinal"] = self.count
                    self.sites.append(site)

    def install(self) -> "RecompileMonitor":
        self._prev_flag = bool(jax.config.jax_log_compiles)
        jax.config.update("jax_log_compiles", True)
        logging.getLogger("jax").addHandler(self)
        return self

    def uninstall(self) -> None:
        logging.getLogger("jax").removeHandler(self)
        if self._prev_flag is not None:
            jax.config.update("jax_log_compiles", self._prev_flag)
            self._prev_flag = None

    __enter__ = install

    def __exit__(self, *exc: Any) -> None:
        self.uninstall()


SANITIZE_REPORT_NAME = "sanitize_report.json"

_THIS_FILE = os.path.abspath(__file__)


def _user_site(frames: "traceback.StackSummary"
               ) -> Optional[Dict[str, Any]]:
    """Deepest frame that belongs to USER code — not jax/site-packages,
    not the stdlib, not this module. That frame is where the evidence
    points when the static pass is asked 'did you clear this site?'."""
    for fr in reversed(list(frames)):
        fn = fr.filename or ""
        if (not fn or fn.startswith("<")
                or "site-packages" in fn or "dist-packages" in fn
                or "importlib" in fn
                or os.path.abspath(fn) == _THIS_FILE
                or fn.startswith(_STDLIB_DIR)):
            continue
        return {"path": os.path.abspath(fn), "line": int(fr.lineno or 1),
                "func": fr.name or "?", "snippet": (fr.line or "")[:200]}
    return None


_STDLIB_DIR = os.path.dirname(os.path.abspath(contextlib.__file__))


class SanitizeReport:
    """Machine-readable evidence from the runtime sanitizer — the bridge
    between ``--sanitize`` and the static pass (analysis/, GL013).

    Violations accumulate as dicts ``{kind, path, line, func, detail,
    snippet}`` where ``kind`` is ``transfer_guard`` (an implicit
    host<->device transfer tripped ``jax.transfer_guard("disallow")``)
    or ``steady_recompile`` (XLA compiles kept happening after steady
    state). ``write(dir)`` drops a ``sanitize_report.json`` sidecar
    atomically and never raises — evidence collection must not take the
    run down with it. When ``default_dir`` is set, every ``record``
    re-writes the sidecar so the evidence survives the crash that the
    violation itself usually causes.

    ``analysis --runtime-evidence RUN_DIR`` consumes the sidecar: a
    violation at a site the static pass cleared is a GL013 coverage-gap
    finding — the linter and the sanitizer audit each other instead of
    silently disagreeing."""

    VERSION = 1

    def __init__(self, default_dir: str = "") -> None:
        self.violations: List[Dict[str, Any]] = []
        self.default_dir = default_dir

    # ------------------------------------------------------------- capture

    def record(self, kind: str, detail: str,
               site: Optional[Dict[str, Any]] = None) -> None:
        if site is None:  # {} means "explicitly no location"
            site = _user_site(traceback.extract_stack()) or {}
        self.violations.append({
            "kind": kind,
            "path": site.get("path", ""),
            "line": site.get("line", 0),
            "func": site.get("func", ""),
            "snippet": site.get("snippet", ""),
            "detail": detail[:500],
        })
        if self.default_dir:
            self.write(self.default_dir)

    @staticmethod
    def _is_trip(exc: BaseException) -> bool:
        return "isallow" in str(exc)  # [Dd]isallowed transfer guard trip

    @staticmethod
    def _site_from(exc: BaseException) -> Optional[Dict[str, Any]]:
        return _user_site(traceback.extract_tb(exc.__traceback__))

    @contextlib.contextmanager
    def guard(self):
        """``jax.transfer_guard("disallow")`` that records the trip —
        site taken from the deepest user frame of the raising traceback
        — before re-raising. The violation is never swallowed: sanitize
        mode still fails loudly, it just leaves evidence behind."""
        with jax.transfer_guard("disallow"):
            try:
                yield
            except Exception as e:
                if self._is_trip(e):
                    self.record("transfer_guard", detail=str(e)[:500],
                                site=self._site_from(e))
                raise

    @contextlib.contextmanager
    def watch(self):
        """Record-only variant for code that arms the transfer guard
        itself (DecodeServer's engine): captures a trip's evidence as it
        propagates, without arming a second guard."""
        try:
            yield
        except Exception as e:
            if self._is_trip(e):
                self.record("transfer_guard", detail=str(e)[:500],
                            site=self._site_from(e))
            raise

    def note_recompiles(self, monitor: RecompileMonitor,
                        steady_after: int) -> None:
        """Fold a monitor's captured compile sites into violations: every
        compile OBSERVED after the first ``steady_after`` is a steady-
        state recompile (the warmup budget is the caller's to define —
        compiles-at-first-step for the trainer, compiles-at-first-token
        for the decode server)."""
        for site in monitor.sites:
            if site.get("ordinal", 0) <= steady_after:
                continue
            self.record(
                "steady_recompile",
                detail=f"XLA compile after steady state "
                       f"({site.get('detail', '')})",
                site=site)
        if not monitor.sites and monitor.count > steady_after:
            # monitor ran without site capture: still leave evidence,
            # just without a source location to cross-reference
            self.record(
                "steady_recompile",
                detail=f"{monitor.count - steady_after} XLA compile(s) "
                       f"after steady state ({monitor.last})",
                site={})

    # ------------------------------------------------------------- sidecar

    def write(self, out_dir: str) -> str:
        """Atomic best-effort sidecar write; returns the path ("" on any
        failure — remote paths, read-only dirs, mid-teardown)."""
        if not out_dir or "://" in out_dir:
            return ""
        path = os.path.join(out_dir, SANITIZE_REPORT_NAME)
        try:
            os.makedirs(out_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"version": self.VERSION,
                           "violations": self.violations}, f, indent=1)
            os.replace(tmp, path)
            return path
        except OSError:  # pragma: no cover - defensive
            return ""


class StallBreakdown:
    """Per-step stall accounting: WHERE the host loop's wall time goes,
    so "is the input pipeline the bottleneck" is a number, not a guess.

    Four gauges, attributed by the trainer / device-prefetch wrapper:

    * ``data_wait_s``   — blocked on the host iterator (batch assembly;
      the thread-prefetch queue was empty when the loop asked);
    * ``h2d_wait_s``    — blocked placing the batch on the mesh (the
      ``device_put``/``shard_batch`` call; near-zero when transfers
      overlap compute, the full copy cost on synchronous backends);
    * ``dispatch_s``    — enqueueing the compiled step (trace-cache
      lookup + argument handling; does NOT include device execution);
    * ``device_step_s`` — trailing: wall time from a step's dispatch
      returning to its outputs materializing, observed when the lagged
      metrics fetch blocks on a k-steps-old output (``dispatch_lag``;
      an upper bound on device execution — it includes queue wait).

    ``add`` accumulates; ``lap`` returns the window's per-step means and
    resets it (the ``log_interval`` cadence); ``totals`` is cumulative.
    Gauges with no samples report 0.0 so every sink/bench row carries
    all four keys.
    """

    GAUGES = ("data_wait_s", "h2d_wait_s", "dispatch_s", "device_step_s")

    def __init__(self) -> None:
        self._win = {g: [0.0, 0] for g in self.GAUGES}   # [sum, count]
        self._tot = {g: [0.0, 0] for g in self.GAUGES}

    def add(self, gauge: str, seconds: float) -> None:
        for acc in (self._win[gauge], self._tot[gauge]):
            acc[0] += seconds
            acc[1] += 1

    @staticmethod
    def _means(accs) -> dict:
        return {g: (s / n if n else 0.0) for g, (s, n) in accs.items()}

    def lap(self) -> dict:
        """Per-step means since the last lap; resets the window."""
        out = self._means(self._win)
        self._win = {g: [0.0, 0] for g in self.GAUGES}
        return out

    def totals(self) -> dict:
        """Cumulative per-step means since construction."""
        return self._means(self._tot)

    def sums(self) -> dict:
        """Cumulative SECONDS per gauge since construction (not means) —
        the goodput decomposition needs absolute time, not rates."""
        return {g: s for g, (s, _) in self._tot.items()}


class GoodputTracker:
    """Decomposes a training attempt's wall time into where it went, so
    "goodput" (useful-step time / wall time) is a number every run carries
    — the first-class metric large preemptible fleets are run by (ROADMAP
    item 5: preemption is the steady state, not the exception).

    Categories are EXCLUSIVE overheads, attributed by the trainer:

    * ``startup_s``   — process spawn -> TrainLoop construction (interpreter
      + jax import + distributed init; known only under the launcher, which
      stamps the spawn wall-clock into ``DPT_SPAWN_T``);
    * ``setup_s``     — TrainLoop construction minus restore (mesh/state
      init, trace-time work) — the share a restart pays even with a warm
      cache and nothing to restore;
    * ``restore_s``   — checkpoint discovery + restore (incl. the
      walk-back over corrupt checkpoints and the donation-safety copies);
    * ``compile_s``   — AOT lower()/compile() (collapses to the cache
      lookup on warm restarts);
    * ``save_s``      — blocking checkpoint-save time (schedule + barriers);
    * ``data_stall_s``— blocked on the input pipeline (attributed at
      summary time from the StallBreakdown sums);
    * ``recompute_s`` — re-running steps a previous attempt had already
      passed (work between the last checkpoint and a crash is lost and
      paid again after resume).

    One category deliberately does NOT live here: ``hang_s`` — the window
    a silently wedged attempt burned before the launcher's watchdog
    killed it. A hung process cannot attribute its own waste, so the
    LAUNCHER measures it (beacon freeze -> kill) and books it into the
    attempt record; :func:`chaos.goodput.aggregate_run` folds it as its
    own run-level category next to these.

    ``useful_step_s`` is the RESIDUAL: wall − Σ overheads. That makes the
    decomposition account for every second by construction — the honest
    framing, since "useful" legitimately includes dispatch and host-loop
    time the step pipeline needs. ``base_s`` shifts the wall-clock origin
    earlier than construction (the startup share measured on a different
    clock), so per-attempt wall ≈ spawn→now.
    """

    CATEGORIES = ("startup_s", "setup_s", "restore_s", "compile_s",
                  "save_s", "data_stall_s", "recompute_s")

    def __init__(self, t0: Optional[float] = None) -> None:
        self._t0 = time.perf_counter() if t0 is None else t0
        self.base_s = 0.0
        self._acc = {c: 0.0 for c in self.CATEGORIES}

    def add(self, category: str, seconds: float) -> None:
        self._acc[category] += max(0.0, seconds)

    def get(self, category: str) -> float:
        return self._acc[category]

    def wall_s(self) -> float:
        return self.base_s + (time.perf_counter() - self._t0)

    def summary(self, extra: Optional[dict] = None) -> dict:
        """Point-in-time decomposition. ``extra`` merges categories whose
        running total lives elsewhere (the trainer passes the
        StallBreakdown's ``data_stall_s`` sum here rather than mirroring
        every add)."""
        acc = dict(self._acc)
        for k, v in (extra or {}).items():
            acc[k] = acc.get(k, 0.0) + max(0.0, v)
        wall = self.wall_s()
        overhead = sum(acc.values())
        useful = max(0.0, wall - overhead)
        return {
            "wall_s": wall,
            "useful_step_s": useful,
            "goodput": (useful / wall) if wall > 0 else 0.0,
            **acc,
        }


class EventStats:
    """Per-event latency accounting (e.g. serving time-to-first-token):
    throughput means hide tail latency, and serving SLOs live in the tail.

    ``add`` records one event's seconds; ``summary`` reports count, mean,
    p50, p95 (nearest-rank on the sorted sample), and max — all 0.0 when
    empty so downstream rows always carry every key."""

    def __init__(self) -> None:
        self._vals: list = []

    def add(self, seconds: float) -> None:
        self._vals.append(float(seconds))

    def __len__(self) -> int:
        return len(self._vals)

    def summary(self) -> dict:
        if not self._vals:
            return {"count": 0, "mean": 0.0, "p50": 0.0, "p95": 0.0,
                    "max": 0.0}
        v = sorted(self._vals)
        n = len(v)
        return {
            "count": n,
            "mean": sum(v) / n,
            "p50": v[(n - 1) // 2],
            "p95": v[min(n - 1, max(0, -(-95 * n // 100) - 1))],
            "max": v[-1],
        }


class StepTimer:
    """Wall-clock step timing with warmup skip (first steps compile).

    ``lap()`` returns (steps/sec, tokens/sec) over the window since the last
    call. Async-dispatch friendly: call it right after a ``block_until_ready``
    on the step output (or accept one-step skew).
    """

    def __init__(self, tokens_per_step: float, warmup: int = 2):
        self.tokens_per_step = tokens_per_step
        self.warmup = warmup
        self._steps = 0
        self._t0: Optional[float] = None
        self._window_steps = 0

    def tick(self) -> None:
        self._steps += 1
        if self._steps == self.warmup:
            self._t0 = time.perf_counter()
            self._window_steps = 0
        elif self._steps > self.warmup:
            self._window_steps += 1

    def lap(self):
        if self._t0 is None or self._window_steps == 0:
            return 0.0, 0.0
        dt = time.perf_counter() - self._t0
        sps = self._window_steps / max(dt, 1e-9)
        self._t0 = time.perf_counter()
        self._window_steps = 0
        return sps, sps * self.tokens_per_step
