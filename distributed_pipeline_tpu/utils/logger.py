"""Rank-aware key-value metrics logger.

Capability parity with the reference logger (``/root/reference/basic_utils/
logger.py``, itself derived from the OpenAI-baselines logger): per-iteration
``logkv``/``logkv_mean`` accumulation, multi-sink ``dumpkvs`` flush, level-gated
text logging, a ``profile_kv`` wall-time context manager, and rank gating so
only one process writes sinks (reference gates on ``LOCAL_RANK==0`` at
logger.py:373-377; here we gate on ``jax.process_index()==0`` with an env-var
fallback so the logger works before/without JAX initialization).

Differences from the reference, on purpose:

* ``wandb`` is an optional import (the reference imports it unconditionally at
  logger.py:16, which breaks machines without it);
* cross-process metric averaging uses a JAX ``psum``-based helper
  (``distributed_mean``) instead of an MPI communicator;
* TensorBoard output uses ``tensorboardX``/``tf`` only if importable.

Sink formats: human-readable table, JSONL, CSV (with dynamic column migration,
reference logger.py:124-139), TensorBoard (optional), wandb (optional).
"""

from __future__ import annotations

import contextlib
import datetime
import json
import os
import os.path as osp
import sys
import tempfile
import time
import warnings
from collections import defaultdict
from typing import Any, Dict, IO, Iterable, List, Optional, Sequence, Union

__all__ = [
    "DEBUG", "INFO", "WARN", "ERROR", "DISABLED",
    "logkv", "logkv_mean", "logkv_sum", "logkvs", "logkvs_mean", "dumpkvs",
    "getkvs",
    "log", "debug", "info", "warn", "error",
    "set_level", "get_dir", "record_tabular", "dump_tabular",
    "profile_kv", "profile", "configure", "reset", "scoped_configure",
    "Logger", "get_current", "make_output_format", "append_output_format",
    "distributed_mean_comm",
]

DEBUG = 10
INFO = 20
WARN = 30
ERROR = 40
DISABLED = 50


def _fetch_all(values: Sequence[Any]) -> List[float]:
    """Materialize a batch of (possibly device-resident) scalars as floats
    with at most ONE device transfer. Per-value ``float()`` costs a full
    round trip each — ruinous on remote/tunneled accelerators (see
    ``Logger.merged_kvs``)."""
    values = list(values)
    try:
        import jax

        idx = [i for i, v in enumerate(values) if isinstance(v, jax.Array)]
        if idx:
            fetched = jax.device_get([values[i] for i in idx])
            for i, f in zip(idx, fetched):
                values[i] = f
    except ImportError:  # pure-python usage of the logger
        pass
    return [float(v) for v in values]


def _process_index() -> int:
    """Writer-rank detection without forcing JAX backend init.

    Env vars cover the pre-init window (set by the launcher, see
    parallel/launcher.py); after ``jax.distributed.initialize`` the authoritative
    ``jax.process_index()`` is used.
    """
    for var in ("JAX_PROCESS_INDEX", "PROCESS_INDEX", "LOCAL_RANK", "RANK"):
        if var in os.environ:
            try:
                return int(os.environ[var])
            except ValueError:
                pass
    try:
        import jax
        if jax._src.xla_bridge._backends:  # backend already up -> cheap & exact
            return jax.process_index()
    except Exception:
        pass
    return 0


# --------------------------------------------------------------------- sinks

class KVWriter:
    def writekvs(self, kvs: Dict[str, Any]) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class SeqWriter:
    def writeseq(self, seq: Iterable[str]) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class HumanOutputFormat(KVWriter, SeqWriter):
    """Aligned key | value table (reference logger.py:38-98), 30-char truncation."""

    def __init__(self, filename_or_file: Union[str, IO]):
        if isinstance(filename_or_file, str):
            self.file = open(filename_or_file, "at")
            self.own_file = True
        else:
            self.file = filename_or_file
            self.own_file = False

    @staticmethod
    def _truncate(s: str) -> str:
        return s[:27] + "..." if len(s) > 30 else s

    def writekvs(self, kvs: Dict[str, Any]) -> None:
        # Rows, not a dict keyed by truncated names: keys that collide after
        # truncation must both still be printed.
        rows = []
        for key, val in sorted(kvs.items()):
            valstr = f"{val:<8.3g}" if hasattr(val, "__float__") else str(val)
            rows.append((self._truncate(key), self._truncate(valstr)))
        if not rows:
            warnings.warn("Tried to write empty key-value dict")
            return
        keywidth = max(len(k) for k, _ in rows)
        valwidth = max(len(v) for _, v in rows)
        dashes = "-" * (keywidth + valwidth + 7)
        lines = [dashes]
        for key, val in rows:
            lines.append(f"| {key}{' ' * (keywidth - len(key))} | "
                         f"{val}{' ' * (valwidth - len(val))} |")
        lines.append(dashes)
        self.file.write("\n".join(lines) + "\n")
        self.file.flush()

    def writeseq(self, seq: Iterable[str]) -> None:
        self.file.write(" ".join(map(str, seq)) + "\n")
        self.file.flush()

    def close(self) -> None:
        if self.own_file:
            self.file.close()


class JSONOutputFormat(KVWriter):
    """One JSON object per dump (JSONL), numpy/jax scalars coerced to float
    (reference logger.py:101-113)."""

    def __init__(self, filename: str):
        self.file = open(filename, "at")

    def writekvs(self, kvs: Dict[str, Any]) -> None:
        out = {}
        for k, v in kvs.items():
            if hasattr(v, "dtype") or hasattr(v, "__float__"):
                try:
                    v = float(v)
                except (TypeError, ValueError):
                    v = str(v)
            out[k] = v
        self.file.write(json.dumps(out) + "\n")
        self.file.flush()

    def close(self) -> None:
        self.file.close()


class CSVOutputFormat(KVWriter):
    """CSV with dynamic column addition: when a new key appears, the whole file
    is rewritten with the widened header (reference logger.py:116-150)."""

    def __init__(self, filename: str):
        self.filename = filename
        self.file = open(filename, "a+t")
        self.keys: List[str] = []
        self.sep = ","
        # Recover keys from an existing file so resume appends consistently.
        self.file.seek(0)
        header = self.file.readline().strip("\n")
        if header:
            self.keys = header.split(self.sep)
        self.file.seek(0, os.SEEK_END)

    def writekvs(self, kvs: Dict[str, Any]) -> None:
        extra_keys = sorted(set(kvs.keys()) - set(self.keys))
        if extra_keys:
            self.keys.extend(extra_keys)
            self.file.seek(0)
            lines = self.file.readlines()
            self.file.seek(0)
            self.file.truncate()
            self.file.write(self.sep.join(self.keys) + "\n")
            for line in lines[1:]:
                self.file.write(line.rstrip("\n") + self.sep * len(extra_keys) + "\n")
        elif not self.file.tell():
            self.file.write(self.sep.join(self.keys) + "\n")
        row = []
        for key in self.keys:
            v = kvs.get(key)
            row.append("" if v is None else str(v))
        self.file.write(self.sep.join(row) + "\n")
        self.file.flush()

    def close(self) -> None:
        self.file.close()


class TensorBoardOutputFormat(KVWriter):
    """TensorBoard events via tensorboardX (optional; the reference reaches
    into raw TF internals, logger.py:153-191 — tensorboardX is the clean
    equivalent)."""

    def __init__(self, log_dir: str):
        from tensorboardX import SummaryWriter  # lazy; optional dep
        self.writer = SummaryWriter(log_dir)
        self.step = 1

    def writekvs(self, kvs: Dict[str, Any]) -> None:
        step = int(kvs.get("step", self.step))
        for k, v in kvs.items():
            if hasattr(v, "__float__"):
                self.writer.add_scalar(k, float(v), step)
        self.step = step + 1

    def close(self) -> None:
        self.writer.close()


class WandbOutputFormat(KVWriter):
    """wandb sink (optional import, unlike reference's hard import logger.py:16)."""

    def __init__(self):
        import wandb  # lazy; optional dep
        self.wandb = wandb

    def writekvs(self, kvs: Dict[str, Any]) -> None:
        if self.wandb.run is not None:
            self.wandb.log(dict(kvs))


def make_output_format(fmt: str, ev_dir: str, log_suffix: str = "") -> KVWriter:
    """Factory (reference logger.py:194-207)."""
    os.makedirs(ev_dir, exist_ok=True)
    if fmt == "stdout":
        return HumanOutputFormat(sys.stdout)
    if fmt == "log":
        return HumanOutputFormat(osp.join(ev_dir, f"log{log_suffix}.txt"))
    if fmt == "json":
        return JSONOutputFormat(osp.join(ev_dir, f"progress{log_suffix}.json"))
    if fmt == "csv":
        return CSVOutputFormat(osp.join(ev_dir, f"progress{log_suffix}.csv"))
    if fmt == "tensorboard":
        return TensorBoardOutputFormat(osp.join(ev_dir, f"tb{log_suffix}"))
    if fmt == "wandb":
        return WandbOutputFormat()
    raise ValueError(f"Unknown format specified: {fmt}")


# ----------------------------------------------------------------- front end

def logkv(key: str, val: Any) -> None:
    """Log one key-value pair for this iteration (overwrite semantics)."""
    get_current().logkv(key, val)


def logkv_mean(key: str, val: Any) -> None:
    """Log a value averaged over all calls between dumps (running mean)."""
    get_current().logkv_mean(key, val)


def logkv_sum(key: str, val: Any) -> None:
    """Accumulate a SUM over all calls between dumps (profile_kv semantics,
    exposed as a first-class call): right for additive costs like
    ``compile_time_s``, where several recompiles inside one log window
    should add up, not average away."""
    get_current().name2val[key] += val


def logkvs(d: Dict[str, Any]) -> None:
    for k, v in d.items():
        logkv(k, v)


def logkvs_mean(d: Dict[str, Any]) -> None:
    for k, v in d.items():
        logkv_mean(k, v)


def dumpkvs() -> Dict[str, Any]:
    """Flush accumulated key-values to all sinks; returns the dict
    (reference keeps this return "for unit testing purposes", logger.py:372)."""
    return get_current().dumpkvs()


def getkvs() -> Dict[str, Any]:
    return get_current().merged_kvs()


def log(*args: Any, level: int = INFO) -> None:
    get_current().log(*args, level=level)


def debug(*args: Any) -> None:
    log(*args, level=DEBUG)


def info(*args: Any) -> None:
    log(*args, level=INFO)


def warn(*args: Any) -> None:
    log(*args, level=WARN)


def error(*args: Any) -> None:
    log(*args, level=ERROR)


def set_level(level: int) -> None:
    get_current().set_level(level)


def get_dir() -> Optional[str]:
    """Directory the logger writes to (doubles as the checkpoint auto-resume
    discovery dir, reference trainer.py:330-335)."""
    return get_current().dir


record_tabular = logkv
dump_tabular = dumpkvs


@contextlib.contextmanager
def profile_kv(scopename: str, sync_fn=None):
    """Accumulate wall time into ``wait_<scope>`` (reference logger.py:296-303).
    ``sync_fn`` (e.g. ``jax.block_until_ready`` on a result) makes async device
    work attributable to the scope. The interval comes from an
    ``obs.trace.Stopwatch`` (monotonic, and the GL009-sanctioned owner of
    ad-hoc timing deltas — a raw ``time.time()`` subtraction here was the
    rule's dogfooded true positive, and wall-clock steps could book
    negative or inflated waits)."""
    from ..obs.trace import Stopwatch

    logkey = "wait_" + scopename
    watch = Stopwatch()
    try:
        yield
    finally:
        if sync_fn is not None:
            sync_fn()
        get_current().name2val[logkey] += watch.lap_s()


def profile(n: str):
    """Decorator: profile_kv around every call (reference logger.py:306-320)."""
    def decorator(func):
        def wrapper(*args, **kwargs):
            with profile_kv(n):
                return func(*args, **kwargs)
        wrapper.__name__ = getattr(func, "__name__", "wrapped")
        return wrapper
    return decorator


# ------------------------------------------------------------------- backend

class Logger:
    CURRENT: Optional["Logger"] = None
    DEFAULT: Optional["Logger"] = None

    # logkv_mean folds its raw-value buffer into a (sum, count) pair whenever
    # it reaches this many entries, so huge log_intervals can't pin an
    # unbounded list of device scalars. The fold keeps the newest
    # MEAN_BUF_KEEP entries raw: those may be in-flight device scalars from
    # the current step (a caller may log one key up to MEAN_BUF_KEEP times
    # per step), and float() on an in-flight scalar would stall the
    # pipeline — the exact sync this buffering avoids. Everything older is
    # long since computed, so float() is a cheap copy.
    MEAN_BUF_CAP = 256
    MEAN_BUF_KEEP = 32

    def __init__(self, dir: Optional[str], output_formats: Sequence[KVWriter],
                 comm: Any = None):
        self.name2val: Dict[str, float] = defaultdict(float)
        self.name2mean: Dict[str, list] = {}
        self.name2mean_folded: Dict[str, list] = {}  # key -> [sum, count]
        self.level = INFO
        self.dir = dir
        self.output_formats = list(output_formats)
        self.comm = comm  # optional distributed-mean hook (callable: dict->dict)

    # kv API
    def logkv(self, key: str, val: Any) -> None:
        self.name2val[key] = val

    def logkv_mean(self, key: str, val: Any) -> None:
        # Values are buffered raw and averaged at dumpkvs: no float(val) here,
        # or every logged jax device scalar forces a device->host sync per
        # step (the reference's grad-norm bug, trainer.py:265-271). Buffering
        # also never does array arithmetic, so values from different device
        # meshes can coexist until they become floats at dump.
        buf = self.name2mean.setdefault(key, [])
        buf.append(val)
        if len(buf) >= self.MEAN_BUF_CAP:
            keep = self.MEAN_BUF_KEEP
            folded = self.name2mean_folded.setdefault(key, [0.0, 0])
            folded[0] += sum(_fetch_all(buf[:-keep]))
            folded[1] += len(buf) - keep
            del buf[:-keep]

    def merged_kvs(self, return_counts: bool = False):
        """Overwrite-keys plus materialized means (device scalars become
        floats here — the single sync point). ALL buffered device scalars
        transfer in ONE device_get: fetching them one-by-one costs a full
        device round trip each, which on a remote-tunneled accelerator turns
        a dump into a minute-long stall (measured 60s/dump on the v5e
        tunnel at log_interval=100 — 4x total training slowdown).

        ``return_counts=True`` additionally returns each key's sample
        count (overwrite keys count 1) — what the cross-process comm
        weights by, matching the reference's ``mpi_weighted_mean``
        (logger.py:418-445) semantics for uneven per-host counts."""
        d = dict(self.name2val)
        counts = {k: 1 for k in d}
        keys = sorted(set(self.name2mean) | set(self.name2mean_folded))
        flat: list = []
        spans = {}
        for key in keys:
            buf = self.name2mean.get(key, ())
            spans[key] = (len(flat), len(buf))
            flat.extend(buf)
        fetched = _fetch_all(flat)
        for key in keys:
            s, n = self.name2mean_folded.get(key, (0.0, 0))
            start, ln = spans[key]
            total = s + sum(fetched[start:start + ln])
            count = n + ln
            if count:
                d[key] = total / count
                counts[key] = count
        return (d, counts) if return_counts else d

    def dumpkvs(self) -> Dict[str, Any]:
        if self.level == DISABLED:
            return {}
        d, counts = self.merged_kvs(return_counts=True)
        if self.comm is not None:
            import inspect
            try:
                two_arg = len(inspect.signature(
                    self.comm).parameters) >= 2
            except (TypeError, ValueError):  # builtins/partials: assume new
                two_arg = True
            d = self.comm(d, counts) if two_arg else self.comm(d)
        if _process_index() == 0:
            for fmt in self.output_formats:
                if isinstance(fmt, KVWriter):
                    fmt.writekvs(d)
        self.name2val.clear()
        self.name2mean.clear()
        self.name2mean_folded.clear()
        return d

    # text API
    def log(self, *args: Any, level: int = INFO) -> None:
        if self.level <= level:
            self._do_log(args)

    def set_level(self, level: int) -> None:
        self.level = level

    def set_comm(self, comm: Any) -> None:
        self.comm = comm

    def get_dir(self) -> Optional[str]:
        return self.dir

    def close(self) -> None:
        for fmt in self.output_formats:
            fmt.close()

    def _do_log(self, args: Iterable[Any]) -> None:
        for fmt in self.output_formats:
            if isinstance(fmt, SeqWriter):
                fmt.writeseq(map(str, args))


def get_current() -> Logger:
    if Logger.CURRENT is None:
        _configure_default_logger()
    return Logger.CURRENT  # type: ignore[return-value]


def append_output_format(fmt: str) -> None:
    """Attach one more sink to the current logger — the hook that lets the
    entry point add the wandb sink only after ``wandb.init`` succeeded
    (the reference instead hardwires ``wandb.log`` into dumpkvs,
    logger.py:373-377)."""
    cur = get_current()
    cur.output_formats.append(make_output_format(fmt, cur.dir or "."))


def distributed_mean_comm():
    """Returns a comm callable averaging numeric metrics across JAX
    processes, COUNT-WEIGHTED like the reference's ``mpi_weighted_mean``
    (logger.py:418-445): each rank contributes (value * count, count) per
    key and the merged metric is sum(v*c)/sum(c), so uneven per-host
    sample counts (ragged eval tails, rank-gated logging cadence) do not
    skew the mean. Multi-host safe via
    ``multihost_utils.process_allgather``. No-op when single-process."""
    def comm(d: Dict[str, Any],
             counts: Optional[Dict[str, int]] = None) -> Dict[str, Any]:
        import jax
        if jax.process_count() == 1:
            return d
        import numpy as np
        import zlib
        from jax.experimental import multihost_utils
        keys = sorted(k for k, v in d.items() if hasattr(v, "__float__"))
        if not keys:
            return d
        # Ranks may log divergent key sets (rank-guarded metrics); a blind
        # allgather would misalign values by index. Verify agreement first
        # and fail safe to local values when key sets differ.
        key_hash = np.array([zlib.crc32(",".join(keys).encode()), len(keys)],
                            dtype=np.int64)
        all_hashes = np.asarray(multihost_utils.process_allgather(key_hash))
        if not (all_hashes == all_hashes[0]).all():
            warnings.warn("distributed_mean: metric key sets differ across "
                          "processes; skipping cross-process averaging")
            return d
        counts = counts or {}
        cnt = np.array([float(counts.get(k, 1) or 1) for k in keys],
                       dtype=np.float64)
        val = np.array([float(d[k]) for k in keys], dtype=np.float64)
        local = np.stack([val * cnt, cnt])                  # [2, K]
        gathered = np.asarray(multihost_utils.process_allgather(local))
        sums = gathered.reshape(jax.process_count(), 2, -1).sum(axis=0)
        mean = sums[0] / np.maximum(sums[1], 1.0)
        out = dict(d)
        out.update({k: float(m) for k, m in zip(keys, mean)})
        return out
    return comm


def configure(dir: Optional[str] = None, format_strs: Optional[Sequence[str]] = None,
              comm: Any = None, log_suffix: str = "",
              _close_prev: bool = True) -> None:
    """Configure the global logger (reference logger.py:448-477).

    Directory defaults to ``$OPENAI_LOGDIR`` or a dated tmp dir; non-zero
    processes get a ``-rank%03i`` file suffix; formats default from
    ``$OPENAI_LOG_FORMAT`` (writer rank) / ``$OPENAI_LOG_FORMAT_MPI`` (others).
    """
    if dir is None:
        dir = os.getenv("OPENAI_LOGDIR")
    if dir is None:
        dir = osp.join(
            tempfile.gettempdir(),
            datetime.datetime.now().strftime("dpt-%Y-%m-%d-%H-%M-%S-%f"),
        )
    assert isinstance(dir, str)
    dir = osp.expanduser(dir)
    os.makedirs(dir, exist_ok=True)

    rank = _process_index()
    if rank > 0:
        log_suffix = log_suffix + "-rank%03i" % rank
    if format_strs is None:
        if rank == 0:
            format_strs = os.getenv("OPENAI_LOG_FORMAT", "stdout,log,csv").split(",")
        else:
            format_strs = os.getenv("OPENAI_LOG_FORMAT_MPI", "log").split(",")
    format_strs = list(filter(None, format_strs))
    output_formats = [make_output_format(f, dir, log_suffix) for f in format_strs]

    # Close the logger being replaced so its file handles flush and release
    # (skipped by scoped_configure, which restores the previous logger).
    if (_close_prev and Logger.CURRENT is not None
            and Logger.CURRENT is not Logger.DEFAULT):
        Logger.CURRENT.close()
    Logger.CURRENT = Logger(dir=dir, output_formats=output_formats, comm=comm)
    if output_formats:
        log(f"Logging to {dir}")


def _configure_default_logger() -> None:
    configure(format_strs=["stdout"])
    Logger.DEFAULT = Logger.CURRENT


def reset() -> None:
    if Logger.CURRENT is not Logger.DEFAULT:
        if Logger.CURRENT is not None:
            Logger.CURRENT.close()
        Logger.CURRENT = Logger.DEFAULT
        log("Reset logger")


@contextlib.contextmanager
def scoped_configure(dir: Optional[str] = None,
                     format_strs: Optional[Sequence[str]] = None,
                     comm: Any = None):
    prevlogger = Logger.CURRENT
    configure(dir=dir, format_strs=format_strs, comm=comm, _close_prev=False)
    try:
        yield
    finally:
        if Logger.CURRENT is not None:
            Logger.CURRENT.close()
        Logger.CURRENT = prevlogger
