"""TrainLoop: the training runtime as ONE jitted step.

Capability parity with the reference engine (``/root/reference/utils/
trainer.py``): microbatch gradient accumulation, AdamW, multi-rate EMA,
linear LR annealing, gradient clipping with grad-norm telemetry, interval-
driven log/eval/save, and filename-convention checkpoint/resume.

TPU-native redesign (SURVEY.md §3.4 hot-loop notes) — everything the
reference does eagerly folds into a single compiled step:

==============================================  ===========================
reference (eager torch, per step)               here (inside one jit)
==============================================  ===========================
python micro loop + DDP ``no_sync`` juggling    ``lax.scan`` over a
  (trainer.py:230-235, 216-220)                 [n_micro, ...] batch; XLA
                                                emits ONE gradient psum
``(p.grad**2).sum().item()`` per param — a      ``optax.global_norm`` as a
  device->host sync every step (:265-271)       device scalar, no sync
``_anneal_lr`` mutating opt groups (:257)       optax schedule traced into
                                                the step
EMA python loop per rate (:360-370)             vectorized pytree lerp
DDP bucketed all-reduce (:115-128)              sharding propagation: grads
                                                inherit the params' specs
==============================================  ===========================

The loop structure, hook names, and checkpoint layout stay recognizably the
reference's (``run_loop``/``run_step``/``forward_only``/``save``), so a user
of the reference scaffold finds the same control surface.
"""

from __future__ import annotations

import collections
import contextlib
import os
import time
from typing import Any, Callable, Dict, Iterator, Optional, Sequence, Tuple, \
    Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from flax import struct
from jax.sharding import Mesh

from ..data.device_prefetch import DeviceBatch, prefetch_to_device
from ..models import Workload
from ..obs import ledger as ledger_lib
from ..obs import trace as trace_lib
from ..ops.fused_update import fused_adamw_ema, resolve_fused_update
from ..parallel import mesh as mesh_lib
from ..parallel import partition as partition_lib
from ..parallel.sharding import (
    batch_shardings,
    param_shardings,
    replicated,
    shard_batch,
)
from . import checkpoint as ckpt_lib
from . import logger
from .perf import AOTStep, GoodputTracker, RecompileMonitor, \
    SanitizeReport, StallBreakdown, \
    StepTimer, device_peak_flops, mfu, peak_live_bytes, tree_bytes, \
    tree_bytes_per_replica, transformer_train_flops_per_token

__all__ = ["TrainLoop", "TrainState", "update_ema"]


@struct.dataclass
class TrainState:
    """Everything the jitted step owns (donated and returned every step)."""

    step: jnp.ndarray            # int32 scalar
    params: Any
    opt_state: Any
    ema: Dict[str, Any]          # rate-string -> params-shaped tree


def update_ema(ema: Any, params: Any, rate: float) -> Any:
    """``trg = trg*rate + src*(1-rate)`` as a pytree lerp (reference
    ``update_ema``, trainer.py:360-370, in-place loop)."""
    return jax.tree_util.tree_map(
        lambda e, p: e * rate + p * (1.0 - rate), ema, params)


def _abstract_like(tree: Any) -> Any:
    """Live tree -> ShapeDtypeStructs carrying the live shardings (the
    restore target for checkpoint resume)."""
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding),
        tree)


class TrainLoop:
    """Reference-shaped constructor (``TrainLoop(...)`` then ``.run_loop()``
    or ``()``, trainer.py:45/175/357); ``model`` is a :class:`models.Workload`.
    """

    def __init__(
        self,
        *,
        model: Workload,
        data: Iterator[Dict[str, np.ndarray]],
        batch_size: int,
        microbatch: int = -1,
        lr: float = 1e-4,
        ema_rate: str = "0.9999",
        log_interval: int = 50,
        eval_interval: int = 1000,
        save_interval: int = 10000,
        resume_checkpoint: str = "",
        gradient_clipping: float = -1.0,
        weight_decay: float = 0.0,
        learning_steps: int = 0,
        eval_data: Optional[Iterator[Dict[str, np.ndarray]]] = None,
        eval_callbacks: Sequence[Callable[["TrainLoop"], None]] = (),
        mesh: Optional[Mesh] = None,
        checkpoint_dir: str = "",
        seed: int = 102,
        profile_dir: str = "",
        warmup_steps: int = 0,
        keep_checkpoints: int = 0,
        eval_batches_consumed: int = 0,
        sanitize: bool = False,
        prefetch_depth: int = 0,
        dispatch_lag: int = 0,
        chaos: Optional[Any] = None,
        progress_file: str = "",
        recompute_until_step: int = 0,
        shard_optimizer: bool = False,
        fused_update: Any = "auto",
        partition_rules: Optional[Sequence[Tuple[str, Any]]] = None,
        trace: Optional[bool] = None,
        profile_steps: str = "",
        cost_ledger: bool = False,
    ) -> None:
        # Time-to-signal accounting starts at construction: everything up
        # to the end of the first optimizer step (state init, restore,
        # tracing, XLA compile, dispatch) is setup the user waits through.
        self._construct_t0 = time.perf_counter()
        self.workload = model
        self.data = data
        self.eval_data = eval_data
        self.eval_callbacks = list(eval_callbacks)
        self.batch_size = batch_size
        # microbatch default = whole batch (reference trainer.py:70)
        self.microbatch = microbatch if microbatch > 0 else batch_size
        if batch_size % self.microbatch:
            raise ValueError(f"batch_size {batch_size} not divisible by "
                             f"microbatch {self.microbatch} (static shapes)")
        self.n_micro = batch_size // self.microbatch
        self.lr = lr
        self.ema_rates: Tuple[str, ...] = tuple(
            r.strip() for r in str(ema_rate).split(",") if r.strip())
        self.log_interval = log_interval
        self.eval_interval = eval_interval
        # cumulative eval batches drawn (incl. before a resume) — recorded
        # in each checkpoint's meta sidecar so resumes fast-forward the
        # eval stream exactly even if --eval_interval changed
        self.eval_batches_consumed = eval_batches_consumed
        self.save_interval = save_interval
        self.gradient_clipping = gradient_clipping
        self.weight_decay = weight_decay
        self.learning_steps = learning_steps
        self.warmup_steps = warmup_steps
        self.keep_checkpoints = keep_checkpoints
        self._saver = ckpt_lib.AsyncSaver()
        self.checkpoint_dir = checkpoint_dir or logger.get_dir() or ""
        # Run-dir handshake: the launcher cannot re-derive the run dir a
        # wrapped script resolved, so workers stamp it into the file the
        # launcher names — that is where attempts.jsonl and the progress
        # beacons live. Every rank writes (identical content, last wins):
        # the rank-0 worker may be the one the chaos plan just killed.
        run_dir_file = os.environ.get("DPT_RUN_DIR_FILE", "")
        if run_dir_file and self.checkpoint_dir:
            d = (self.checkpoint_dir if "://" in self.checkpoint_dir
                 else os.path.abspath(self.checkpoint_dir))
            try:
                with open(run_dir_file, "w") as f:
                    f.write(d)
            except OSError:
                pass  # supervision telemetry must never fail training
        # SURVEY.md §5.1 rebuild note: a first-class jax.profiler trace hook.
        # A short window a few steps in (past compilation) is captured into
        # profile_dir in TensorBoard format; 0-length dir disables.
        # --profile_steps "A:B" overrides the window (loop steps, [A, B)) —
        # the programmatic XLA-level view next to the obs/ span timeline.
        self.profile_dir = profile_dir
        self._profile_window = (3, 8)  # [start, stop) steps after loop entry
        if profile_steps:
            try:
                a, b = (int(x) for x in profile_steps.split(":"))
            except ValueError:
                raise ValueError(f"profile_steps must be 'A:B' loop-step "
                                 f"ints, got {profile_steps!r}") from None
            if not 0 <= a < b:
                raise ValueError(f"profile_steps window must satisfy "
                                 f"0 <= A < B, got {profile_steps!r}")
            self._profile_window = (a, b)
        self._profiling = False
        # tri-state: True arms, False forces OFF (an A/B's control arm
        # must stay untraced even under DPT_TRACE), None defers to the
        # env (how launcher-supervised rings arm without a CLI flag)
        self._trace = trace

        # Cost ledger (obs/ledger.py): per-compiled-program FLOPs/bytes/
        # collective extraction + the roofline MFU-gap attribution row,
        # logged each log window and snapshotted to
        # <run_dir>/perf_ledger.json. Off by default: extraction is a
        # one-time HLO walk but the padding meter touches every batch.
        self.cost_ledger = cost_ledger
        self.padding = ledger_lib.PaddingMeter() if cost_ledger else None
        # measured steady rate anchor, armed at first-step completion:
        # (steps since, seconds since, stall sums since) excludes the
        # compile-bearing first step, the same boundary
        # steady_recompile_count uses
        self._ledger_watch: Optional[trace_lib.Stopwatch] = None
        self._ledger_step0 = 0
        self._ledger_stall0: Dict[str, float] = {}
        # extraction cache: cost_analysis + the HLO walk are immutable
        # per compiled executable, and as_text() on a real model is a
        # multi-second serialization — paying it once per log window
        # would inflate the very mfu_gap_host the ledger reports.
        # Keyed by executable identity so an AOTStep shape-change
        # recompile invalidates naturally.
        self._ledger_cost_cache: Dict[str, Tuple[Any, Dict[str, Any]]] = {}

        # Steady-state throughput layer (ISSUE 5): keep the device queue
        # full. prefetch_depth > 0 wraps the data iterator so batches are
        # device_put onto the mesh (with the step's exact sharding) while
        # the previous step computes; dispatch_lag = k defers fetching a
        # step's metric scalars until k later steps have dispatched, so
        # the host never blocks on the step it just enqueued. Both default
        # OFF here (the config layer turns them on for real runs) so the
        # eager semantics tests rely on stay the default API behavior.
        self.prefetch_depth = prefetch_depth
        self.dispatch_lag = dispatch_lag
        self.stalls = StallBreakdown()
        # (loop step idx, dispatch-return timestamp, device metrics tree)
        self._inflight: "collections.deque" = collections.deque()

        # Chaos harness + goodput accounting (ISSUE 8). ``chaos`` is a
        # ChaosInjector (or None): three hook points — top of run_step,
        # before each batch pull, right after a save is scheduled — let a
        # ChaosPlan kill/stall/corrupt this process at an exact step.
        # ``progress_file`` (set by run/train.py under the launcher) is a
        # per-step beacon: current step + in-attempt goodput snapshot,
        # atomically replaced each step — a SIGKILLed attempt's flight
        # recorder, and how the launcher measures step progress for its
        # crash-loop fail-fast. ``recompute_until_step`` marks steps an
        # earlier attempt already paid for (the last-checkpoint..crash
        # window): their wall time books as recompute, not useful.
        self.chaos = chaos
        self.progress_file = progress_file
        self.recompute_until_step = recompute_until_step

        # Auto-sharding engine (ISSUE 9): params shard by the workload's
        # declared partition-rule table (parallel/partition.py) —
        # ``partition_rules`` overrides it per run; workloads with neither
        # keep the flax logical-metadata compat path. ``shard_optimizer``
        # turns on ZeRO-1: Adam moments and EMA copies additionally
        # sharded across the data mesh axis with gather-on-use inside the
        # compiled step (per-replica weight-update memory / ~dp).
        self.shard_optimizer = shard_optimizer
        # --fused_update swaps the staged optax update (opt.update ->
        # apply_updates -> one EMA tree-map per rate) for the single-pass
        # Pallas kernel (ops/fused_update.py); losses stay bit-identical
        # and the opt_state pytree keeps optax's structure, so checkpoints
        # and ZeRO-1 shardings don't care which path wrote them. The flag
        # is tri-state ("auto" = fused on TPU only); resolve it once here.
        self.fused_update = resolve_fused_update(fused_update)
        self.partition_rules = (tuple(partition_rules)
                                if partition_rules else None)
        self.goodput = GoodputTracker(t0=self._construct_t0)
        spawn_t = os.environ.get("DPT_SPAWN_T", "")
        if spawn_t:
            # The launcher stamps each worker's spawn wall-clock: the
            # interpreter+jax+distributed-init span before this
            # constructor ran is real attempt time, booked as startup.
            startup = max(0.0, time.time() - float(spawn_t))
            self.goodput.base_s = startup
            self.goodput.add("startup_s", startup)
        self._recompiles_at_first_step: Optional[int] = None

        # Runtime sanitizer (the dynamic half of analysis/ graftlint):
        # count every XLA compile into the recompile_count gauge, and run
        # the train/eval step dispatch under a jax transfer guard so any
        # IMPLICIT host<->device transfer (a stray numpy array reaching a
        # compiled call, a tracer silently fetched) raises instead of
        # quietly serializing the step. Explicit device_put/device_get —
        # everything the loop does on purpose — stays legal.
        self.sanitize = sanitize
        self._recompiles = RecompileMonitor(capture_sites=sanitize)
        # Machine-readable evidence sidecar (ISSUE 19 runtime bridge):
        # every guard trip / steady recompile lands in
        # <checkpoint_dir>/sanitize_report.json for the static pass to
        # cross-reference (analysis --runtime-evidence, GL013).
        self.sanitize_report = SanitizeReport(
            default_dir=self.checkpoint_dir if sanitize else "")
        self._sanitizer_reported = False
        if sanitize:
            self._recompiles.install()
        try:
            self._finish_init(mesh, batch_size, seed, resume_checkpoint)
        except BaseException:
            # construction can die mid-build (param init / AOT compile is
            # where an HBM OOM fires) and callers that retry with a smaller
            # batch (bench.py) never get a handle to stop_sanitizer() —
            # detach the process-global hooks here so a failed attempt
            # doesn't leak the 'jax' logging handler or leave
            # jax_log_compiles stuck on.
            self._recompiles.uninstall()
            raise

    def _finish_init(self, mesh, batch_size: int, seed: int,
                     resume_checkpoint: str) -> None:
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh()
        # Under the launcher (DPT_ATTEMPT set) every TrainLoop emits the
        # per-step progress beacon by default: supervision — crash-loop
        # detection, step-progress records, post-mortem goodput — works
        # for ANY wrapped script, not just run/train.py.
        if (not self.progress_file and self.checkpoint_dir
                and "://" not in self.checkpoint_dir
                and os.environ.get("DPT_ATTEMPT") is not None):
            from ..chaos.goodput import beacon_path
            self.progress_file = beacon_path(self.checkpoint_dir,
                                             jax.process_index())
        # Span tracing (obs/): armed by the trace flag or DPT_TRACE (the
        # env rides the launcher's worker environment to every attempt of
        # every ring, like DPT_PREFETCH_DEPTH). Off -> the NULL tracer:
        # one attribute check per hook, no span objects, no writes. Spans
        # are booked from the SAME measured seconds handed to the goodput
        # tracker, so the trace and the ledger can never disagree.
        self.tracer = trace_lib.tracer_for(
            self.checkpoint_dir, jax.process_index(), armed=self._trace)
        # global batch = per-host batch x hosts (reference trainer.py:89)
        self.global_batch = batch_size * jax.process_count()
        dpf = (self.mesh.shape["data"] * self.mesh.shape["fsdp"]
               * self.mesh.shape["expert"])
        global_micro = self.microbatch * jax.process_count()
        if global_micro % dpf:
            raise ValueError(
                f"global microbatch {global_micro} (= microbatch "
                f"{self.microbatch} x {jax.process_count()} hosts) must be "
                f"divisible by data x fsdp x expert mesh axes = {dpf}")
        self._base_rng = jax.random.PRNGKey(seed)

        # AOT compile metrics (perf.AOTStep): total seconds spent in
        # lower()/compile() and construction->first-optimizer-step wall time.
        # None until the first step so a zero can't masquerade as "free".
        self.compile_time_s: Optional[float] = None
        self.time_to_first_step_s: Optional[float] = None

        self._build_state(resume_checkpoint)
        self._build_step_fns()

        # Device prefetch wraps the data stream AFTER the step fns exist
        # (it places batches with _prepare's sharding — the layout the AOT
        # step was compiled for). Wrapping only reorders WHEN transfers
        # happen, never WHICH indices the underlying iterator draws, so
        # skip_batches exact-resume is untouched.
        if self.prefetch_depth > 0 and self.data is not None:
            self.data = self._wrap_prefetch(self.data)

        # Cumulative sample count via the get_batch_length hook; seeded from
        # the resumed step so the gauge is continuous across restarts.
        self._samples = self.step * self.global_batch

        tokens_per_step = self.global_batch * self.workload.seq_len
        self._timer = StepTimer(tokens_per_step)
        self._flops_per_token = transformer_train_flops_per_token(
            self.n_params, self.workload.num_layers,
            self.workload.hidden_size, self.workload.seq_len)
        # Goodput step-slice anchors: wall time between consecutive
        # run_step completions is one step's slice; compile/data-stall
        # deltas within a slice are already booked to their own
        # categories, so recompute attribution subtracts them.
        # Construction minus the restore share is setup: state init and
        # trace-time work a restart pays even warm. Booking it keeps the
        # useful residual to actual step-loop time.
        self.goodput.add("setup_s",
                         (time.perf_counter() - self._construct_t0)
                         - self.goodput.get("restore_s"))
        self._g_prev_t = time.perf_counter()
        self._g_prev_wall = time.time()
        self._g_prev_stall = self._stall_sum()
        self._g_prev_compile = self.goodput.get("compile_s")

    def _wrap_prefetch(self, data: Iterator) -> Iterator[DeviceBatch]:
        return prefetch_to_device(
            data, put=self._prepare, depth=self.prefetch_depth,
            length_of=self.get_batch_length, stats=self.stalls)

    def set_data(self, data: Iterator, *, eval_data: Optional[Iterator] = None,
                 eval_batches_consumed: Optional[int] = None,
                 samples_consumed: Optional[int] = None) -> None:
        """Late data wiring: iterators created AFTER construction, so their
        resume fast-forward can use the step this loop ACTUALLY restored —
        which may be older than the newest checkpoint when the restore
        walked back past a corrupt one (run/train.py builds the loop
        first, reads ``loop.step``, then skips exactly that many batches).
        Applies the same prefetch wrapping the constructor would.

        ``samples_consumed`` re-seeds the cumulative ``samples`` gauge:
        on an ELASTIC resume (global batch changed with the topology) the
        constructor's ``step * global_batch`` estimate uses the NEW
        global batch and would mis-state history — the checkpoint's meta
        sidecar records the true count."""
        self.data = (self._wrap_prefetch(data)
                     if self.prefetch_depth > 0 and data is not None
                     else data)
        if eval_data is not None:
            self.eval_data = eval_data
        if eval_batches_consumed is not None:
            self.eval_batches_consumed = eval_batches_consumed
        if samples_consumed is not None:
            self._samples = int(samples_consumed)

    def _stall_sum(self) -> float:
        s = self.stalls.sums()
        return s["data_wait_s"] + s["h2d_wait_s"]

    # ------------------------------------------------------------ state setup

    def _make_optimizer(self) -> optax.GradientTransformation:
        """AdamW with the reference's linear anneal ``lr*(1-step/total)``
        (trainer.py:257-263) and decoupled weight decay (trainer.py:99)."""
        # Constant-LR runs keep the plain float (not a schedule callable):
        # a callable changes the opt_state pytree structure
        # (ScaleByScheduleState vs empty ScaleState), which would break
        # optimizer-state restore of checkpoints saved before a schedule
        # was in play.
        sched = (self._lr_at if self.learning_steps > 0
                 or self.warmup_steps > 0 else self.lr)
        return optax.adamw(sched, b1=0.9, b2=0.999, eps=1e-8,
                           weight_decay=self.weight_decay)

    def _lr_at(self, step):
        """Reference linear anneal ``lr*(1-step/total)`` (trainer.py:257-263),
        optionally preceded by a linear warmup from 0 over ``warmup_steps``
        (exceeds the reference; default 0 keeps its exact schedule). One
        method is BOTH the optax schedule and the logged lr gauge."""
        lr = jnp.asarray(self.lr, jnp.float32)
        if self.learning_steps > 0:
            lr = lr * jnp.maximum(0.0, 1.0 - step / self.learning_steps)
        if self.warmup_steps > 0:
            lr = lr * jnp.minimum(1.0, (step + 1) / self.warmup_steps)
        return lr

    def _build_state(self, resume_checkpoint: str) -> None:
        wl = self.workload
        init_rng = jax.random.fold_in(self._base_rng, 0)
        abstract = jax.eval_shape(wl.init_params, init_rng)
        abstract_unboxed = nn.meta.unbox(abstract)
        # Param shardings from the declared rule table (the partition
        # engine); --partition_rules overrides, and workloads without a
        # table (custom families) fall back to the flax logical-metadata
        # compat path. The tables are equivalence-tested against that
        # path, so flipping engines never changes a layout.
        rules = (self.partition_rules
                 if self.partition_rules is not None
                 else partition_lib.rules_for_workload(wl))
        if rules is not None:
            specs = partition_lib.match_partition_rules(rules,
                                                        abstract_unboxed)
            pshard = partition_lib.resolve_shardings(self.mesh, specs,
                                                     abstract_unboxed)
        else:
            pshard = param_shardings(self.mesh, abstract)
        self._pshard = pshard
        self.opt = self._make_optimizer()

        # ZeRO-1 (--shard_optimizer): weight-update state — Adam moments
        # AND the EMA copies — lives sharded across the data axis on top
        # of whatever fsdp/tensor sharding the params already have. The
        # step only touches that state elementwise, so GSPMD gathers on
        # use (all-gather of the per-step updates, not the stored state)
        # and per-replica weight-update bytes drop by ~dp. With dp == 1
        # (or the flag off) the ZeRO layout degenerates to the param
        # layout and nothing changes.
        zshard = (partition_lib.zero1_shardings(self.mesh, pshard,
                                                abstract_unboxed)
                  if self.shard_optimizer else pshard)
        self._zshard = zshard

        # Optimizer-state shardings: params-shaped leaves (mu/nu) take the
        # weight-update layout — the param shardings (FSDP/ZeRO-3 contract,
        # SURVEY.md §7 hard parts), plus the data axis under ZeRO-1 — and
        # scalars (count) replicate. jit does NOT propagate input shardings
        # to outputs, so this must be explicit.
        rep = replicated(self.mesh)
        abstract_opt = jax.eval_shape(self.opt.init, abstract_unboxed)
        oshard = optax.tree_map_params(
            self.opt, lambda _, s: s, abstract_opt, zshard,
            transform_non_params=lambda _: rep)
        self._oshard = oshard

        with self.mesh:
            params = jax.jit(
                lambda r: nn.meta.unbox(wl.init_params(r)),
                out_shardings=pshard)(init_rng)
            opt_state = jax.jit(self.opt.init, out_shardings=oshard)(params)
            # Fresh EMA = copy of params (reference deepcopies,
            # trainer.py:110-113). Distinct buffers, NOT aliases: the jitted
            # step donates the whole state, and donating one buffer through
            # several tree slots is an error. Under ZeRO-1 the copies land
            # directly in the data-sharded layout (one compiled copy fn,
            # reused per rate).
            if self.shard_optimizer:
                copy_to_z = jax.jit(
                    lambda p: jax.tree_util.tree_map(jnp.copy, p),
                    out_shardings=zshard)
                ema = {r: copy_to_z(params) for r in self.ema_rates}
            else:
                ema = {r: jax.tree_util.tree_map(jnp.copy, params)
                       for r in self.ema_rates}

        self.n_params = wl.param_count(params)
        self.step = 0

        # Sanitize mode guards the restore too (the cold-path half of the
        # checkpoint net): Orbax restores into the requested shardings via
        # explicit placement, so an implicit transfer here means resume
        # code regressed into a host round-trip.
        t_restore0 = time.perf_counter()
        t_restore0_wall = time.time()
        with self._sanitize_guard():
            restored = ckpt_lib.restore_resume_state(
                self.checkpoint_dir,
                abstract_params=_abstract_like(params),
                ema_rates=self.ema_rates,
                abstract_opt=_abstract_like(opt_state),
                # EMA restore target: under ZeRO-1 the EMA layout differs
                # from the params layout (data-sharded), and a degraded
                # (missing/corrupt) companion must land in it too — the
                # AOT step's pinned shardings reject a params-layout EMA
                # at the second step.
                abstract_ema=(_abstract_like(next(iter(ema.values())))
                              if ema else None),
                explicit_model_path=resume_checkpoint,
            )
        self.resumed_from = ""
        if restored is not None:
            self.step = restored["step"]
            self.resumed_from = restored.get("path", "")
            # One-time defensive copy: the jitted train step DONATES the
            # whole TrainState, and donating orbax-restored buffers directly
            # is unsafe when the executable came from the persistent
            # compilation cache (jaxlib 0.4.37 CPU: reproducible heap
            # corruption — "malloc(): smallbin double linked list
            # corrupted" — in the resume-with-warm-cache path). Copying
            # hands the step exclusively-owned buffers; sharding is
            # preserved (restore targeted the live shardings). Peak memory
            # stays at the pre-copy ~2x state: the fresh-init tree is
            # dropped BEFORE each copy and the restored source is popped so
            # it frees as soon as its copy materializes.
            own = lambda t: jax.tree_util.tree_map(jnp.copy, t)
            del params
            params = own(restored.pop("params"))
            if restored["ema"]:
                del ema
                ema = own(restored.pop("ema"))
            if restored["opt_state"] is not None:
                del opt_state
                opt_state = own(restored.pop("opt_state"))
            logger.info(f"resumed from step {self.step} "
                        f"({self.resumed_from or self.checkpoint_dir})")
        # Restore cost (discovery + orbax reads + walk-back + ownership
        # copies) is goodput overhead — the number a warm resume should
        # shrink, and the per-attempt "resume overhead" attempts.jsonl
        # records. The trace span books the SAME seconds.
        restore_dt = time.perf_counter() - t_restore0
        self.goodput.add("restore_s", restore_dt)
        if self.tracer.enabled:
            self.tracer.complete("restore", "ckpt", t_restore0_wall,
                                 restore_dt,
                                 args={"step": self.step,
                                       "resumed": restored is not None})
        self._resume_step = self.step

        self.state = TrainState(
            step=jax.device_put(jnp.asarray(self.step, jnp.int32),
                                replicated(self.mesh)),
            params=params, opt_state=opt_state, ema=ema)

    # ------------------------------------------------------------- step fns

    def _build_step_fns(self) -> None:
        wl = self.workload
        clip = self.gradient_clipping
        opt = self.opt
        rates = self.ema_rates
        # rate strings -> floats OUTSIDE the traced step (graftlint GL002:
        # float() under trace is indistinguishable from a device sync)
        rate_of = {r: float(r) for r in rates}
        pshard = self._pshard
        base_rng = self._base_rng
        lr_at = self._lr_at

        def micro_scan(params: Any, batch: Dict[str, jnp.ndarray],
                       rng: jax.Array, with_grad: bool):
            """lax.scan over the [n_micro, ...] leading axis, accumulating
            loss metrics (and grads) — the reference's inner microbatch loop
            + DDP no_sync trick (trainer.py:230-235) with the single psum
            emitted by XLA at the end.

            Deliberate deviation from the reference: microbatch grads are
            AVERAGED (scale 1/n_micro), where the reference sums unscaled
            ``loss.backward()`` calls — so the effective gradient here is
            independent of the accumulation factor and the baseline lr must
            NOT be rescaled when comparing loss curves with microbatching
            (codified by test_grad_accumulation_equivalence)."""
            def loss_fn(p, mb, r):
                d = wl.compute_losses(p, mb, r)
                return d["loss"], d

            def one(mb, i):
                r = jax.random.fold_in(rng, i)
                if with_grad:
                    (_, d), g = jax.value_and_grad(
                        loss_fn, has_aux=True)(params, mb, r)
                    return g, d
                _, d = loss_fn(params, mb, r)
                return (), d

            def body(carry, xs):
                mb, i = xs
                g, d = one(mb, i)
                g_acc, m_acc = carry
                if with_grad:
                    g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                m_acc = jax.tree_util.tree_map(jnp.add, m_acc, d)
                return (g_acc, m_acc), None

            n_micro = jax.tree_util.tree_leaves(batch)[0].shape[0]
            # First microbatch runs outside the scan: its outputs give the
            # carry its structure (no abstract tracing tricks needed).
            g0, m0 = one(jax.tree_util.tree_map(lambda x: x[0], batch),
                         jnp.asarray(0, jnp.int32))
            if n_micro > 1:
                rest = jax.tree_util.tree_map(lambda x: x[1:], batch)
                (g, m), _ = jax.lax.scan(
                    body, (g0, m0), (rest, jnp.arange(1, n_micro)))
            else:
                g, m = g0, m0
            scale = 1.0 / n_micro
            m = jax.tree_util.tree_map(lambda x: x * scale, m)
            if with_grad:
                g = jax.tree_util.tree_map(lambda x: x * scale, g)
            return g, m

        def train_step(state: TrainState, batch: Dict[str, jnp.ndarray]):
            rng = jax.random.fold_in(base_rng, state.step)
            grads, metrics = micro_scan(state.params, batch, rng,
                                        with_grad=True)
            gnorm = optax.global_norm(grads)
            if clip > 0:  # reference grad_clip, trainer.py:246-255
                scale = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            if self.fused_update:
                # single-pass kernel (ops/fused_update.py): same opt_state
                # structure, bit-identical losses — the optax chain below
                # is the reference twin
                lr_fn = (self._lr_at
                         if self.learning_steps > 0 or self.warmup_steps > 0
                         else lambda _c: jnp.asarray(self.lr, jnp.float32))
                params, opt_state, ema = fused_adamw_ema(
                    state.params, grads, state.opt_state, state.ema,
                    lr_fn=lr_fn, weight_decay=self.weight_decay)
                params = jax.lax.with_sharding_constraint(params, pshard)
            else:
                updates, opt_state = opt.update(grads, state.opt_state,
                                                state.params)
                params = optax.apply_updates(state.params, updates)
                params = jax.lax.with_sharding_constraint(params, pshard)
                ema = {r: update_ema(state.ema[r], params, rate_of[r])
                       for r in rates}
            metrics = dict(metrics)
            metrics["grad_norm"] = gnorm          # device scalar — no sync
            metrics["lr"] = lr_at(state.step)
            new_state = TrainState(step=state.step + 1, params=params,
                                   opt_state=opt_state, ema=ema)
            return new_state, metrics

        def eval_step(params: Any, batch: Dict[str, jnp.ndarray],
                      rng: jax.Array):
            _, metrics = micro_scan(params, batch, rng, with_grad=False)
            return metrics

        # Explicit AOT lower()/compile() instead of dispatch-time jit: the
        # first run_step/forward_only triggers a TIMED compile, surfaced as
        # the compile_time_s / time_to_first_step_s metrics (perf.AOTStep).
        # With the persistent compilation cache enabled (run/train.py,
        # bench.py) a warm restart's compile_time_s collapses to the cache
        # lookup, and the split makes that visible instead of folding it
        # into the first step's wall time.
        #
        # out_shardings pins the output state to the INPUT state's layout
        # (params/mu/nu/EMA shard alike — the FSDP contract from
        # _build_state). Without it GSPMD may emit outputs with drifted
        # specs (e.g. a small bias's mu replicated instead of fsdp-sharded),
        # and the AOT executable — unlike dispatch jit, which would silently
        # recompile a second variant for step 2's new input shardings —
        # rejects the mismatch. Pinning gives step-stable shardings AND
        # kills that hidden double compile. Metrics are scalars: replicated.
        rep = replicated(self.mesh)
        state_shard = TrainState(step=rep, params=pshard,
                                 opt_state=self._oshard,
                                 ema={r: self._zshard for r in rates})
        self._train_step = AOTStep(
            jax.jit(train_step, donate_argnums=(0,),
                    out_shardings=(state_shard, rep)), "train_step",
            on_compile=self._note_compile)
        self._eval_step = AOTStep(jax.jit(eval_step, out_shardings=rep),
                                  "eval_step",
                                  on_compile=self._note_compile)
        # Sequence-parallel meshes shard the batch's L axis too, so each chip
        # only ever holds its L/n activation slice (ring attention does the
        # cross-shard interaction).
        self._batch_sharding = batch_shardings(
            self.mesh, microbatched=True,
            seq_sharded=self.mesh.shape["sequence"] > 1)

    def _note_compile(self, name: str, seconds: float) -> None:
        """AOTStep callback: accumulate and log compile time (summed across
        step functions and recompiles within a log window)."""
        self.compile_time_s = (self.compile_time_s or 0.0) + seconds
        self.goodput.add("compile_s", seconds)
        if self.tracer.enabled:
            # the span re-books the exact seconds the ledger got; the
            # wall anchor back-dates it so the timeline shows WHEN
            self.tracer.complete("compile", "compile",
                                 time.time() - seconds, seconds,
                                 args={"fn": name})
        logger.logkv_sum("compile_time_s", round(seconds, 3))
        logger.info(f"compiled {name} in {seconds:.2f}s")

    @property
    def recompile_count(self) -> int:
        """XLA compiles observed since construction (sanitize mode only;
        0 when the monitor is off). Steady state should freeze this."""
        return self._recompiles.count

    def stop_sanitizer(self) -> int:
        """Detach the sanitizer's process-global hooks (the 'jax' logging
        handler and the jax_log_compiles flag) and return the final
        recompile count. Idempotent; a no-op when sanitize was off. Call
        when the loop is done in a process that keeps running (bench legs,
        tests) — nothing re-arms it. Also the moment the evidence sidecar
        is finalized: steady-state recompiles become violations, and the
        report (possibly empty — that's the 'ran clean' evidence) lands
        beside the checkpoints."""
        self._recompiles.uninstall()
        if self.sanitize and not self._sanitizer_reported:
            self._sanitizer_reported = True
            if self._recompiles_at_first_step is not None:
                self.sanitize_report.note_recompiles(
                    self._recompiles, self._recompiles_at_first_step)
            self.sanitize_report.write(self.checkpoint_dir)
        return self._recompiles.count

    def _sanitize_guard(self):
        return (self.sanitize_report.guard() if self.sanitize
                else contextlib.nullcontext())

    # ------------------------------------------------------------- data prep

    def _prepare(self, batch: Dict[str, np.ndarray]) -> Dict[str, jax.Array]:
        """Host batch [B, ...] -> global sharded [n_micro, B_micro_global, ...]."""
        if self.padding is not None and "pad_mask" in batch:
            # active-token accounting off the mask the data path already
            # carries — the padding_waste_frac side of the cost ledger.
            # np.sum on the host batch; thread-safe (the prefetch wrapper
            # calls _prepare from its own thread).
            pm = batch["pad_mask"]
            self.padding.add(int(pm.sum()), int(pm.size))
        mb = self.microbatch
        reshaped = {k: v.reshape((self.n_micro, mb) + v.shape[1:])
                    for k, v in batch.items()}
        return shard_batch(self.mesh, reshaped,
                           sharding=self._batch_sharding, batch_axis=1)

    # ------------------------------------------------------------- the loop

    def get_batch_length(self, batch: Dict[str, np.ndarray]) -> int:
        """Number of examples in a host batch — the reference's user hook
        (trainer.py:33-43) for custom batch structures; the default reads
        the first leaf's leading dim. Feeds the cumulative ``samples``
        gauge, so subclasses with exotic batches (nested, ragged-marker,
        dict-of-dicts) override ONE method instead of the loop."""
        return int(len(jax.tree_util.tree_leaves(batch)[0]))

    def next_batch(self) -> Union[Dict[str, np.ndarray], DeviceBatch]:
        """Pull the next training batch, attributing host-iterator wait to
        the ``data_wait_s`` stall gauge. With device prefetch on, the
        wrapper attributes its own waits internally (this call returns a
        buffered :class:`DeviceBatch` without double counting)."""
        if self.chaos is not None:
            # An injected iterator stall is exactly the failure the
            # data_wait gauge measures — attribute it there so the stall
            # lands in the goodput breakdown as input-pipeline time.
            stalled = self.chaos.on_data(self)
            if stalled:
                self.stalls.add("data_wait_s", stalled)
        if self.prefetch_depth > 0:
            return next(self.data)
        t0 = time.perf_counter()
        batch = next(self.data)
        self.stalls.add("data_wait_s", time.perf_counter() - t0)
        return batch

    def run_step(self, batch: Union[Dict[str, np.ndarray], DeviceBatch]
                 ) -> Dict[str, Any]:
        """One optimizer step (reference run_step, trainer.py:198-201).

        Accepts either a host batch (prepared + transferred here, the
        eager path) or a :class:`DeviceBatch` from the prefetch wrapper
        (already on the mesh — dispatch is all that's left). With
        ``dispatch_lag > 0`` the returned metrics are the CURRENT step's
        device scalars, but logging them is deferred: step N-k's metrics
        are fetched/logged while step N runs, so the host never blocks on
        the step it just enqueued (flush_metrics drains the tail)."""
        if self.chaos is not None:
            # Kill/corrupt faults scheduled for the step about to run —
            # self.step is the count of COMPLETED steps, so a fault at
            # step k fires after k steps finished, before step k+1.
            self.chaos.on_step(self)
        first = self.time_to_first_step_s is None
        if isinstance(batch, DeviceBatch):
            prepared = batch.arrays
            n_items = batch.n_items
        else:
            t0 = time.perf_counter()
            prepared = self._prepare(batch)
            self.stalls.add("h2d_wait_s", time.perf_counter() - t0)
            n_items = self.get_batch_length(batch)
        t0 = time.perf_counter()
        with self.mesh, self._sanitize_guard():
            self.state, metrics = self._train_step(self.state, prepared)
        dispatched = time.perf_counter()
        self.stalls.add("dispatch_s", dispatched - t0)
        if first:
            # Block once so "time to first step" means a COMPLETED step
            # (async dispatch would otherwise stop the clock at enqueue).
            jax.block_until_ready(metrics["loss"])
            self.time_to_first_step_s = (time.perf_counter()
                                         - self._construct_t0)
            logger.logkv("time_to_first_step_s",
                         round(self.time_to_first_step_s, 3))
            # Steady-state recompile baseline: compiles after this point
            # are silent retraces — the gauge that must stay frozen on a
            # warm-cache resume (the chaos bench acceptance).
            self._recompiles_at_first_step = self._recompiles.count
            # Ledger rate anchor: tokens/s and per-step stall means
            # measured from here on cover only steady steps (the first
            # step's dispatch_s carries the whole AOT compile, which
            # would swamp a mean taken from step 0).
            self._ledger_watch = trace_lib.Stopwatch()
            self._ledger_step0 = self.step + 1
            self._ledger_stall0 = self.stalls.sums()
        self.step += 1
        self._samples += n_items * jax.process_count()
        self._timer.tick()
        # Goodput step-slice attribution: the wall span since the previous
        # run_step completed is this step's slice. For steps an earlier
        # attempt already reached (<= recompute_until_step), the slice —
        # minus whatever compile/data-stall time inside it was already
        # booked to its own category — is recompute: real work, but work
        # the run has paid for once before.
        now = time.perf_counter()
        if self.step <= self.recompute_until_step:
            booked = ((self.goodput.get("compile_s") - self._g_prev_compile)
                      + (self._stall_sum() - self._g_prev_stall))
            self.goodput.add(
                "recompute_s", max(0.0, (now - self._g_prev_t) - booked))
        if self.tracer.enabled:
            # the step span IS the goodput step-slice (previous run_step
            # completion -> this one): same boundary, same seconds, so
            # summing trace step spans reproduces the ledger's step time
            self.tracer.complete(
                "step", "train", self._g_prev_wall, now - self._g_prev_t,
                args={"step": self.step,
                      "recompute": self.step <= self.recompute_until_step})
            self._g_prev_wall = time.time()
        self._g_prev_t = now
        self._g_prev_stall = self._stall_sum()
        self._g_prev_compile = self.goodput.get("compile_s")
        if self.progress_file:
            self._write_beacon()
        if self.dispatch_lag > 0:
            self._inflight.append((self.step, dispatched, metrics))
            while len(self._inflight) > self.dispatch_lag:
                self._emit_lagged()
        else:
            logger.logkvs_mean(metrics)
        self.log_step()
        return metrics

    def _emit_lagged(self) -> None:
        """Fetch/log the OLDEST in-flight step's metrics. Blocking here —
        k steps after dispatch — is where ``device_step_s`` is observed:
        the span from that step's dispatch returning to its outputs
        materializing (device execution + queue wait, a trailing upper
        bound). The values logged are exactly the step's device scalars,
        just late."""
        step_idx, dispatched, metrics = self._inflight.popleft()
        jax.block_until_ready(metrics["loss"])
        self.stalls.add("device_step_s", time.perf_counter() - dispatched)
        logger.logkvs_mean(metrics)

    def flush_metrics(self) -> None:
        """Drain every in-flight lagged metric (logged values become
        complete up to the current step). Called before eval, before each
        checkpoint save, and at loop exit, so anything that reads the
        logs at those boundaries sees exact, fully-caught-up values."""
        while self._inflight:
            self._emit_lagged()

    def forward_only(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """Eval pass without grads (reference forward_only trainer.py:223-228);
        metrics are logged under an ``eval_`` prefix."""
        # fold_in data must be uint32; offset eval streams away from the
        # train stream (which folds in the raw step). Replicate the key
        # onto the mesh explicitly: a single-device key gets resharded
        # implicitly at dispatch, which the sanitize guard (rightly) trips
        # on when the eval step actually consumes it (diffuseq).
        rng = jax.device_put(
            jax.random.fold_in(self._base_rng, 0x7FFF0000 + self.step),
            replicated(self.mesh))
        t_eval0_wall = time.time()
        watch = trace_lib.Stopwatch() if self.tracer.enabled else None
        prepared = self._prepare(batch)
        with self.mesh, self._sanitize_guard():
            metrics = self._eval_step(self.state.params, prepared, rng)
        if watch is not None:
            # dispatch span only (blocking on the eval output here would
            # add the per-eval sync async dispatch exists to avoid)
            self.tracer.complete("eval", "eval", t_eval0_wall,
                                 watch.lap_s(), args={"step": self.step})
        logger.logkvs_mean({f"eval_{k}": v for k, v in metrics.items()})
        return metrics

    def log_step(self) -> None:
        """step + cumulative samples (reference log_step trainer.py:273-275);
        samples accumulate through the get_batch_length hook (equals
        ``step * global_batch`` unless a subclass overrides it)."""
        logger.logkv("step", self.step)
        logger.logkv("samples", self._samples)
        if self.sanitize:
            logger.logkv("recompile_count", self.recompile_count)

    # ------------------------------------------------------ goodput/beacon

    @property
    def steady_recompile_count(self) -> int:
        """XLA compiles observed AFTER the first completed step (sanitize
        mode): the warm-path gauge — a resumed attempt under a warm
        persistent cache must hold this at 0 even though its construction
        legitimately compiled (restore copies are new programs on a first
        resume)."""
        if self._recompiles_at_first_step is None:
            return 0
        return self._recompiles.count - self._recompiles_at_first_step

    def goodput_summary(self) -> Dict[str, float]:
        """Point-in-time goodput decomposition for this attempt: wall
        (spawn→now when the launcher stamped DPT_SPAWN_T, else
        construction→now) split into useful / startup / restore / compile
        / save / data-stall / recompute."""
        return self.goodput.summary(extra={"data_stall_s": self._stall_sum()})

    def _write_beacon(self) -> None:
        """Atomically replace the per-step progress beacon: step, wall
        clock, and the goodput snapshot. A killed attempt's last beacon is
        its flight recorder (the launcher snapshots it into
        attempts.jsonl); the step field doubles as the launcher's
        crash-loop progress probe and the next attempt's
        recompute-boundary."""
        payload = {
            "step": self.step,
            # the step THIS attempt restored from: progress must be judged
            # against it, not the run's high-water mark — an attempt that
            # walked back past a corrupt checkpoint makes real progress
            # below the old maximum
            "start_step": self._resume_step,
            "t": time.time(),
            "attempt": int(os.environ.get("DPT_ATTEMPT") or 0),
            "rank": jax.process_index(),
            "recompile_count": self.recompile_count,
            "steady_recompile_count": self.steady_recompile_count,
            "goodput": {k: round(v, 6)
                        for k, v in self.goodput_summary().items()},
        }
        tmp = self.progress_file + ".tmp"
        try:
            import json as _json
            with open(tmp, "w") as f:
                f.write(_json.dumps(payload))
            os.replace(tmp, self.progress_file)
        except OSError as e:  # beacon is telemetry: never fail a step
            logger.warn(f"progress beacon write failed: {e}")

    def _write_goodput_record(self) -> None:
        """Rank 0, at loop exit: the attempt's final goodput record
        (``goodput_attempt{A:03d}.json`` next to the checkpoints). The
        clean-exit counterpart of the beacon — aggregate_run prefers it."""
        if not self.checkpoint_dir or jax.process_index() != 0:
            return
        attempt = int(os.environ.get("DPT_ATTEMPT") or 0)
        payload = {
            "attempt": attempt,
            "steps": [self._resume_step, self.step],
            "recompile_count": self.recompile_count,
            "steady_recompile_count": self.steady_recompile_count,
            "compile_time_s": self.compile_time_s or 0.0,
            **{k: round(v, 6) for k, v in self.goodput_summary().items()},
        }
        try:
            import json as _json
            path = os.path.join(self.checkpoint_dir,
                                f"goodput_attempt{attempt:03d}.json")
            with open(path, "w") as f:
                f.write(_json.dumps(payload))
        except OSError as e:
            logger.warn(f"goodput record write failed: {e}")

    def footprint(self) -> Dict[str, int]:
        """HBM/params footprint gauges (ISSUE 9): logical state bytes plus
        the per-replica (one device's addressable shard) bytes — the
        number ZeRO-1 exists to shrink — and the backend's peak live
        allocation (0 where the backend doesn't report memory stats, e.g.
        CPU). Logged every log window and carried on bench train rows."""
        s = self.state
        return {
            "params_bytes": tree_bytes(s.params),
            "params_bytes_per_replica": tree_bytes_per_replica(s.params),
            "opt_state_bytes": tree_bytes(s.opt_state),
            "opt_state_bytes_per_replica":
                tree_bytes_per_replica(s.opt_state),
            "ema_bytes_per_replica": tree_bytes_per_replica(s.ema),
            "peak_live_bytes": peak_live_bytes(),
        }

    # ------------------------------------------------------- cost ledger

    def ledger_rows(self) -> Dict[str, Dict[str, Any]]:
        """Per-compiled-program cost-ledger rows (obs/ledger.py): XLA's
        own FLOPs/bytes accounting + the HLO collective tally off the
        AOT executables this loop already holds, folded with the
        analytic ``flops_per_token``, the measured steady tokens/s, and
        the stall gauges into the roofline MFU-gap attribution. The
        train row reuses the EXACT stall/goodput seconds the ledger
        elsewhere reports (``data_stall_s_total`` is the same expression
        ``goodput_summary`` folds), so the two can never disagree."""
        rows: Dict[str, Dict[str, Any]] = {}
        tokens_per_step = self.global_batch * self.workload.seq_len
        steps_per_s = 0.0
        n_steady = 0
        if self._ledger_watch is not None:
            n_steady = self.step - self._ledger_step0
            dt = self._ledger_watch.peek_s()
            if n_steady > 0 and dt > 0:
                steps_per_s = n_steady / dt
        # steady-window per-step stall means (sums since the first-step
        # anchor / steady steps): the cumulative means would fold the
        # first step's compile-bearing dispatch into every attribution
        sums = self.stalls.sums()
        steady = {g: (max(0.0, s - self._ledger_stall0.get(g, 0.0))
                      / n_steady if n_steady > 0 else 0.0)
                  for g, s in sums.items()}
        host_stall = (steady["data_wait_s"] + steady["h2d_wait_s"]
                      + steady["dispatch_s"])
        device_kind = getattr(jax.devices()[0], "device_kind", "cpu")
        for name, aot in (("train_step", self._train_step),
                          ("eval_step", self._eval_step)):
            if aot.compiled is None:
                continue
            cached = self._ledger_cost_cache.get(name)
            if cached is None or cached[0] is not aot.compiled:
                cached = (aot.compiled,
                          ledger_lib.extract_cost(aot.compiled))
                self._ledger_cost_cache[name] = cached
            row: Dict[str, Any] = {"program": name, **cached[1]}
            if name == "train_step":
                row.update({
                    "tokens_per_step": tokens_per_step,
                    "flops_per_token": self._flops_per_token,
                    "analytic_flops_per_step":
                        self._flops_per_token * tokens_per_step,
                    "steps_per_s": steps_per_s,
                    "tokens_per_s": steps_per_s * tokens_per_step,
                    "device_step_s": steady["device_step_s"],
                    "host_stall_s_per_step": host_stall,
                    # goodput-identity fields: the SAME cumulative sums
                    # the goodput summary folds as data_stall_s
                    "data_stall_s_total": self._stall_sum(),
                })
                row.update(ledger_lib.roofline_attribution(
                    tokens_per_s=row["tokens_per_s"],
                    flops_per_token=self._flops_per_token,
                    peak_flops=device_peak_flops(),
                    n_devices=jax.device_count(),
                    steps_per_s=steps_per_s,
                    collective_bytes_per_step=row.get(
                        "collective_bytes_per_step", 0.0),
                    bytes_accessed=row.get("bytes_accessed", 0.0),
                    host_stall_s_per_step=host_stall,
                    device_kind=device_kind,
                    padding_waste_frac=(self.padding.frac
                                        if self.padding is not None
                                        else 0.0)))
            rows[name] = row
        return rows

    def _write_ledger_snapshot(self,
                               rows: Dict[str, Dict[str, Any]]) -> None:
        if not rows or not self.checkpoint_dir \
                or "://" in self.checkpoint_dir:
            return
        ledger_lib.write_ledger(
            self.checkpoint_dir, rows, t=time.time(),
            extra={"step": self.step,
                   "n_devices": jax.device_count(),
                   "device_kind": getattr(jax.devices()[0],
                                          "device_kind", "cpu")})

    def _log_throughput(self) -> None:
        sps, tps = self._timer.lap()
        if tps > 0:
            logger.logkv("steps_per_sec", round(sps, 4))
            logger.logkv("tokens_per_sec", round(tps, 1))
            logger.logkv("tokens_per_sec_per_chip",
                         round(tps / jax.device_count(), 1))
            logger.logkv("mfu", round(mfu(tps, self._flops_per_token), 4))
        # Stall breakdown: mean seconds/step over the window for each of
        # data_wait/h2d_wait/dispatch/device_step — "is the input pipeline
        # the bottleneck" as a number in every sink.
        for gauge, mean_s in self.stalls.lap().items():
            logger.logkv(gauge, round(mean_s, 6))
        # Cumulative goodput ratio (useful-step share of the attempt's
        # wall so far) rides the same cadence: a run bleeding time to
        # restarts/stalls shows it here long before the bench does.
        logger.logkv("goodput", round(self.goodput_summary()["goodput"], 4))
        # Memory footprint: params/opt-state bytes (per-replica is the
        # ZeRO-1 acceptance gauge) + backend peak live bytes.
        for gauge, b in self.footprint().items():
            logger.logkv(gauge, b)
        # Cost ledger (--cost_ledger): the train step's roofline MFU-gap
        # decomposition rides the same cadence, and the run-dir
        # perf_ledger.json snapshot refreshes (atomic replace) so
        # status/export read a live attribution, not only a post-mortem.
        if self.cost_ledger:
            rows = self.ledger_rows()
            tr = rows.get("train_step")
            if tr:
                for gauge in ledger_lib.GAP_TERMS:
                    logger.logkv(gauge, round(tr[gauge], 4))
                logger.logkv("collective_bytes_per_step",
                             tr["collective_bytes_per_step"])
                logger.logkv("padding_waste_frac",
                             round(tr["padding_waste_frac"], 4))
            self._write_ledger_snapshot(rows)

    def _maybe_profile(self, loop_step: int) -> None:
        """Start/stop the jax.profiler trace window (steps counted from loop
        entry so resumed runs still capture a post-compilation window)."""
        start, stop = self._profile_window
        if loop_step == start and not self._profiling:
            jax.profiler.start_trace(self.profile_dir)
            self._profiling = True
            logger.info(f"profiler: tracing steps {start}..{stop} "
                        f"-> {self.profile_dir}")
        elif loop_step == stop and self._profiling:
            jax.block_until_ready(self.state.params)
            jax.profiler.stop_trace()
            self._profiling = False

    def run_loop(self) -> None:
        """Interval-driven outer loop (reference run_loop trainer.py:175-196):
        log every ``log_interval``, eval every ``eval_interval``, save every
        ``save_interval``, final save on exit. An interval <= 0 disables
        that periodic action (the reference's modulo would die on 0); the
        final save still runs with periodic saves disabled, so every run
        leaves a restorable checkpoint."""
        loop_step = 0
        try:
            while self.learning_steps <= 0 or self.step < self.learning_steps:
                if self.profile_dir:
                    self._maybe_profile(loop_step)
                batch = self.next_batch()
                self.run_step(batch)
                loop_step += 1
                if self.log_interval > 0 and self.step % self.log_interval == 0:
                    self._log_throughput()
                    logger.dumpkvs()
                if (self.eval_data is not None and self.eval_interval > 0
                        and self.step % self.eval_interval == 0):
                    # Lagged metrics are flushed at eval boundaries so the
                    # eval-step dump lines up with fully-logged train steps.
                    self.flush_metrics()
                    self.forward_only(next(self.eval_data))
                    self.eval_batches_consumed += 1
                    # Reference runs callbacks on rank 0 only
                    # (trainer.py:189-191) because torch callbacks are
                    # host-local. Here they may jit over globally-sharded
                    # params (e.g. the decode callback), and in
                    # multi-controller JAX every process must join such a
                    # computation — so ALL processes run the callbacks and
                    # output stays rank-gated in the logger sinks.
                    # Sanitize mode extends the transfer guard over the
                    # callbacks: with async dispatch on, an implicit
                    # transfer inside a callback is exactly the kind of
                    # accidental per-eval sync the guard exists to catch.
                    with self._sanitize_guard():
                        for cb in self.eval_callbacks:
                            cb(self)
                if (self.save_interval > 0
                        and self.step % self.save_interval == 0):
                    self.save(wait=False)  # write overlaps training
        finally:
            if self._profiling:  # run ended (or raised) inside the window:
                jax.profiler.stop_trace()  # flush the trace either way
                self._profiling = False
            try:
                # final flush: the last dispatch_lag steps' metrics are
                # still in flight — without this they would never reach
                # the sinks
                self.flush_metrics()
            finally:
                # exception path too — including a flush that re-raises
                # the poisoned in-flight step it blocks on: drain the
                # in-flight save before unwinding — a process exiting
                # mid-commit can hang the other hosts in orbax's
                # finalization barrier
                self.wait_for_saves()
        if self.save_interval <= 0 or self.step % self.save_interval != 0:
            self.save(wait=False)
        self.wait_for_saves()  # exit barrier: the last write must be durable
        self._prune()  # final retention pass over the finalized set
        # The attempt's goodput decomposition, durable next to the
        # checkpoints (and in the logs): the clean-exit record
        # aggregate_run folds with the launcher's attempts.jsonl.
        summary = self.goodput_summary()
        logger.logkvs({f"goodput_{k}" if k != "goodput" else k:
                       round(v, 4) for k, v in summary.items()})
        self._write_goodput_record()
        if self.cost_ledger:
            # final ledger snapshot: the attribution the run ends on
            self._write_ledger_snapshot(self.ledger_rows())
        self.tracer.close()
        if self.sanitize:
            # clean exit finalizes the evidence sidecar (trips already
            # auto-wrote on the way down in the exception path)
            self.stop_sanitizer()

    __call__ = run_loop  # reference trainer.py:357

    # ------------------------------------------------------------ checkpoint

    def save(self, wait: bool = True) -> None:
        """model_/ema_{rate}_/opt_{step:06d} under the run dir (reference
        save(), trainer.py:277-302). ``wait=False`` (what the step loop
        passes) schedules the write ASYNC so it overlaps the next
        ``save_interval`` of training; the barrier then runs before the
        next save, before retention pruning, and at loop exit
        (checkpoint.AsyncSaver). Orbax fetches to host synchronously inside
        the call, so the jitted step's buffer donation stays safe. The
        default ``wait=True`` keeps direct calls durable-on-return."""
        if not self.checkpoint_dir:
            logger.warn("no checkpoint_dir configured; skipping save")
            return
        # Checkpoint boundaries are metric-exact points: drain the lagged
        # metric ring so the logs at a save reflect every step saved.
        self.flush_metrics()
        # Sanitize mode keeps the transfer guard up through the save
        # scheduling: Orbax's device->host fetch is explicit (and proven
        # guard-clean by test), so anything that trips here is an
        # accidental implicit transfer sneaking into the save path.
        t_save0 = time.perf_counter()
        t_save0_wall = time.time()
        with self._sanitize_guard():
            self._saver.save(
                self.checkpoint_dir, self.step, self.state.params,
                ema={r: self.state.ema[r] for r in self.ema_rates},
                opt_state=self.state.opt_state, wait=wait)
        save_dt = time.perf_counter() - t_save0
        self.goodput.add("save_s", save_dt)
        if self.tracer.enabled:
            self.tracer.complete("save", "ckpt", t_save0_wall, save_dt,
                                 args={"step": self.step,
                                       "async": not wait})
        if self.chaos is not None:
            # crash_in_save faults fire HERE: the async array write is in
            # flight (or, with wait=True, just finalized), so a SIGKILL
            # lands between write and finalize — the torn-checkpoint case
            # the resume path must survive.
            self.chaos.on_save(self)
        ckpt_lib.save_meta(self.checkpoint_dir, self.step, {
            "eval_batches_consumed": self.eval_batches_consumed,
            "eval_interval": self.eval_interval,
            # Elastic-resume topology facts (ISSUE 10): the GLOBAL batch
            # and cumulative sample count at save time. A resume on a
            # DIFFERENT topology (more/fewer hosts) must fast-forward the
            # data stream by global samples consumed — step count alone is
            # meaningless across a global-batch change. mesh shape rides
            # along for debugging/attribution.
            "global_batch": self.global_batch,
            "samples": self._samples,
            "mesh": {a: int(s) for a, s in self.mesh.shape.items()},
        })
        mode = ("saved checkpoint" if wait
                else "scheduled async checkpoint save")
        logger.info(f"{mode} at step {self.step} -> {self.checkpoint_dir}")
        # Retention ranks only FINALIZED checkpoints (unfinalized orbax tmp
        # dirs are excluded by prune_checkpoints), so pruning here never
        # needs to barrier on the save just scheduled: with wait=False it
        # simply lags by the one in-flight save (bounded at keep+1 dirs on
        # disk; run_loop runs a final pass at exit).
        self._prune()

    def _prune(self) -> None:
        if self.keep_checkpoints <= 0:
            return
        pruned = ckpt_lib.prune_checkpoints(self.checkpoint_dir,
                                            self.keep_checkpoints)
        if pruned:
            logger.info(f"pruned checkpoints at steps {pruned} "
                        f"(keep_checkpoints={self.keep_checkpoints})")

    def wait_for_saves(self) -> None:
        """Barrier on the in-flight async checkpoint saves, if any."""
        t0 = time.perf_counter()
        self._saver.wait()
        self.goodput.add("save_s", time.perf_counter() - t0)
