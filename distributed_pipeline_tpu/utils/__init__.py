from . import logger
