from . import logger

# trainer/checkpoint/perf are imported lazily by consumers: pulling them in
# here would make every logger-only import (e.g. the launcher) pay the full
# jax/flax/optax import cost.
