"""Checkpoint/resume with the reference's filename-is-metadata contract.

Parity target (``/root/reference/utils/trainer.py:277-355``, SURVEY.md §5.4):
per-run directory holding ``model_{step:06d}`` / ``ema_{rate}_{step:06d}`` /
``opt_{step:06d}``, resume step parsed from the trailing six digits, newest
checkpoint auto-discovered from the run/log dir, and companion files located
by convention.

TPU-native backend: Orbax (each name is an Orbax directory rather than a
``.pt`` file). That buys what blobfile+torch.save could not: multi-host-safe
single-writer semantics, sharded-array save/restore that keeps each chip's
shard on-chip (no host gather), and atomic finalization. Restore takes an
abstract target tree so arrays come back with the requested shardings.

Paths go through ``etils.epath``, so run dirs and resume paths may be remote
URIs (``gs://...``) exactly like the reference's blobfile-backed reads
(``/root/reference/basic_utils/dist_util.py:118-124``, SURVEY.md §5.4).
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
from etils import epath

from . import logger

import orbax.checkpoint as ocp

__all__ = [
    "save_checkpoint", "AsyncSaver", "restore_checkpoint",
    "restore_resume_state", "resume_target",
    "parse_step_from_name", "find_resume_checkpoint", "find_ema_checkpoint",
    "find_opt_checkpoint", "latest_step", "prune_checkpoints",
]

_STEP_RE = re.compile(r"(\d{6,})$")


def _checkpointer() -> ocp.Checkpointer:
    # StandardCheckpointer = async PyTree save with OCDBT; one per call keeps
    # this module stateless (cheap: it is a thin handle).
    return ocp.StandardCheckpointer()


def parse_step_from_name(name: str) -> Optional[int]:
    """``model_012345`` -> 12345 (reference ``parse_resume_step_from_filename``,
    trainer.py:319-327 — trailing digits are the step)."""
    m = _STEP_RE.search(name.rstrip("/"))
    return int(m.group(1)) if m else None


_ORBAX_TMP_MARKER = ".orbax-checkpoint-tmp"


def _is_unfinalized(name: str) -> bool:
    """Orbax writes into ``<name>.orbax-checkpoint-tmp-<timestamp>`` and
    renames on finalize; a crash mid-save leaves the tmp behind. Its
    trailing timestamp parses as a huge step, so treating it as a real
    checkpoint would (a) make auto-resume pick an unrestorable directory
    and (b) make retention-pruning rank it newest and delete genuine
    checkpoints instead."""
    return _ORBAX_TMP_MARKER in name


def _scan(directory: str, prefix: str) -> List[Tuple[int, str]]:
    if not directory:
        return []
    d = epath.Path(directory)
    if not d.is_dir():
        return []
    out = []
    for child in d.iterdir():
        if child.name.startswith(prefix) and not _is_unfinalized(child.name):
            step = parse_step_from_name(child.name)
            if step is not None:
                out.append((step, os.fspath(child)))
    return sorted(out)


def find_resume_checkpoint(directory: str) -> Optional[str]:
    """Newest ``model_*`` checkpoint in the run dir (reference
    ``find_resume_checkpoint`` trainer.py:329-335 scans the logger dir)."""
    found = _scan(directory, "model_")
    return found[-1][1] if found else None


def resume_target(directory: str,
                  explicit_model_path: str = "") -> Tuple[int, str]:
    """``(step, model_path)`` a run over ``directory`` will resume from —
    ``(0, "")`` when fresh. The ONE discovery rule (explicit path wins,
    else newest ``model_*``, step parsed from the name). run/train.py
    resolves this ONCE and hands the path to TrainLoop as the explicit
    resume target, so the data-stream fast-forward and the restored state
    cannot desync even if another checkpoint lands mid-setup (exact-order
    resume)."""
    path = explicit_model_path or find_resume_checkpoint(directory)
    if not path:
        return 0, ""
    return parse_step_from_name(path) or 0, path


def save_meta(directory: str, step: int, meta: dict) -> None:
    """Write the per-checkpoint metadata sidecar (``meta_{step:06d}.json``):
    run facts the filenames cannot carry — today the consumed-eval-batch
    count and the eval interval, so a resume can fast-forward the eval
    stream exactly even when ``--eval_interval`` changed (the r4 advisor's
    'a warning is not a contract'). Process 0 only; tiny synchronous
    write."""
    import json as _json

    if jax.process_index() != 0:
        return
    p = epath.Path(directory) / f"meta_{step:06d}.json"
    try:
        p.write_text(_json.dumps(meta))
    except Exception as e:  # metadata must never fail a save
        logger.warn(f"checkpoint meta write failed ({p}): {e}")


def load_meta(directory: str, step: int) -> Optional[dict]:
    """The sidecar written by :func:`save_meta`, or None (pre-r5
    checkpoints have none — callers fall back to flag-derived values)."""
    import json as _json

    p = epath.Path(directory) / f"meta_{step:06d}.json"
    try:
        if p.is_file():
            return _json.loads(p.read_text())
    except Exception as e:
        logger.warn(f"checkpoint meta read failed ({p}): {e}")
    return None


def find_ema_checkpoint(directory: str, step: int, rate: str) -> Optional[str]:
    path = epath.Path(directory) / f"ema_{rate}_{step:06d}"
    return os.fspath(path) if path.is_dir() else None


def find_opt_checkpoint(directory: str, step: int) -> Optional[str]:
    path = epath.Path(directory) / f"opt_{step:06d}"
    return os.fspath(path) if path.is_dir() else None


def latest_step(directory: str) -> int:
    found = _scan(directory, "model_")
    return found[-1][0] if found else 0


class AsyncSaver:
    """At most ONE checkpoint save in flight, overlapping training.

    Orbax's ``StandardCheckpointer.save`` is async: the device-to-host
    fetch happens synchronously inside ``save()`` (so the caller may
    freely donate/overwrite device buffers afterwards — the jitted step's
    ``donate_argnums`` is safe), while the durable write proceeds on
    background threads. The reference blocks the step loop for the whole
    write (its save + barrier, trainer.py:277-302); here the barrier
    moves to where it is actually needed: before the NEXT save, before
    retention pruning, and at exit (``wait()``). At BASELINE-5 scale
    params + 3 EMA copies + Adam state is ~5x model size — that write now
    costs the step loop only the D2H fetch."""

    def __init__(self) -> None:
        self._ckptrs: List[ocp.Checkpointer] = []

    def wait(self) -> None:
        """Block until every in-flight save is durable."""
        for c in self._ckptrs:
            c.wait_until_finished()
            c.close()
        self._ckptrs = []

    def save(self, directory: str, step: int, params: Any,
             ema: Optional[Dict[str, Any]] = None,
             opt_state: Optional[Any] = None, wait: bool = False) -> None:
        """Schedule ``model_{step:06d}`` (+ ``ema_{rate}_``/``opt_``)
        under ``directory``. Multi-host safe: every process must call this
        (Orbax coordinates the single-writer protocol). Waits for the
        PREVIOUS save first (one step's saves in flight max — the
        reference's barrier-before-next-save contract); ``wait=True`` also
        blocks until THIS save is durable (the reference's
        fully-synchronous semantics).

        One checkpointer PER TREE: orbax's ``AsyncCheckpointer.save``
        waits for that handle's previous write on entry, so scheduling
        model + EMAs + opt on a single handle would serialize them and
        only overlap the last — separate handles let all trees' writes
        proceed concurrently in the background."""
        self.wait()
        d = epath.Path(directory)
        if not d.is_absolute() and "://" not in directory:
            d = epath.Path(os.path.abspath(directory))  # orbax: absolute
        if jax.process_index() == 0:
            d.mkdir(parents=True, exist_ok=True)
        trees = [(d / f"model_{step:06d}", params)]
        trees += [(d / f"ema_{rate}_{step:06d}", tree)
                  for rate, tree in (ema or {}).items()]
        if opt_state is not None:
            trees.append((d / f"opt_{step:06d}", opt_state))
        for path, tree in trees:
            ckptr = _checkpointer()
            ckptr.save(path, tree, force=True)
            self._ckptrs.append(ckptr)
        if wait:
            self.wait()


def save_checkpoint(directory: str, step: int, params: Any,
                    ema: Optional[Dict[str, Any]] = None,
                    opt_state: Optional[Any] = None) -> None:
    """Synchronous one-shot save: all processes block until the write is
    durable (the reference's semantics, trainer.py:282). The step loop
    uses :class:`AsyncSaver` instead to overlap the write with training."""
    AsyncSaver().save(directory, step, params, ema=ema,
                      opt_state=opt_state, wait=True)


def prune_checkpoints(directory: str, keep: int) -> List[int]:
    """Delete all but the newest ``keep`` checkpoint steps (model + every
    companion ``ema_*``/``opt_`` of the pruned step). The reference keeps
    everything; at three EMA rates + optimizer state a 320k-step run
    accumulates ~5x params-size per save, so long runs need a retention
    policy. Process 0 only (single-writer, like the save protocol);
    returns the pruned step numbers. ``keep <= 0`` disables pruning."""
    if keep <= 0 or jax.process_index() != 0:
        return []
    d = epath.Path(directory)
    if not d.is_dir():
        return []
    # ONE directory listing serves both the step ranking and the deletes —
    # each listing is a remote LIST on gs:// run dirs. Unfinalized Orbax
    # tmp dirs are excluded from BOTH: they must never rank as checkpoints
    # nor be deleted (one may be a save in flight).
    children = [(child, child.name) for child in d.iterdir()
                if not _is_unfinalized(child.name)]
    steps = sorted(parse_step_from_name(n) for _, n in children
                   if n.startswith("model_")
                   and parse_step_from_name(n) is not None)
    doomed = set(steps[:-keep] if len(steps) > keep else [])
    if not doomed:
        return []
    # A step counts as pruned only when EVERY one of its dirs (model_ +
    # companions) deleted; partial failures are reported per step so the
    # log never claims a step was removed while a restorable model_
    # remains (r4 advisor).
    failed = set()
    touched = set()
    for child, name in children:
        if (name.startswith(("model_", "ema_", "opt_", "meta_"))
                and parse_step_from_name(name) in doomed):
            step = parse_step_from_name(name)
            touched.add(step)
            try:
                if name.startswith("meta_"):
                    child.unlink()
                else:
                    child.rmtree()
            # broad by design: epath's gs:// backends surface failures as
            # tf.errors.OpError / gcsfs HttpError etc., not OSError
            except Exception as e:
                # Retention is housekeeping: a delete failure (gs://
                # permissions, concurrent cleanup) must never abort the
                # training run that just saved successfully.
                failed.add(step)
                logger.warn(f"checkpoint retention: could not delete "
                            f"{child}: {e}")
    if failed:
        logger.warn(f"checkpoint retention: steps "
                    f"{sorted(failed)} only PARTIALLY deleted — their "
                    f"remaining dirs will be retried next retention pass")
    return sorted(touched - failed)


def restore_checkpoint(path: str, abstract_target: Any) -> Any:
    """Restore one tree; ``abstract_target`` (jax.eval_shape output with
    shardings attached) dictates dtypes/shardings of the result."""
    ckptr = _checkpointer()
    try:
        return ckptr.restore(path, abstract_target)
    finally:
        ckptr.close()


def restore_resume_state(directory: str, *, abstract_params: Any,
                         ema_rates: Tuple[str, ...] = (),
                         abstract_opt: Any = None,
                         explicit_model_path: str = "") -> Optional[Dict[str, Any]]:
    """The full auto-resume dance (reference ``_load_and_sync_parameters`` +
    ``_load_ema_parameters`` + ``_load_optimizer_state``,
    trainer.py:136-173): discover the newest model checkpoint (or use the
    explicit one), then fetch companion EMA/opt states by naming convention.
    Missing companions degrade to the restored params (the reference seeds
    EMA from params, trainer.py:110-113). Returns None when nothing to resume.
    """
    if explicit_model_path:
        # An explicitly requested resume must never silently fall through to
        # fresh init (a typo'd path, or a reference-style model_NNNNNN.pt
        # FILE where an Orbax checkpoint DIRECTORY is expected, would
        # otherwise restart training from scratch unnoticed; the reference
        # asserts on malformed names, trainer.py:319-327).
        if not epath.Path(explicit_model_path).is_dir():
            raise FileNotFoundError(
                f"resume_checkpoint={explicit_model_path!r} is not an Orbax "
                f"checkpoint directory (expected .../model_{{step:06d}}/)")
        model_path = explicit_model_path
    else:
        model_path = find_resume_checkpoint(directory)
        if not model_path:
            return None
    # Parse the step from the path actually being restored (never re-scan:
    # a checkpoint finalized between two scans would desync step and params).
    step = parse_step_from_name(model_path) or 0
    params = restore_checkpoint(model_path, abstract_params)
    out: Dict[str, Any] = {"step": step, "params": params, "ema": {},
                           "opt_state": None}
    directory = os.fspath(epath.Path(model_path).parent)
    for rate in ema_rates:
        p = find_ema_checkpoint(directory, step, rate)
        if p:
            out["ema"][rate] = restore_checkpoint(p, abstract_params)
        else:
            # Missing companion degrades to a COPY of params (reference seeds
            # EMA from params, trainer.py:110-113) — never an alias, which
            # would be donated twice by the jitted step and crash.
            import jax.numpy as jnp
            out["ema"][rate] = jax.tree_util.tree_map(jnp.copy, params)
    if abstract_opt is not None:
        p = find_opt_checkpoint(directory, step)
        if p:
            out["opt_state"] = restore_checkpoint(p, abstract_opt)
    return out
