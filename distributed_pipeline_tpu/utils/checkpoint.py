"""Checkpoint/resume with the reference's filename-is-metadata contract.

Parity target (``/root/reference/utils/trainer.py:277-355``, SURVEY.md §5.4):
per-run directory holding ``model_{step:06d}`` / ``ema_{rate}_{step:06d}`` /
``opt_{step:06d}``, resume step parsed from the trailing six digits, newest
checkpoint auto-discovered from the run/log dir, and companion files located
by convention.

TPU-native backend: Orbax (each name is an Orbax directory rather than a
``.pt`` file). That buys what blobfile+torch.save could not: multi-host-safe
single-writer semantics, sharded-array save/restore that keeps each chip's
shard on-chip (no host gather), and atomic finalization. Restore takes an
abstract target tree so arrays come back with the requested shardings.

ELASTIC-TOPOLOGY contract (ISSUE 10): the abstract target carries the
shardings of the mesh the RESUMING run built — which need not be the
mesh the checkpoint was written on. Orbax reshards on restore, so a
checkpoint saved at dp=N restores cleanly at dp=M (params, Adam moments,
EMA copies — including ZeRO-1 data-axis-sharded state in either
direction, since :func:`restore_resume_state`'s ``abstract_opt`` /
``abstract_ema`` always describe the NEW run's layout). The meta sidecar
(:func:`save_meta`) records the save-time ``global_batch`` / ``samples``
/ mesh shape so run/train.py can fast-forward the data stream by global
samples consumed rather than per-host step position.

Paths go through ``etils.epath``, so run dirs and resume paths may be remote
URIs (``gs://...``) exactly like the reference's blobfile-backed reads
(``/root/reference/basic_utils/dist_util.py:118-124``, SURVEY.md §5.4).
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
from etils import epath

from . import logger

import orbax.checkpoint as ocp

__all__ = [
    "save_checkpoint", "AsyncSaver", "restore_checkpoint",
    "restore_resume_state", "resume_target",
    "parse_step_from_name", "find_resume_checkpoint", "find_ema_checkpoint",
    "find_opt_checkpoint", "latest_step", "prune_checkpoints",
    "in_flight_steps",
]

_STEP_RE = re.compile(r"(\d{6,})$")


def _checkpointer() -> ocp.Checkpointer:
    # StandardCheckpointer = async PyTree save with OCDBT; one per call keeps
    # this module stateless (cheap: it is a thin handle).
    return ocp.StandardCheckpointer()


def parse_step_from_name(name: str) -> Optional[int]:
    """``model_012345`` -> 12345 (reference ``parse_resume_step_from_filename``,
    trainer.py:319-327 — trailing digits are the step)."""
    m = _STEP_RE.search(name.rstrip("/"))
    return int(m.group(1)) if m else None


_ORBAX_TMP_MARKER = ".orbax-checkpoint-tmp"

# Finalization markers orbax leaves INSIDE a committed checkpoint dir:
# _CHECKPOINT_METADATA on the rename-atomic (local fs) protocol, and
# commit_success.txt on in-place backends (gs://) where the final NAME
# exists for the whole write and only the marker says "durable".
_COMMIT_MARKERS = ("_CHECKPOINT_METADATA", "commit_success.txt")

# Saves scheduled but not yet durable in THIS process: {(abs_dir, step)}.
# AsyncSaver registers/clears them so retention pruning can never rank or
# delete a checkpoint whose background write is still in flight (on
# in-place backends the dir already carries its final name mid-write).
_IN_FLIGHT: set = set()


def _norm_dir(directory: str) -> str:
    """The directory key used by the in-flight registry (absolute for
    local paths, verbatim for URIs — mirrors AsyncSaver's path logic)."""
    if "://" in directory:
        return directory.rstrip("/")
    return os.path.abspath(directory)


def in_flight_steps(directory: str) -> set:
    """Steps with a save scheduled by this process that is not yet durable."""
    key = _norm_dir(directory)
    return {s for d, s in _IN_FLIGHT if d == key}


def _is_unfinalized(name: str) -> bool:
    """Orbax writes into ``<name>.orbax-checkpoint-tmp-<timestamp>`` and
    renames on finalize; a crash mid-save leaves the tmp behind. Its
    trailing timestamp parses as a huge step, so treating it as a real
    checkpoint would (a) make auto-resume pick an unrestorable directory
    and (b) make retention-pruning rank it newest and delete genuine
    checkpoints instead."""
    return _ORBAX_TMP_MARKER in name


def _looks_finalized(path: "epath.Path") -> bool:
    """True when the checkpoint dir carries orbax's commit marker. A dir
    with its FINAL name but no marker is a torn save: an in-place write
    that crashed between the array write and finalize (or another
    process's write still in flight) — auto-resume must skip it and
    retention must not count or delete it."""
    try:
        return any((path / m).exists() for m in _COMMIT_MARKERS)
    except Exception:
        return False  # unreadable == not restorable; treat as torn


def _scan(directory: str, prefix: str,
          finalized_only: bool = False) -> List[Tuple[int, str]]:
    if not directory:
        return []
    # Absolute paths out (local only; URIs pass through): orbax REJECTS
    # relative restore paths, so discovery from a cwd-relative run dir
    # (run/train.py's default model_checkpoints/...) must not hand the
    # restore a path it will refuse.
    directory = _norm_dir(directory)
    d = epath.Path(directory)
    if not d.is_dir():
        return []
    out = []
    for child in d.iterdir():
        if child.name.startswith(prefix) and not _is_unfinalized(child.name):
            step = parse_step_from_name(child.name)
            if step is None:
                continue
            if finalized_only and not _looks_finalized(child):
                continue
            out.append((step, os.fspath(child)))
    return sorted(out)


def find_resume_checkpoint(directory: str) -> Optional[str]:
    """Newest FINALIZED ``model_*`` checkpoint in the run dir (reference
    ``find_resume_checkpoint`` trainer.py:329-335 scans the logger dir).
    Torn saves — orbax tmp dirs AND final-named dirs without the commit
    marker — are skipped, so a crash mid-save resumes from the previous
    step instead of dying on an unrestorable directory."""
    found = _scan(directory, "model_", finalized_only=True)
    return found[-1][1] if found else None


def resume_target(directory: str,
                  explicit_model_path: str = "") -> Tuple[int, str]:
    """``(step, model_path)`` a run over ``directory`` would resume from —
    ``(0, "")`` when fresh. The discovery rule (explicit path wins, else
    newest finalized ``model_*``, step parsed from the name). NOTE: this
    is a PREVIEW — :func:`restore_resume_state` may walk back further if
    the newest checkpoint fails to restore, which is why run/train.py now
    wires the data fast-forward from the step the loop ACTUALLY restored
    (``TrainLoop.set_data``), not from this function."""
    path = explicit_model_path or find_resume_checkpoint(directory)
    if not path:
        return 0, ""
    return parse_step_from_name(path) or 0, path


def save_meta(directory: str, step: int, meta: dict) -> None:
    """Write the per-checkpoint metadata sidecar (``meta_{step:06d}.json``):
    run facts the filenames cannot carry — the consumed-eval-batch count
    and eval interval (so a resume fast-forwards the eval stream exactly
    even when ``--eval_interval`` changed; the r4 advisor's 'a warning is
    not a contract') and, for elastic resume, the save-time
    ``global_batch`` / cumulative ``samples`` / mesh shape (the
    topology-invariant data-stream position). Process 0 only; tiny
    synchronous write."""
    import json as _json

    if jax.process_index() != 0:
        return
    p = epath.Path(directory) / f"meta_{step:06d}.json"
    try:
        p.write_text(_json.dumps(meta))
    except Exception as e:  # metadata must never fail a save
        logger.warn(f"checkpoint meta write failed ({p}): {e}")


def load_meta(directory: str, step: int) -> Optional[dict]:
    """The sidecar written by :func:`save_meta`, or None (pre-r5
    checkpoints have none — callers fall back to flag-derived values)."""
    import json as _json

    p = epath.Path(directory) / f"meta_{step:06d}.json"
    try:
        if p.is_file():
            return _json.loads(p.read_text())
    except Exception as e:
        logger.warn(f"checkpoint meta read failed ({p}): {e}")
    return None


def find_ema_checkpoint(directory: str, step: int, rate: str) -> Optional[str]:
    path = epath.Path(directory) / f"ema_{rate}_{step:06d}"
    return os.fspath(path) if path.is_dir() else None


def find_opt_checkpoint(directory: str, step: int) -> Optional[str]:
    path = epath.Path(directory) / f"opt_{step:06d}"
    return os.fspath(path) if path.is_dir() else None


def latest_step(directory: str) -> int:
    found = _scan(directory, "model_", finalized_only=True)
    return found[-1][0] if found else 0


class AsyncSaver:
    """At most ONE checkpoint save in flight, overlapping training.

    Orbax's ``StandardCheckpointer.save`` is async: the device-to-host
    fetch happens synchronously inside ``save()`` (so the caller may
    freely donate/overwrite device buffers afterwards — the jitted step's
    ``donate_argnums`` is safe), while the durable write proceeds on
    background threads. The reference blocks the step loop for the whole
    write (its save + barrier, trainer.py:277-302); here the barrier
    moves to where it is actually needed: before the NEXT save, before
    retention pruning, and at exit (``wait()``). At BASELINE-5 scale
    params + 3 EMA copies + Adam state is ~5x model size — that write now
    costs the step loop only the D2H fetch."""

    def __init__(self) -> None:
        self._ckptrs: List[ocp.Checkpointer] = []
        self._inflight_keys: List[Tuple[str, int]] = []

    def wait(self) -> None:
        """Block until every in-flight save is durable."""
        for c in self._ckptrs:
            c.wait_until_finished()
            c.close()
        self._ckptrs = []
        # Only now — durable — does the step leave the in-flight registry
        # and become fair game for retention pruning.
        for key in self._inflight_keys:
            _IN_FLIGHT.discard(key)
        self._inflight_keys = []

    def save(self, directory: str, step: int, params: Any,
             ema: Optional[Dict[str, Any]] = None,
             opt_state: Optional[Any] = None, wait: bool = False) -> None:
        """Schedule ``model_{step:06d}`` (+ ``ema_{rate}_``/``opt_``)
        under ``directory``. Multi-host safe: every process must call this
        (Orbax coordinates the single-writer protocol). Waits for the
        PREVIOUS save first (one step's saves in flight max — the
        reference's barrier-before-next-save contract); ``wait=True`` also
        blocks until THIS save is durable (the reference's
        fully-synchronous semantics).

        One checkpointer PER TREE: orbax's ``AsyncCheckpointer.save``
        waits for that handle's previous write on entry, so scheduling
        model + EMAs + opt on a single handle would serialize them and
        only overlap the last — separate handles let all trees' writes
        proceed concurrently in the background."""
        self.wait()
        d = epath.Path(directory)
        if not d.is_absolute() and "://" not in directory:
            d = epath.Path(os.path.abspath(directory))  # orbax: absolute
        if jax.process_index() == 0:
            d.mkdir(parents=True, exist_ok=True)
        trees = [(d / f"model_{step:06d}", params)]
        trees += [(d / f"ema_{rate}_{step:06d}", tree)
                  for rate, tree in (ema or {}).items()]
        if opt_state is not None:
            trees.append((d / f"opt_{step:06d}", opt_state))
        # Register BEFORE scheduling: from the first array write until
        # wait() observes durability, this step is invisible to (and
        # undeletable by) prune_checkpoints — the model_ tree can finalize
        # while its ema_/opt_ companions are still writing, and an
        # in-place backend's dirs carry their final names the whole time.
        key = (_norm_dir(os.fspath(d)), step)
        _IN_FLIGHT.add(key)
        self._inflight_keys.append(key)
        for path, tree in trees:
            ckptr = _checkpointer()
            ckptr.save(path, tree, force=True)
            self._ckptrs.append(ckptr)
        if wait:
            self.wait()


def save_checkpoint(directory: str, step: int, params: Any,
                    ema: Optional[Dict[str, Any]] = None,
                    opt_state: Optional[Any] = None) -> None:
    """Synchronous one-shot save: all processes block until the write is
    durable (the reference's semantics, trainer.py:282). The step loop
    uses :class:`AsyncSaver` instead to overlap the write with training."""
    AsyncSaver().save(directory, step, params, ema=ema,
                      opt_state=opt_state, wait=True)


def prune_checkpoints(directory: str, keep: int) -> List[int]:
    """Delete all but the newest ``keep`` checkpoint steps (model + every
    companion ``ema_*``/``opt_`` of the pruned step). The reference keeps
    everything; at three EMA rates + optimizer state a 320k-step run
    accumulates ~5x params-size per save, so long runs need a retention
    policy. Process 0 only (single-writer, like the save protocol);
    returns the pruned step numbers. ``keep <= 0`` disables pruning."""
    if keep <= 0 or jax.process_index() != 0:
        return []
    d = epath.Path(directory)
    if not d.is_dir():
        return []
    # ONE directory listing serves both the step ranking and the deletes —
    # each listing is a remote LIST on gs:// run dirs. Unfinalized
    # checkpoints are excluded from BOTH ranking and deletion: orbax tmp
    # dirs, final-named dirs without the commit marker (an in-place write
    # mid-flight or a torn crash), and any step the AsyncSaver registry
    # says this process is still writing — a save must become durable
    # before retention may count it, let alone delete it.
    inflight = in_flight_steps(directory)
    children = [(child, child.name) for child in d.iterdir()
                if not _is_unfinalized(child.name)]
    protected = set(inflight)
    steps = []
    for child, n in children:
        step = parse_step_from_name(n)
        if not n.startswith("model_") or step is None:
            continue
        if step in inflight or not _looks_finalized(child):
            protected.add(step)
        else:
            steps.append(step)
    steps = sorted(steps)
    doomed = set(steps[:-keep] if len(steps) > keep else []) - protected
    if not doomed:
        return []
    # A step counts as pruned only when EVERY one of its dirs (model_ +
    # companions) deleted; partial failures are reported per step so the
    # log never claims a step was removed while a restorable model_
    # remains (r4 advisor).
    failed = set()
    touched = set()
    for child, name in children:
        if (name.startswith(("model_", "ema_", "opt_", "meta_"))
                and parse_step_from_name(name) in doomed):
            step = parse_step_from_name(name)
            touched.add(step)
            try:
                if name.startswith("meta_"):
                    child.unlink()
                else:
                    child.rmtree()
            # broad by design: epath's gs:// backends surface failures as
            # tf.errors.OpError / gcsfs HttpError etc., not OSError
            except Exception as e:
                # Retention is housekeeping: a delete failure (gs://
                # permissions, concurrent cleanup) must never abort the
                # training run that just saved successfully.
                failed.add(step)
                logger.warn(f"checkpoint retention: could not delete "
                            f"{child}: {e}")
    if failed:
        logger.warn(f"checkpoint retention: steps "
                    f"{sorted(failed)} only PARTIALLY deleted — their "
                    f"remaining dirs will be retried next retention pass")
    return sorted(touched - failed)


def restore_checkpoint(path: str, abstract_target: Any) -> Any:
    """Restore one tree; ``abstract_target`` (jax.eval_shape output with
    shardings attached) dictates dtypes/shardings of the result."""
    ckptr = _checkpointer()
    try:
        return ckptr.restore(path, abstract_target)
    finally:
        ckptr.close()


def restore_resume_state(directory: str, *, abstract_params: Any,
                         ema_rates: Tuple[str, ...] = (),
                         abstract_opt: Any = None,
                         abstract_ema: Any = None,
                         explicit_model_path: str = "") -> Optional[Dict[str, Any]]:
    """The full auto-resume dance (reference ``_load_and_sync_parameters`` +
    ``_load_ema_parameters`` + ``_load_optimizer_state``,
    trainer.py:136-173): discover the newest model checkpoint (or use the
    explicit one), then fetch companion EMA/opt states by naming convention.
    Missing companions degrade to the restored params (the reference seeds
    EMA from params, trainer.py:110-113). Returns None when nothing to resume.

    ``abstract_ema`` is the EMA restore target when its layout differs
    from the params' (ZeRO-1: EMA sharded across the data axis while
    params replicate over it); defaults to ``abstract_params``. Degraded
    (missing/corrupt) companions are placed into that layout too — the
    trainer's AOT step pins its state shardings, so a params-layout EMA
    would be rejected at the second step.
    """
    if explicit_model_path:
        # An explicitly requested resume must never silently fall through to
        # fresh init (a typo'd path, or a reference-style model_NNNNNN.pt
        # FILE where an Orbax checkpoint DIRECTORY is expected, would
        # otherwise restart training from scratch unnoticed; the reference
        # asserts on malformed names, trainer.py:319-327). It also never
        # walks back: the user asked for THIS checkpoint, so a failure to
        # restore it is their error to see, not ours to paper over.
        if not epath.Path(explicit_model_path).is_dir():
            raise FileNotFoundError(
                f"resume_checkpoint={explicit_model_path!r} is not an Orbax "
                f"checkpoint directory (expected .../model_{{step:06d}}/)")
        candidates = [explicit_model_path]
    else:
        # Newest first; older finalized checkpoints are the walk-back
        # ladder. Before this, one corrupt newest checkpoint (bit rot, a
        # partially-synced copy, an injected chaos fault) made EVERY
        # restart attempt die in restore forever — the elastic launcher
        # would burn its whole restart budget on an unrestorable file.
        found = _scan(directory, "model_", finalized_only=True)
        candidates = [p for _, p in reversed(found)]
        if not candidates:
            return None
    last_err: Optional[Exception] = None
    for model_path in candidates:
        # Parse the step from the path actually being restored (never
        # re-scan: a checkpoint finalized between two scans would desync
        # step and params).
        step = parse_step_from_name(model_path) or 0
        try:
            params = restore_checkpoint(model_path, abstract_params)
        except Exception as e:  # orbax surfaces corruption as
            # ValueError/FileNotFoundError/tensorstore errors — any of
            # them means "this checkpoint cannot feed a resume"
            if explicit_model_path:
                raise
            logger.warn(
                f"resume: restoring {model_path} failed "
                f"({type(e).__name__}: {str(e)[:200]}); walking back to "
                f"the next older checkpoint")
            last_err = e
            continue
        break
    else:
        # Every discovered checkpoint failed to restore. Fail LOUDLY: a
        # silent fresh start from step 0 in a dir full of checkpoints is
        # the worst outcome (it would overwrite the run's history), and
        # the launcher's crash-loop fail-fast stops the restart burn.
        raise RuntimeError(
            f"resume: all {len(candidates)} checkpoint(s) in {directory} "
            f"failed to restore; newest error: {last_err}") from last_err
    out: Dict[str, Any] = {"step": step, "params": params, "ema": {},
                           "opt_state": None, "path": model_path}
    directory = os.fspath(epath.Path(model_path).parent)
    abs_ema = abstract_ema if abstract_ema is not None else abstract_params

    def _degraded(rate: str) -> Any:
        # Missing/unrestorable companion degrades to a COPY of params
        # (reference seeds EMA from params, trainer.py:110-113) — never an
        # alias, which would be donated twice by the jitted step and crash.
        # The copy is then PLACED into the EMA layout: under ZeRO-1 that
        # differs from the params layout, and the step's pinned shardings
        # make a mislaid EMA a hard error one step later. (device_put is
        # an explicit transfer — legal under the sanitizer's guard; on an
        # identical layout it's a no-op over the fresh copy.)
        import jax.numpy as jnp
        copy = jax.tree_util.tree_map(jnp.copy, params)
        if abstract_ema is None:
            return copy
        return jax.device_put(
            copy, jax.tree_util.tree_map(lambda a: a.sharding, abs_ema))

    for rate in ema_rates:
        p = find_ema_checkpoint(directory, step, rate)
        try:
            out["ema"][rate] = (restore_checkpoint(p, abs_ema)
                                if p else _degraded(rate))
        except Exception as e:  # corrupt companion: degrade like missing
            logger.warn(f"resume: EMA companion {p} failed to restore "
                        f"({type(e).__name__}); seeding from params")
            out["ema"][rate] = _degraded(rate)
    if abstract_opt is not None:
        p = find_opt_checkpoint(directory, step)
        if p:
            try:
                out["opt_state"] = restore_checkpoint(p, abstract_opt)
            except Exception as e:  # fresh optimizer beats a dead resume
                logger.warn(f"resume: optimizer companion {p} failed to "
                            f"restore ({type(e).__name__}); reinitializing")
    return out
