"""Sharding rules: logical axis names -> mesh axes.

This file is the whole "parallelism engine" — the TPU-native replacement for
the reference's DDP wrapper (``/root/reference/utils/trainer.py:115-128``) and
the hook its `grad_clip` leaves for sharded optimizers (``trainer.py:246-255``).
Models annotate weights with logical names (models/backbone.py); this module
maps them onto the mesh; XLA inserts every collective. Changing parallelism
strategy (DP -> FSDP -> +TP) is a rules/mesh change, zero engine code
(SURVEY.md §2.2, BASELINE.md configs 2/3/5).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import batch_spec

__all__ = ["LOGICAL_RULES", "param_shardings", "batch_shardings",
           "shard_batch", "replicated"]

# Logical-name -> mesh-axis rules.
#   embed  -> fsdp:   parameter/optimizer sharding (ZeRO-3 analogue): every
#                     weight has an "embed" dim, so every weight shards.
#   mlp/heads -> tensor: Megatron-style TP pairing — wi column-, wo
#                     row-parallel; attention heads split across chips.
#   vocab  -> tensor+fsdp: embedding/logit matrix splits over vocab.
LOGICAL_RULES: Tuple[Tuple[str, Optional[str]], ...] = (
    ("batch", ("data", "fsdp", "expert")),
    # vocab shards over tensor (Megatron vocab-parallel logits) AND fsdp
    # (ZeRO for the big tied table — on its VOCAB dim, not hidden: a
    # hidden-sharded table propagates fsdp onto every [B, L, hidden]
    # activation it produces, which fights the batch sharding and forces
    # the SPMD partitioner into full-replication resharding. Falls back
    # to replication when the vocab doesn't divide; pad the vocab to keep
    # ZeRO coverage).
    ("vocab", ("tensor", "fsdp")),
    ("embed", "fsdp"),
    ("mlp", "tensor"),
    ("heads", "tensor"),
    ("kv", None),
    ("length", "sequence"),
    # MoE expert-weight leading dim -> expert parallelism (models/moe.py);
    # the dispatch/combine einsums against batch-sharded activations make
    # XLA emit the all-to-alls (GShard recipe).
    ("expert", "expert"),
    # Stacked-layer leading dim -> pipeline stages (models/pipeline.py).
    ("layers", "pipe"),
)


def param_shardings(mesh: Mesh, abstract_variables: Any,
                    rules: Sequence[Tuple[str, Any]] = LOGICAL_RULES) -> Any:
    """NamedShardings for a (possibly abstract) boxed variables tree carrying
    flax logical-partitioning metadata. Axes whose size the dim doesn't divide
    fall back to replication (so tiny test models shard cleanly)."""
    specs = nn.get_partition_spec(abstract_variables)
    shapes = jax.tree_util.tree_map(lambda x: x.shape,
                                    nn.meta.unbox(abstract_variables))

    def fix(spec: P, shape) -> NamedSharding:
        fixed = []
        for dim, ax in zip(shape, tuple(spec) + (None,) * len(shape)):
            axes = ax if isinstance(ax, tuple) else (ax,) if ax else ()
            size = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
            fixed.append(ax if size > 1 and dim % size == 0 else None)
        return NamedSharding(mesh, P(*fixed))

    mesh_specs = nn.logical_to_mesh_sharding(specs, mesh, list(rules))
    return jax.tree_util.tree_map(
        lambda s, shape: fix(s.spec, shape), mesh_specs, shapes)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_shardings(mesh: Mesh, microbatched: bool = False,
                    seq_sharded: bool = False) -> NamedSharding:
    """Sharding for data batches: [B, ...] over (data, fsdp) — FSDP ranks
    consume distinct data shards, ZeRO semantics. ``microbatched`` prepends an
    unsharded gradient-accumulation axis [n_micro, B_micro, ...]."""
    spec = batch_spec(mesh, seq_sharded=seq_sharded)
    if microbatched:
        spec = P(None, *spec)
    return NamedSharding(mesh, spec)


def shard_batch(mesh: Mesh, batch: Dict[str, np.ndarray],
                sharding: Optional[NamedSharding] = None,
                batch_axis: int = 0) -> Dict[str, jax.Array]:
    """Host-local numpy batch -> global device array. Single-host this is a
    sharded device_put; multi-host it assembles the global array from each
    process's local shard (the reference's per-rank-batch semantics,
    trainer.py:89: global batch = local x world_size). ``batch_axis`` is 1
    for microbatched [n_micro, B_micro, ...] arrays."""
    if sharding is None:
        sharding = batch_shardings(mesh, microbatched=batch_axis == 1)

    def put(x: np.ndarray) -> jax.Array:
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        global_shape = list(x.shape)
        global_shape[batch_axis] *= jax.process_count()
        return jax.make_array_from_process_local_data(
            sharding, x, tuple(global_shape))

    return {k: put(v) for k, v in batch.items()}
