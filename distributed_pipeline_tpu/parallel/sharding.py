"""Sharding compat surface: flax logical axis names -> mesh axes.

The parameter-sharding ENGINE now lives in ``parallel/partition.py`` (the
regex-rule tables + ZeRO-1 layer, ISSUE 9): models declare ordered
``(path-regex, PartitionSpec)`` tables and the trainer resolves them
there. This module remains as (a) the thin compat shim for models that
still carry flax ``nn.with_logical_partitioning`` metadata —
:func:`param_shardings` translates their logical specs and delegates the
materialization (divisibility fix, NamedSharding binding) to the engine —
and (b) the batch/IO helpers (:func:`batch_shardings`,
:func:`shard_batch`, :func:`replicated`), which shard data, not params.

Historical note: this file used to BE the parallelism engine (the
TPU-native replacement for the reference's DDP wrapper,
``/root/reference/utils/trainer.py:115-128``); changing strategy is still
a rules/mesh change with zero engine code, the rules just moved to
partition tables.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import batch_spec

__all__ = ["LOGICAL_RULES", "param_shardings", "batch_shardings",
           "shard_batch", "replicated"]

# Logical-name -> mesh-axis rules.
#   embed  -> fsdp:   parameter/optimizer sharding (ZeRO-3 analogue): every
#                     weight has an "embed" dim, so every weight shards.
#   mlp/heads -> tensor: Megatron-style TP pairing — wi column-, wo
#                     row-parallel; attention heads split across chips.
#   vocab  -> tensor+fsdp: embedding/logit matrix splits over vocab.
LOGICAL_RULES: Tuple[Tuple[str, Optional[str]], ...] = (
    ("batch", ("data", "fsdp", "expert")),
    # vocab shards over tensor (Megatron vocab-parallel logits) AND fsdp
    # (ZeRO for the big tied table — on its VOCAB dim, not hidden: a
    # hidden-sharded table propagates fsdp onto every [B, L, hidden]
    # activation it produces, which fights the batch sharding and forces
    # the SPMD partitioner into full-replication resharding. Falls back
    # to replication when the vocab doesn't divide; pad the vocab to keep
    # ZeRO coverage).
    ("vocab", ("tensor", "fsdp")),
    ("embed", "fsdp"),
    ("mlp", "tensor"),
    ("heads", "tensor"),
    ("kv", None),
    ("length", "sequence"),
    # MoE expert-weight leading dim -> expert parallelism (models/moe.py);
    # the dispatch/combine einsums against batch-sharded activations make
    # XLA emit the all-to-alls (GShard recipe).
    ("expert", "expert"),
    # Stacked-layer leading dim -> pipeline stages (models/pipeline.py).
    ("layers", "pipe"),
)


def param_shardings(mesh: Mesh, abstract_variables: Any,
                    rules: Sequence[Tuple[str, Any]] = LOGICAL_RULES) -> Any:
    """NamedShardings for a (possibly abstract) boxed variables tree carrying
    flax logical-partitioning metadata. Compat shim: the logical names are
    translated to mesh specs here, then the partition engine materializes
    them (axes whose size the dim doesn't divide fall back to replication
    — partition.fix_spec, so tiny test models shard cleanly)."""
    from .partition import resolve_shardings

    specs = nn.get_partition_spec(abstract_variables)
    shapes = jax.tree_util.tree_map(lambda x: x.shape,
                                    nn.meta.unbox(abstract_variables))
    mesh_specs = nn.logical_to_mesh_sharding(specs, mesh, list(rules))
    spec_tree = jax.tree_util.tree_map(lambda s: s.spec, mesh_specs)
    return resolve_shardings(mesh, spec_tree, shapes)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_shardings(mesh: Mesh, microbatched: bool = False,
                    seq_sharded: bool = False) -> NamedSharding:
    """Sharding for data batches: [B, ...] over (data, fsdp) — FSDP ranks
    consume distinct data shards, ZeRO semantics. ``microbatched`` prepends an
    unsharded gradient-accumulation axis [n_micro, B_micro, ...]."""
    spec = batch_spec(mesh, seq_sharded=seq_sharded)
    if microbatched:
        spec = P(None, *spec)
    return NamedSharding(mesh, spec)


def shard_batch(mesh: Mesh, batch: Dict[str, np.ndarray],
                sharding: Optional[NamedSharding] = None,
                batch_axis: int = 0) -> Dict[str, jax.Array]:
    """Host-local numpy batch -> global device array. Single-host this is a
    sharded device_put; multi-host it assembles the global array from each
    process's local shard (the reference's per-rank-batch semantics,
    trainer.py:89: global batch = local x world_size). ``batch_axis`` is 1
    for microbatched [n_micro, B_micro, ...] arrays."""
    if sharding is None:
        sharding = batch_shardings(mesh, microbatched=batch_axis == 1)

    def put(x: np.ndarray) -> jax.Array:
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        global_shape = list(x.shape)
        global_shape[batch_axis] *= jax.process_count()
        return jax.make_array_from_process_local_data(
            sharding, x, tuple(global_shape))

    return {k: put(v) for k, v in batch.items()}
