"""Ring attention: sequence/context parallelism over the ``sequence`` mesh axis.

The reference has no long-context machinery at all (SURVEY.md §5.7 — its max
context is whatever the user model fits on one GPU). Here long context is
first-class: activations shard over sequence ([B, H, L/n, Dh] per chip) and
attention runs as a ring — each chip holds its query shard, while key/value
shards rotate around the ``sequence`` axis via ``ppermute`` (ICI
neighbor-to-neighbor, the topology TPU ICI is best at). Per hop, a chip
runs the Pallas flash kernel (ops/flash_attention.py) on (its query shard x
the visiting K/V block) and folds the block's normalized output into a
running online-softmax state using the kernel's log-sum-exp, so

* memory per chip stays O(L/n): the flash kernel streams the block through
  VMEM (never materializing the [L/n, L/n] score matrix the dense fallback
  would), and the fold state is O(L/n);
* compute and communication overlap naturally (the next block can be in
  flight while the current one multiplies);
* the math is EXACTLY softmax attention — tests assert parity with the
  dense XLA path, gradients included (``ppermute`` and the flash kernel's
  LSE output are both differentiable).

Causal masking: the diagonal hop (block from this chip's own shard) runs the
kernel with its causal flag; blocks from earlier shards attend fully; blocks
from later shards contribute nothing (zero output, -inf LSE — weight 0 in
the fold). The three cases select via ``lax.switch`` on the traced source
index — safe per-device branching, because every branch is chip-local
compute (no collectives inside), so no SPMD rendezvous can diverge; the
``ppermute`` rotating the carry stays unconditional every hop.

Usage: inside ``shard_map`` (models get there via
``ops.attention.dot_product_attention(impl="ring")`` which wraps this in a
``shard_map`` over the ambient mesh).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e9

__all__ = ["ring_attention", "ring_attention_sharded", "current_mesh"]


def current_mesh():
    """The ambient ``with mesh:`` context's mesh (None outside one)."""
    from jax._src.mesh import thread_resources
    m = thread_resources.env.physical_mesh
    return None if m.empty else m


def _dense_block_attn(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      kmask: Optional[jnp.ndarray], causal: bool,
                      q_off: jnp.ndarray, k_off: jnp.ndarray,
                      sm_scale: float) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense einsum fallback for one (q-shard x kv-block) piece ->
    (normalized out, lse), f32 stats. Shapes: q [B,H,Lq,D], k/v [B,H,Lk,D].
    Materializes the [Lq, Lk] score block — kept only as the reference
    implementation the flash path is tested against."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if kmask is not None:
        s = s + (1.0 - kmask.astype(jnp.float32))[:, None, None, :] * NEG_INF
    if causal:
        Lq, Lk = q.shape[-2], k.shape[-2]
        rows = q_off + jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0)
        cols = k_off + jax.lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1)
        s = jnp.where((rows >= cols)[None, None], s, NEG_INF)
    live = s > NEG_INF / 2
    m = jnp.max(s, axis=-1)                                   # [B,H,Lq]
    p = jnp.where(live, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)                                   # [B,H,Lq]
    pv = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    out = pv / jnp.maximum(l, 1e-20)[..., None]
    lse = m + jnp.log(jnp.maximum(l, 1e-20))
    return out, lse


def _flash_block_attn(q, k, v, kmask, causal, my, src,
                      block_q: int, block_k: int):
    """One hop through the Pallas flash kernel -> (normalized out, lse).

    ``my``/``src`` are traced shard indices; under causal attention they
    select diagonal (causal kernel), past (full kernel), or future (zero
    contribution) — chip-local branching only, see module docstring."""
    from ..ops.flash_attention import flash_attention_lse

    if not causal:
        return flash_attention_lse(q, k, v, kmask, False, block_q, block_k)

    B, H, Lq, D = q.shape

    def diag(_):
        return flash_attention_lse(q, k, v, kmask, True, block_q, block_k)

    def past(_):
        return flash_attention_lse(q, k, v, kmask, False, block_q, block_k)

    def future(_):
        return (jnp.zeros((B, H, Lq, D), q.dtype),
                jnp.full((B, H, Lq), NEG_INF, jnp.float32))

    idx = jnp.where(src == my, 0, jnp.where(src < my, 1, 2))
    return jax.lax.switch(idx, (diag, past, future), None)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   pad_mask: Optional[jnp.ndarray] = None,
                   causal: bool = False,
                   axis_name: str = "sequence",
                   use_flash: bool = True,
                   block_q: int = 1024, block_k: int = 1024) -> jnp.ndarray:
    """Exact attention over sequence-sharded [B, H, L_local, Dh] inputs.
    Must run inside ``shard_map`` with ``axis_name`` bound.

    Each hop yields a NORMALIZED block output plus its LSE; the cross-hop
    fold re-weights by ``exp(lse - m_run)`` so the final result is exactly
    global softmax attention. ``use_flash=False`` selects the dense einsum
    per-hop reference (O((L/n)^2) score memory — tests only)."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    L_local = q.shape[-2]
    sm_scale = q.shape[-1] ** -0.5
    perm = [(i, (i + 1) % n) for i in range(n)]  # rotate kv around the ring
    q_off = my * L_local

    def hop(carry, i):
        k_blk, v_blk, mask_blk, acc, m_run, l_run = carry
        src = (my - i) % n                # shard that produced this kv block
        if use_flash:
            out_blk, lse_blk = _flash_block_attn(
                q, k_blk, v_blk, mask_blk, causal, my, src, block_q, block_k)
        else:
            out_blk, lse_blk = _dense_block_attn(
                q, k_blk, v_blk, mask_blk, causal,
                q_off, src * L_local, sm_scale)
        m_new = jnp.maximum(m_run, lse_blk)
        alpha = jnp.exp(m_run - m_new)
        beta = jnp.exp(lse_blk - m_new)
        acc = acc * alpha[..., None] + out_blk.astype(jnp.float32) * beta[..., None]
        l_run = l_run * alpha + beta
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        mask_nxt = (jax.lax.ppermute(mask_blk, axis_name, perm)
                    if mask_blk is not None else None)
        return (k_nxt, v_nxt, mask_nxt, acc, m_new, l_run), None

    B, H, _, D = q.shape
    acc0 = jnp.zeros((B, H, L_local, D), jnp.float32)
    m0 = jnp.full((B, H, L_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, L_local), jnp.float32)
    (_, _, _, acc, _, l), _ = jax.lax.scan(
        hop, (k, v, pad_mask, acc0, m0, l0), jnp.arange(n))
    return (acc / jnp.maximum(l, 1e-20)[..., None]).astype(q.dtype)


def ring_attention_sharded(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                           pad_mask: Optional[jnp.ndarray] = None,
                           causal: bool = False,
                           mesh=None,
                           use_flash: bool = True) -> jnp.ndarray:
    """Ring attention on GLOBAL [B, H, L, Dh] arrays: wraps
    :func:`ring_attention` in ``shard_map`` over the ambient (or given) mesh,
    sharding batch over (data, fsdp), heads over tensor, sequence over the
    ring axis."""
    from jax.sharding import PartitionSpec as P
    from ..utils.jax_compat import shard_map

    mesh = mesh if mesh is not None else current_mesh()
    if mesh is None:
        raise ValueError("ring attention needs a mesh: run inside `with mesh:`"
                         " or pass mesh=")
    B, H, L, _ = q.shape
    sp = mesh.shape["sequence"]
    if L % sp:
        raise ValueError(f"sequence length {L} not divisible by the "
                         f"sequence mesh axis ({sp})")
    # Shard batch/heads only over axes whose size divides them (a B=1 init
    # trace must still work on a dp>1 mesh — axes that don't divide fall
    # back to replication).
    batch_axes, rem = [], B
    for a in ("data", "fsdp", "expert"):  # mirror mesh.batch_spec
        if mesh.shape.get(a, 1) > 1 and rem % mesh.shape[a] == 0:
            batch_axes.append(a)
            rem //= mesh.shape[a]
    batch = tuple(batch_axes) or None
    heads = ("tensor" if mesh.shape["tensor"] > 1 and H % mesh.shape["tensor"] == 0
             else None)
    qkv_spec = P(batch, heads, "sequence", None)
    mask_spec = P(batch, "sequence")

    if pad_mask is None:
        fn = shard_map(
            functools.partial(ring_attention, pad_mask=None, causal=causal,
                              use_flash=use_flash),
            mesh=mesh, in_specs=(qkv_spec,) * 3, out_specs=qkv_spec,
            check_vma=False)
        return fn(q, k, v)
    fn = shard_map(
        functools.partial(ring_attention, causal=causal, use_flash=use_flash),
        mesh=mesh, in_specs=(qkv_spec,) * 3 + (mask_spec,),
        out_specs=qkv_spec, check_vma=False)
    return fn(q, k, v, pad_mask)
