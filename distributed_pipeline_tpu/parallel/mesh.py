"""Device-mesh construction: the TPU-native replacement for process groups.

The reference's only notion of topology is the flat c10d world
(``dist_util.py:92-101``) consumed by DDP (``trainer.py:115-128``). On TPU the
equivalent first-class object is a ``jax.sharding.Mesh`` over the ICI torus,
with named axes that the rest of the framework shards against:

* ``data``     — data parallelism (DDP replacement; gradient psum rides ICI)
* ``fsdp``     — parameter/optimizer sharding (ZeRO/FSDP equivalent;
                 BASELINE.json config 5)
* ``tensor``   — tensor parallelism (reserved axis, SURVEY.md §2.2)
* ``sequence`` — sequence/context parallelism for ring attention
                 (SURVEY.md §5.7 "leave a sequence mesh-axis name reserved")
* ``expert``   — expert parallelism (GShard-style: batch shards over it in
                 dense layers, MoE expert weights shard over it, and XLA
                 emits the dispatch/combine all-to-alls from the einsum
                 shardings — models/moe.py)
* ``pipe``     — pipeline parallelism (GPipe-style: stacked layer weights
                 shard over it, activations stream stage-to-stage via
                 ``ppermute`` — models/pipeline.py)

Axis sizes come from ``MeshSettings`` (config/train.py); ``-1`` means "all
remaining devices". Multi-host meshes use ``mesh_utils.create_device_mesh``
so the axis order maps DCN-outermost/ICI-innermost correctly.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["AXES", "make_mesh", "resolve_axis_sizes", "batch_spec", "local_mesh_info"]

AXES: Tuple[str, ...] = ("data", "fsdp", "sequence", "tensor", "expert",
                         "pipe")


def resolve_axis_sizes(dp: int = -1, fsdp: int = 1, sequence: int = 1,
                       tensor: int = 1, expert: int = 1, pipe: int = 1,
                       n_devices: Optional[int] = None) -> Tuple[int, ...]:
    """Resolve ``-1`` axis sizes against the device count and validate the
    product. Returns sizes in AXES order (data, fsdp, sequence, tensor,
    expert, pipe)."""
    n = n_devices if n_devices is not None else jax.device_count()
    sizes = {"data": dp, "fsdp": fsdp, "sequence": sequence, "tensor": tensor,
             "expert": expert, "pipe": pipe}
    unknown = [k for k, v in sizes.items() if v == -1]
    if len(unknown) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {unknown}")
    fixed = math.prod(v for v in sizes.values() if v != -1)
    if unknown:
        if n % fixed != 0:
            raise ValueError(
                f"device count {n} not divisible by fixed axes product {fixed}")
        sizes[unknown[0]] = n // fixed
    total = math.prod(sizes.values())
    if total != n:
        raise ValueError(
            f"mesh axes {sizes} multiply to {total}, but {n} devices are present")
    return tuple(sizes[a] for a in AXES)  # type: ignore[return-value]


def _slice_count(devices: Sequence[jax.Device]) -> int:
    """Number of distinct TPU slices among ``devices`` (1 when the backend
    doesn't report ``slice_index`` — CPU, GPU, single slice)."""
    ids = set()
    for d in devices:
        try:
            s = getattr(d, "slice_index", None)
        except RuntimeError:  # some backends raise instead of returning None
            return 1
        if s is None:
            return 1
        ids.add(s)
    return max(len(ids), 1)


def make_mesh(dp: int = -1, fsdp: int = 1, sequence: int = 1, tensor: int = 1,
              expert: int = 1, pipe: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the framework mesh. Works for 1 device (all axes size 1 except
    one) through multi-host pods; on real TPU slices
    ``mesh_utils.create_device_mesh`` picks an ICI-contiguous layout.

    **Multi-slice pods** (devices spanning several ICI slices joined by
    DCN) are detected from ``slice_index`` and laid out with
    ``create_hybrid_device_mesh``: the ``data`` axis splits across slices
    — its only collective is the once-per-step gradient psum, the most
    DCN-tolerant traffic — while every other axis (fsdp/tensor/sequence/
    expert/pipe collectives run per layer or per hop) stays inside a
    slice, riding ICI. This is the reference's multi-node NCCL scale-out
    story (SURVEY.md §2.3) restated in mesh form."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    shape = resolve_axis_sizes(dp=dp, fsdp=fsdp, sequence=sequence,
                               tensor=tensor, expert=expert, pipe=pipe,
                               n_devices=n)
    from jax.experimental import mesh_utils

    n_slices = _slice_count(devices)
    if n_slices > 1:
        if shape[0] % n_slices != 0:
            raise ValueError(
                f"{n_slices} TPU slices joined by DCN: the data axis must "
                f"split across them (dp={shape[0]} not divisible by "
                f"{n_slices}). Non-data axes cannot span DCN — their "
                f"per-layer collectives would leave ICI.")
        dcn_shape = (n_slices,) + (1,) * (len(AXES) - 1)
        ici_shape = (shape[0] // n_slices,) + tuple(shape[1:])
        device_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=list(devices))
        return Mesh(device_array, AXES)
    try:
        device_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
    except Exception:
        device_array = np.asarray(list(devices)).reshape(shape)
    return Mesh(device_array, AXES)


def batch_spec(mesh: Mesh, seq_sharded: bool = False) -> P:
    """PartitionSpec for a [batch, seq, ...] array: batch over
    data+fsdp+expert (FSDP/expert ranks still consume distinct data shards —
    ZeRO/GShard semantics), and optionally seq over the sequence axis (ring
    attention)."""
    batch_axes = tuple(a for a in ("data", "fsdp", "expert")
                       if mesh.shape[a] > 1) or None
    if isinstance(batch_axes, tuple) and len(batch_axes) == 1:
        batch_axes = batch_axes[0]
    if seq_sharded and mesh.shape["sequence"] > 1:
        return P(batch_axes, "sequence")
    return P(batch_axes)


def local_mesh_info(mesh: Mesh) -> str:
    """Human-readable mesh summary for the launch log."""
    return (f"mesh {dict(mesh.shape)} over {mesh.devices.size} devices "
            f"({jax.process_count()} host(s), "
            f"{len(jax.local_devices())} local device(s))")
