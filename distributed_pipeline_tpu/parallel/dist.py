"""Distributed substrate: the degrade-gracefully communication shim.

Capability parity with the reference comm shim (``/root/reference/basic_utils/
dist_util.py:26-159``): the same call sites work under a multi-host launch,
a bare single process, or CPU-only — every primitive degrades to a no-op /
identity without a cluster (reference contract analyzed in SURVEY.md §2.3).

TPU-native mapping (no NCCL/c10d; XLA emits all collectives):

==========================  ====================================================
reference (torch/c10d)      this module (JAX)
==========================  ====================================================
``setup_dist``              ``setup_dist`` -> ``jax.distributed.initialize``
                            (once-only, skipped for single-process)
``is_available``            coordinator env vars present / multi-process flags
``is_initialized``          ``jax.distributed`` client state
``get_rank``                ``jax.process_index()`` (0 fallback)
``get_world_size``          ``jax.process_count()`` (1 fallback)
``barrier``                 ``multihost_utils.sync_global_devices``
``dev``                     first addressable device (TPU chip or CPU)
``broadcast``/``sync_params``  ``multihost_utils.broadcast_one_to_all``
``load_state_dict``         checkpoint loading lives in utils/checkpoint.py
``find_free_port``          same
==========================  ====================================================

Gradient all-reduce has no explicit call here at all: it is emitted by XLA
from the ``NamedSharding`` of the jitted train step (replacing DDP's bucketed
NCCL all-reduce, reference trainer.py:115-128).
"""

from __future__ import annotations

import functools
import os
import socket
from typing import Any, Optional

__all__ = [
    "is_available",
    "is_initialized",
    "setup_dist",
    "get_rank",
    "get_world_size",
    "barrier",
    "dev",
    "device_count",
    "broadcast",
    "sync_params",
    "find_free_port",
    "AUTORUN_ENV_FLAG",
]

# Set by the launcher on spawned workers (reference DIST_UTIL_AUTORUN_FLAG,
# dist_run.py:312).
AUTORUN_ENV_FLAG = "DPT_DIST_AUTORUN"

_COORD_VARS = ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS")


def is_available() -> bool:
    """True when a multi-process launch is detectable from the environment
    (reference dist_util.py:26-45 — torchrun env vars; here: JAX coordinator
    vars or the launcher's autorun flag). Single-process runs return False and
    everything still works."""
    if getattr(is_available, "cache", None) is not None:
        return is_available.cache  # type: ignore[attr-defined]
    if os.environ.get(AUTORUN_ENV_FLAG):
        return True
    if any(v in os.environ for v in _COORD_VARS):
        return True
    if int(os.environ.get("JAX_NUM_PROCESSES", "1")) > 1:
        return True
    return False


def is_initialized() -> bool:
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:
        return False


@functools.lru_cache(maxsize=None)  # once-only, like reference's lru_cache (dist_util.py:57)
def setup_dist(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Initialize multi-host JAX if a cluster is detectable; otherwise degrade
    silently to single-process (reference dist_util.py:57-85 catches init
    failure and downgrades). Idempotent via ``lru_cache``."""
    import jax

    if is_initialized():
        return
    if not is_available() and coordinator_address is None:
        return  # single-process mode: nothing to do, all fallbacks engage
    try:
        kwargs: dict = {}
        if coordinator_address is not None:
            kwargs["coordinator_address"] = coordinator_address
        elif (addr := next((os.environ[v] for v in _COORD_VARS if v in os.environ),
                           None)) is not None:
            kwargs["coordinator_address"] = addr
        if num_processes is not None:
            kwargs["num_processes"] = num_processes
        elif "JAX_NUM_PROCESSES" in os.environ:
            kwargs["num_processes"] = int(os.environ["JAX_NUM_PROCESSES"])
        if process_id is not None:
            kwargs["process_id"] = process_id
        elif "JAX_PROCESS_INDEX" in os.environ:
            kwargs["process_id"] = int(os.environ["JAX_PROCESS_INDEX"])
        jax.distributed.initialize(**kwargs)
    except Exception as e:  # degrade to single-process, like the reference
        from ..utils import logger
        logger.warn(f"jax.distributed.initialize failed ({e}); "
                    "continuing single-process")


def get_rank() -> int:
    """Process index, 0 when not distributed (reference dist_util.py:92-95)."""
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def get_world_size() -> int:
    """Process count, 1 when not distributed (reference dist_util.py:98-101)."""
    try:
        import jax
        return jax.process_count()
    except Exception:
        return 1


def barrier(name: str = "barrier") -> None:
    """Cross-host sync; no-op single-process (reference dist_util.py:104-106)."""
    import jax
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)


def dev() -> Any:
    """The local accelerator device (reference dist_util.py:109-115 returns
    ``cuda:{LOCAL_RANK}`` or cpu; JAX's per-process addressable device plays
    that role)."""
    import jax
    return jax.local_devices()[0]


def device_count() -> int:
    import jax
    return jax.device_count()


def broadcast(tree: Any) -> Any:
    """Broadcast a pytree from process 0 to all (reference dist_util.py:127-138).
    Identity when single-process."""
    import jax
    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils
    return multihost_utils.broadcast_one_to_all(tree)


def sync_params(params: Any) -> Any:
    """Make all hosts agree on parameters by broadcasting process 0's copy
    (reference dist_util.py:141-152 does per-tensor broadcast; a single pytree
    broadcast is the JAX equivalent)."""
    return broadcast(params)


def find_free_port() -> int:
    """(reference dist_util.py:155-159)"""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("", 0))
        return s.getsockname()[1]
    finally:
        s.close()
