from . import dist
from .dist import (
    barrier,
    broadcast,
    dev,
    device_count,
    find_free_port,
    get_rank,
    get_world_size,
    is_available,
    is_initialized,
    setup_dist,
    sync_params,
)
from .launcher import parse_and_autorun, parse_distributed_args
from .mesh import AXES, batch_spec, make_mesh, resolve_axis_sizes
from . import partition
from .partition import (
    DIFFUSEQ_RULES,
    GPT2_RULES,
    MOE_RULES,
    make_shard_and_gather_fns,
    match_partition_rules,
    parse_partition_rules,
    resolve_shardings,
    rules_for_workload,
    zero1_shardings,
)
