from . import dist
from .dist import (
    barrier,
    broadcast,
    dev,
    device_count,
    find_free_port,
    get_rank,
    get_world_size,
    is_available,
    is_initialized,
    setup_dist,
    sync_params,
)
from .launcher import parse_and_autorun, parse_distributed_args
from .mesh import AXES, batch_spec, make_mesh, resolve_axis_sizes
