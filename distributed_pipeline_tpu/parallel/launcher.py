"""One-flag distributed launcher.

Capability parity with the reference's self-relaunching elastic launcher
(``/root/reference/basic_utils/dist_run.py``): any script gains a
``--distributed`` flag plus launcher knobs; launcher args are split from
script args (dist_run.py:217-255); the reconstructed command line is echoed
(dist_run.py:36-44); spawned children detect the relaunch through an env flag
(dist_run.py:312-318).

TPU-native redesign rather than translation: torchrun re-execs N processes per
node because torch wants one process per GPU. JAX is **one process per host**
(all local chips addressable), so on a real TPU slice there is nothing to
spawn — ``--distributed`` validates/derives the ``jax.distributed`` coordinator
settings and continues in-process, printing the per-host command line for the
other hosts. For development without a pod, ``--nprocs N`` spawns N local
worker processes that form a real ``jax.distributed`` ring over loopback
(each worker restricted to CPU devices) — the stand-in for torchrun's
``--standalone`` local rendezvous (dist_run.py:115-122).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..chaos import goodput as goodput_lib
from ..obs import trace as trace_lib
from .dist import AUTORUN_ENV_FLAG, find_free_port, is_available

__all__ = [
    "create_distributed_parser",
    "parse_distributed_args",
    "run_argv_as_distributed",
    "parse_and_autorun",
    "get_main_modname",
    "parse_capacity_schedule",
    "FORCE_NPROCS_ENV",
    "FORCE_DEVICES_ENV",
]


def create_distributed_parser() -> argparse.ArgumentParser:
    """Launcher-only args (mirror of reference dist_run.py:57-214, reshaped
    for the one-process-per-host JAX model)."""
    # allow_abbrev=False: parse_known_args must not steal prefix-abbreviated
    # SCRIPT flags (e.g. a wrapped script's --proc would otherwise be consumed
    # as --process_id).
    p = argparse.ArgumentParser(add_help=False, allow_abbrev=False)
    p.add_argument("--distributed", action="store_true",
                   help="launch/join a multi-process run")
    p.add_argument("--coordinator_address", default=None,
                   help="host:port of process 0 (like torchrun --master_addr/port)")
    p.add_argument("--num_processes", type=int, default=None,
                   help="total number of host processes")
    p.add_argument("--process_id", type=int, default=None,
                   help="this host's process index (like --node_rank)")
    p.add_argument("--nprocs", type=int, default=0,
                   help="spawn N local CPU worker processes (dev-mode stand-in "
                        "for torchrun --standalone)")
    p.add_argument("--devices_per_proc", type=int, default=2,
                   help="fake CPU devices per spawned local worker")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="restart-rate budget: respawn the worker ring after "
                        "a failure, at most this many times per sliding "
                        "--restart_window_s window (not a lifetime counter "
                        "— a week-long spot-capacity run may restart "
                        "hundreds of times, just not in a tight loop); "
                        "checkpoint auto-resume continues the run "
                        "(reference dist_run.py:123-129)")
    p.add_argument("--restart_window_s", type=float, default=3600.0,
                   help="sliding window (seconds) the --max_restarts budget "
                        "applies to; restarts older than this no longer "
                        "count against the budget. <= 0 restores lifetime "
                        "counting")
    p.add_argument("--restart_backoff_s", type=float, default=1.0,
                   help="base seconds of exponential backoff between "
                        "restart attempts (doubles per consecutive "
                        "failure, capped by --restart_backoff_max_s; "
                        "0 disables). A crashing dependency gets breathing "
                        "room instead of a spawn storm")
    p.add_argument("--restart_backoff_max_s", type=float, default=30.0,
                   help="cap on the exponential restart backoff")
    p.add_argument("--monitor_interval", type=float, default=0.2,
                   help="seconds between worker liveness polls (reference "
                        "dist_run.py:130-136; default is snappier than "
                        "torchrun's 5s — these are local dev workers)")
    p.add_argument("--hang_timeout_s", type=float, default=0.0,
                   help="hang watchdog: kill the worker ring when NO rank's "
                        "progress beacon advances for this many seconds "
                        "(a wedged collective / network stall never exits, "
                        "so liveness polling alone would burn wall time "
                        "forever); the killed window books as 'hang' in the "
                        "goodput fold and the normal restart machinery "
                        "resumes from the last checkpoint. Arms after the "
                        "attempt's FIRST beacon (startup/compile time is "
                        "not a hang); must exceed the slowest legitimate "
                        "step+save interval. 0 disables")
    p.add_argument("--hang_startup_timeout_s", type=float, default=0.0,
                   help="optional pre-first-beacon watchdog: kill an "
                        "attempt that produced NO beacon at all within this "
                        "many seconds of spawn (a worker wedged during "
                        "init/restore). Size it above worst-case "
                        "interpreter+compile+restore startup. 0 disables")
    p.add_argument("--log_dir", default="",
                   help="capture each spawned worker's stdout+stderr to "
                        "{log_dir}/worker_{i}.log (torchrun --log_dir/-r "
                        "redirects, dist_run.py:163-189); restarts append")
    p.add_argument("--log_tee", action="store_true",
                   help="with --log_dir: ALSO stream each worker's output "
                        "to this console, '[worker N]'-prefixed (torchrun "
                        "-t tee, dist_run.py:180-189)")
    return p


def parse_distributed_args(
    parser: argparse.ArgumentParser,
    argv: Optional[Sequence[str]] = None,
) -> Tuple[argparse.Namespace, List[str]]:
    """Split argv into (launcher namespace, remaining script argv)
    (reference dist_run.py:217-255). The script parser's help is augmented so
    ``--help`` documents both arg sets."""
    argv = list(sys.argv[1:] if argv is None else argv)
    dist_parser = create_distributed_parser()
    dist_ns, rest = dist_parser.parse_known_args(argv)
    # Surface launcher options in the script parser's help, like the
    # reference's usage/epilog injection (dist_run.py:227-247).
    epilog = ("launcher options: --distributed "
              "[--coordinator_address H:P] [--num_processes N] "
              "[--process_id I] [--nprocs N] [--devices_per_proc K] "
              "[--max_restarts R] [--restart_window_s S] "
              "[--restart_backoff_s S] [--restart_backoff_max_s S] "
              "[--monitor_interval S] [--hang_timeout_s S] "
              "[--hang_startup_timeout_s S] [--log_dir DIR] [--log_tee]")
    if epilog not in (parser.epilog or ""):
        parser.epilog = ((parser.epilog or "") + "\n\n" + epilog)
    return dist_ns, rest


def get_main_modname() -> Optional[str]:
    """Module name of the running ``__main__`` so children can be relaunched
    with ``-m`` (reference walks the frame stack, dist_run.py:258-282; the
    module spec carries the same information)."""
    main = sys.modules.get("__main__")
    spec = getattr(main, "__spec__", None)
    if spec is not None and spec.name:
        name = spec.name
        return name[:-len(".__main__")] if name.endswith(".__main__") else name
    return None


def _tee_pump(proc, sink, prefix: str):
    """Daemon thread streaming one worker's piped output to BOTH its log
    file and this console (torchrun -t tee semantics, dist_run.py:180-189).
    Returns the thread (joined before the log file closes)."""
    import threading

    def pump():
        echo = True
        for line in iter(proc.stdout.readline, b""):
            # the log file ALWAYS gets the line; a broken console (closed
            # stream, reader exited under a pipe) only disables the echo —
            # stopping the pump would deadlock the worker on a full pipe
            sink.write(line)
            sink.flush()
            if echo:
                try:
                    sys.stdout.write(
                        f"{prefix} {line.decode(errors='replace')}")
                    sys.stdout.flush()
                except (ValueError, OSError):
                    echo = False

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    return t


def _worker_env(i: int, nprocs: int, coord: str, devices_per_proc: int,
                run_timestamp: Optional[str] = None,
                cache_dir: str = "",
                extra_env: Optional[dict] = None,
                platform: str = "cpu") -> dict:
    """Environment for spawned worker ``i`` — the ring coordinates plus the
    persistent-compilation-cache propagation: every worker (and every
    restart attempt) points at the SAME cache dir, so only the first ring
    member to reach a given computation pays its XLA compile; siblings and
    respawned attempts hit the on-disk cache.

    ``platform`` pins the worker's jax backend. The default ("cpu") is
    the dev-ring contract this launcher has always had; the serving fleet
    passes the parent's platform through so TPU replicas are possible
    (ISSUE 13 satellite — the old unconditional cpu pin made fleet
    replicas CPU-only forever). Empty string = no pin at all: the worker
    inherits whatever platform selection the caller's environment
    carries. The fake-device forcing and the remote-plugin disable only
    apply to cpu-pinned workers — they exist to protect dev rings, not to
    cripple real hardware."""
    env = dict(os.environ)
    if run_timestamp:
        env["DPT_RUN_TIMESTAMP"] = run_timestamp
    if cache_dir:
        env["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    # Steady-state-throughput knobs ride the env too (run/train.py checks
    # DPT_PREFETCH_DEPTH / DPT_DISPATCH_LAG before its flags): inherited
    # from this process's environ above, so a launcher-level override
    # reaches every worker of every restart attempt — the one channel a
    # --config_json ring (which rejects individual CLI flags) can be
    # A/B'd through without minting a new config file.
    env.update({
        AUTORUN_ENV_FLAG: "1",
        "JAX_COORDINATOR_ADDRESS": coord,
        "JAX_NUM_PROCESSES": str(nprocs),
        "JAX_PROCESS_INDEX": str(i),
    })
    if platform:
        env["JAX_PLATFORMS"] = platform
    if platform == "cpu":
        env.update({
            # Disable any site-installed remote-accelerator plugin for
            # dev-mode CPU workers (a registered plugin may override the
            # platform selection and grab single-tenant hardware).
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": (env_flags := env.get("XLA_FLAGS", ""))
            + (" " if env_flags else "")
            + f"--xla_force_host_platform_device_count="
              f"{devices_per_proc}",
        })
    # Supervision channel (restart accounting): DPT_ATTEMPT / DPT_SPAWN_T /
    # DPT_RUN_DIR_FILE ride here — launcher-owned keys win over anything
    # inherited from the caller's environ.
    env.update(extra_env or {})
    return env


# Per-attempt capacity override schedules (elastic-topology simulation):
# comma-separated ints indexed by attempt, clamped to the last entry —
# "2,1" means attempt 0 gets 2, every later attempt gets 1. On a real
# fleet, surviving capacity comes from the scheduler/instance metadata;
# on this box's single-host dev rings the env IS the capacity probe, so
# shrink/grow restarts are reproducible in tests and bench legs.
FORCE_NPROCS_ENV = "DPT_FORCE_NPROCS"
FORCE_DEVICES_ENV = "DPT_FORCE_DEVICES_PER_PROC"


def parse_capacity_schedule(text: str) -> Optional[List[int]]:
    """``"2,1"`` -> [2, 1]; empty/unset -> None. Raises on malformed or
    non-positive entries — a silently-ignored capacity override would run
    the wrong topology without anyone noticing."""
    if not text:
        return None
    out = []
    for tok in text.split(","):
        tok = tok.strip()
        if not tok.isdigit() or int(tok) < 1:
            raise ValueError(
                f"capacity schedule entries must be positive ints, got "
                f"{tok!r} in {text!r}")
        out.append(int(tok))
    return out


def _capacity_at(schedule: Optional[List[int]], attempt: int,
                 default: int) -> int:
    if not schedule:
        return default
    return schedule[min(attempt, len(schedule) - 1)]


def _beacon_mtimes(run_dir_file: str) -> Optional[Dict[str, float]]:
    """mtime per progress beacon in the run dir named by the handshake
    file, or None when the dir (or any beacon) isn't known yet. mtime is
    the liveness signal: the trainer atomically replaces each rank's
    beacon every optimizer step, so a frozen newest-mtime means NO rank
    is advancing — the hang signature (a straggler still advances, just
    slowly)."""
    try:
        with open(run_dir_file) as f:
            run_dir = f.read().strip()
    except OSError:
        return None
    if not run_dir or not os.path.isdir(run_dir):
        return None
    # the beacon naming (and the stat walk) is owned by chaos.goodput —
    # one source of truth for what counts as a progress beacon
    return goodput_lib.beacon_mtimes(run_dir) or None


def _run_worker_ring(cmd_base: List[str], nprocs: int, devices_per_proc: int,
                     monitor_interval: float,
                     run_timestamp: Optional[str] = None,
                     log_dir: str = "", log_tee: bool = False,
                     cache_dir: str = "", attempt: int = 0,
                     extra_env: Optional[dict] = None,
                     hang_timeout_s: float = 0.0,
                     hang_startup_timeout_s: float = 0.0,
                     run_dir_file: str = "",
                     status: Optional[dict] = None,
                     tag: str = "", platform: str = "cpu") -> int:
    """One attempt: spawn the ring, poll liveness, fail fast on any death.

    A worker that dies (e.g. on an import error before joining the ring)
    would leave its siblings blocked in jax.distributed.initialize forever —
    terminate them instead (torchrun's elastic agent behavior). Returns the
    max worker exit code.

    HANG WATCHDOG (``hang_timeout_s > 0``): liveness polling only catches
    workers that EXIT; the nastiest production failures are workers that
    wedge (a stuck collective, a network stall) and burn wall time without
    ever dying. The per-step progress beacons double as the liveness
    signal: once this attempt writes its first beacon the watchdog arms,
    and if no rank's beacon advances for ``hang_timeout_s`` the whole ring
    is SIGKILLed (every worker — the TrainLoop has no child processes, so
    killing each pid takes the whole ring down) and supervision treats it
    like any other dead attempt: restart, resume from the last checkpoint.
    ``status`` (a caller-provided dict) receives ``hung``/``hang_s``/
    ``hang_kind`` so the attempt record can book the wasted window to the
    ``hang`` goodput category. ``hang_startup_timeout_s`` optionally also
    bounds the pre-first-beacon window (a worker wedged in init/restore).
    """
    port = find_free_port()
    coord = f"127.0.0.1:{port}"
    label = f"[launcher{' ' + tag if tag else ''}]"
    print(f"{label} attempt {attempt}: spawning {nprocs} local workers, "
          f"coordinator {coord}")
    print(f"{label} worker cmd: {' '.join(cmd_base)}")  # cmdline echo,
    # like reference dist_run.py:36-44
    logs = []
    tee_threads = []
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        mode = "tee'd to console and" if log_tee else "->"
        print(f"{label} per-worker output {mode} "
              f"{log_dir}/worker_N.log")
    procs = []
    # The spawn loop sits INSIDE the try: if opening worker k's log or its
    # Popen raises (OSError mid-loop), the finally still closes every
    # already-opened log and the except path below terminates every
    # already-spawned worker instead of leaking them (r4 advisor).
    codes: List[Optional[int]] = []
    try:
        for i in range(nprocs):
            env = _worker_env(i, nprocs, coord, devices_per_proc,
                              run_timestamp, cache_dir, extra_env=extra_env,
                              platform=platform)
            if log_dir:
                # append: a restarted ring continues the same files (the
                # attempt boundary is visible from the launcher's own log)
                f = open(os.path.join(log_dir, f"worker_{i}.log"), "ab")
                # Attempt header: respawned rings append to the same file,
                # so without a boundary line the interleaved output of N
                # attempts is unattributable when debugging a crash loop.
                f.write(f"[launcher] attempt {attempt}\n".encode())
                f.flush()
                logs.append(f)
                if log_tee:
                    # pipe through a pump thread: file AND console get
                    # every line (reference -t tee, dist_run.py:180-189)
                    proc = subprocess.Popen(cmd_base, env=env,
                                            stdout=subprocess.PIPE,
                                            stderr=subprocess.STDOUT)
                    tee_threads.append(_tee_pump(proc, f, f"[worker {i}]"))
                    procs.append(proc)
                else:
                    procs.append(subprocess.Popen(
                        cmd_base, env=env, stdout=f,
                        stderr=subprocess.STDOUT))
            else:
                procs.append(subprocess.Popen(cmd_base, env=env))
        codes = [None] * len(procs)
        # Hang-watchdog state: armed by this attempt's first beacon write
        # (beacon mtime >= spawn wall-clock — earlier attempts' stale
        # beacons never arm it), re-anchored by every later advance.
        t_spawn_wall = time.time()
        t_start = time.monotonic()
        hang_armed = False
        last_advance = t_start
        last_max_mtime = 0.0
        next_hang_poll = 0.0
        watch = hang_timeout_s > 0 or hang_startup_timeout_s > 0
        while any(c is None for c in codes):
            for i, p in enumerate(procs):
                if codes[i] is None:
                    codes[i] = p.poll()
            failed = [i for i, c in enumerate(codes) if c not in (None, 0)]
            if failed:
                print(f"{label} worker(s) {failed} exited with "
                      f"{[codes[i] for i in failed]}; terminating remaining workers")
                for i, p in enumerate(procs):
                    if codes[i] is None:
                        p.terminate()
                for i, p in enumerate(procs):
                    if codes[i] is None:
                        try:
                            codes[i] = p.wait(timeout=10)
                        except subprocess.TimeoutExpired:
                            p.kill()
                            codes[i] = p.wait()
                break
            now = time.monotonic()
            if watch and run_dir_file and now >= next_hang_poll:
                # beacon stat()s are cheap but not free: throttle the
                # probe independently of the (snappier) liveness poll
                next_hang_poll = now + max(monitor_interval, 0.1)
                mtimes = _beacon_mtimes(run_dir_file)
                mx = max(mtimes.values()) if mtimes else 0.0
                if mx > last_max_mtime:
                    last_max_mtime = mx
                    if mx >= t_spawn_wall - 1e-3:  # THIS attempt's write
                        hang_armed = True
                        last_advance = now
                hung_kind = ""
                if hang_armed and hang_timeout_s > 0 \
                        and now - last_advance > hang_timeout_s:
                    hung_kind = "stall"
                elif not hang_armed and hang_startup_timeout_s > 0 \
                        and now - t_start > hang_startup_timeout_s:
                    hung_kind = "startup"
                if hung_kind:
                    hang_s = now - (last_advance if hang_armed else t_start)
                    print(f"{label} hang watchdog: no rank advanced for "
                          f"{hang_s:.1f}s "
                          f"({'no first beacon' if hung_kind == 'startup' else 'beacons frozen'}); "
                          f"SIGKILLing the worker ring")
                    if status is not None:
                        status.update({"hung": True,
                                       "hang_s": round(hang_s, 3),
                                       "hang_kind": hung_kind})
                    for i, p in enumerate(procs):
                        if codes[i] is None:
                            try:
                                p.send_signal(signal.SIGKILL)
                            except OSError:
                                pass  # died between poll and kill
                    for i, p in enumerate(procs):
                        if codes[i] is None:
                            codes[i] = p.wait()
                    break
            time.sleep(max(monitor_interval, 0.02))
    except BaseException:
        # KeyboardInterrupt or a spawn-phase failure: nothing supervises
        # the ring anymore — tear it down rather than leak workers.
        for p in procs:
            if p.poll() is None:
                p.terminate()
        raise
    finally:
        for t in tee_threads:
            t.join(timeout=5)  # drain piped output before closing files
        for f in logs:
            f.close()
    # Any nonzero code fails the attempt — max() would mask a signal-killed
    # worker (negative returncode) behind a sibling's clean 0.
    return next((c for c in codes if c not in (None, 0)), 0)


class _RestartBudget:
    """R restarts per sliding window, not a lifetime counter: a week-long
    spot-capacity run legitimately restarts hundreds of times — what must
    be stopped is a tight crash loop. ``window_s <= 0`` restores lifetime
    counting (every restart counts forever)."""

    def __init__(self, max_restarts: int, window_s: float,
                 now=time.monotonic) -> None:
        self.max_restarts = max_restarts
        self.window_s = window_s
        self._now = now
        self._stamps: List[float] = []

    def spent(self) -> int:
        if self.window_s > 0:
            cutoff = self._now() - self.window_s
            self._stamps = [t for t in self._stamps if t >= cutoff]
        return len(self._stamps)

    def allows_restart(self) -> bool:
        return self.spent() < self.max_restarts

    def charge(self) -> None:
        self._stamps.append(self._now())


def _crash_looping(records: List[dict]) -> bool:
    """Two consecutive FAILED attempts with zero step progress: the run is
    not recovering, it is burning restarts — stop now rather than when the
    budget runs out. Attempts whose progress is unknown (no beacons: the
    wrapped script is not a TrainLoop run) never trigger this."""
    if len(records) < 2:
        return False
    for rec in records[-2:]:
        if rec.get("rc", 1) == 0 or rec.get("steps") is None \
                or rec.get("steps", 0) > 0:
            return False
    return True


def _harvest_attempt(run_dir_file: str, attempt: int, rc: int,
                     t_spawn: float, t_exit: float, prev_t_exit: float,
                     prev_max_step: Optional[int],
                     ring_status: Optional[dict] = None,
                     nprocs: Optional[int] = None,
                     devices_per_proc: Optional[int] = None
                     ) -> Tuple[dict, Optional[str]]:
    """Build the structured per-attempt record and locate the run dir.

    The run dir is learned through a handshake file the workers write
    (run/train.py / TrainLoop stamp their resolved checkpoint dir into
    ``DPT_RUN_DIR_FILE``) — the launcher cannot re-derive it without
    duplicating the script's dir logic. Step progress and the post-mortem
    goodput snapshot come from the per-rank beacons in that dir."""
    run_dir: Optional[str] = None
    try:
        with open(run_dir_file) as f:
            run_dir = f.read().strip() or None
    except OSError:
        run_dir = None
    end_step: Optional[int] = None
    start_step = prev_max_step
    beacon_goodput = None
    serving_snap = None
    stage = None
    resume_overhead = None
    recompiles = steady_recompiles = None
    if run_dir and os.path.isdir(run_dir):
        beacons = goodput_lib.read_beacons(run_dir)
        ours = {r: b for r, b in beacons.items()
                if int(b.get("attempt", 0)) == attempt}
        if beacons and not ours:
            # The run IS beacon-capable (earlier attempts reported), but
            # this attempt died before its first step — that is zero
            # progress, not unknown progress: the crash-loop fail-fast
            # must see it (an attempt that cannot even restore would
            # otherwise burn the whole restart budget).
            end_step = prev_max_step or 0
        if ours:
            end_step = max(int(b.get("step", 0)) for b in ours.values())
            # Progress is measured against the step THIS attempt restored
            # from (the beacon's start_step), not the run's high-water
            # mark: after a walk-back past a corrupt checkpoint an attempt
            # legitimately advances below the old maximum, and calling
            # that zero progress would let the crash-loop fail-fast kill
            # a recovering run.
            starts = [int(b["start_step"]) for b in ours.values()
                      if b.get("start_step") is not None]
            if starts:
                start_step = min(starts)
            # rank 0's beacon carries the attempt's goodput snapshot (the
            # flight recorder aggregate_run falls back to when the attempt
            # died before writing its clean-exit sidecar)
            b0 = ours.get(0) or next(iter(ours.values()))
            beacon_goodput = b0.get("goodput")
            # serving replicas beacon a `serving` snapshot instead of a
            # training goodput one — harvest it the same way, so a killed
            # replica attempt keeps its flight recorder (aggregate_serving
            # falls back to it when no clean-exit sidecar exists)
            snap = b0.get("serving")
            serving_snap = snap if isinstance(snap, dict) else None
            # MPMD stage workers stamp their stage id into every beacon:
            # carried into the attempt record so per-stage rings'
            # attempts.jsonl rows are attributable after the run
            stage = b0.get("stage")
            recompiles = b0.get("recompile_count")
            steady_recompiles = b0.get("steady_recompile_count")
            if isinstance(beacon_goodput, dict):
                resume_overhead = (beacon_goodput.get("startup_s", 0.0)
                                   + beacon_goodput.get("restore_s", 0.0)
                                   + beacon_goodput.get("compile_s", 0.0))
    steps = (None if end_step is None
             else max(0, end_step - (start_step or 0)))
    record = {
        "attempt": attempt,
        "rc": rc,
        "t_spawn": round(t_spawn, 3),
        "t_exit": round(t_exit, 3),
        "duration_s": round(t_exit - t_spawn, 3),
        "downtime_s": round(max(0.0, t_spawn - prev_t_exit), 3)
        if prev_t_exit else 0.0,
        "start_step": start_step,
        "end_step": end_step,
        "steps": steps,
        "resume_overhead_s": (round(resume_overhead, 3)
                              if resume_overhead is not None else None),
        "recompile_count": recompiles,
        "steady_recompile_count": steady_recompiles,
        "goodput": beacon_goodput,
    }
    if serving_snap is not None:
        record["serving"] = serving_snap
    if stage is not None:
        record["stage"] = stage
    if nprocs is not None:
        # The attempt's actual topology (elastic runs shrink/grow between
        # attempts): what aggregate/debug tooling needs to attribute a
        # resume to the capacity it ran at.
        record["nprocs"] = nprocs
        record["devices_per_proc"] = devices_per_proc
    if ring_status and ring_status.get("hung"):
        # Watchdog kill: the frozen window is measured, bounded waste —
        # its own goodput category (hang), not anonymous lost time.
        record["hung"] = True
        record["hang_s"] = ring_status.get("hang_s", 0.0)
        record["hang_kind"] = ring_status.get("hang_kind", "stall")
    return record, run_dir


def run_argv_as_distributed(modname: str, script_argv: Sequence[str],
                            nprocs: int, devices_per_proc: int = 2,
                            max_restarts: int = 0,
                            monitor_interval: float = 0.2,
                            log_dir: str = "", log_tee: bool = False,
                            cache_dir: Optional[str] = None,
                            restart_window_s: float = 3600.0,
                            restart_backoff_s: float = 1.0,
                            restart_backoff_max_s: float = 30.0,
                            hang_timeout_s: float = 0.0,
                            hang_startup_timeout_s: float = 0.0,
                            extra_env: Optional[dict] = None,
                            tag: str = "",
                            worker_platform: str = "cpu") -> int:
    """Spawn ``nprocs`` local worker processes forming a jax.distributed ring
    over loopback (dev-mode multi-process, one CPU backend per worker).

    Restart supervision (reference torch.elastic via ``--max_restarts``,
    dist_run.py:123-136 + SURVEY.md §5.3 recovery story), hardened for
    chaos (ISSUE 8): when the ring dies, the whole ring is respawned on a
    fresh coordinator port and workers resume from the newest restorable
    checkpoint in their run dir. Between attempts the launcher

    * sleeps an EXPONENTIAL BACKOFF (``restart_backoff_s`` doubling per
      consecutive failure up to ``restart_backoff_max_s``),
    * charges a RESTART-RATE BUDGET (``max_restarts`` per sliding
      ``restart_window_s`` window — not a lifetime counter),
    * FAILS FAST on a crash loop (two consecutive attempts with zero step
      progress stop the run: restarts are not fixing anything),
    * RE-DERIVES CAPACITY (elastic topology, ISSUE 10): each attempt's
      worker count / fake-device count comes from the surviving capacity
      — on this box simulated by the ``DPT_FORCE_NPROCS`` /
      ``DPT_FORCE_DEVICES_PER_PROC`` per-attempt schedules ("2,1" =
      attempt 0 at 2, later attempts at 1) — so a run killed at dp=N
      resumes at dp=M and the elastic checkpoint/data machinery reshapes
      it (run/train.py re-derives mesh dims and fast-forwards the data
      stream by global samples consumed), and
    * appends a structured record to ``attempts.jsonl`` in the run dir
      (attempt, rc, duration, step progress, downtime, resume overhead,
      topology, hang window, post-mortem goodput snapshot) so every
      second of the run stays attributable (chaos.goodput.aggregate_run).

    ``hang_timeout_s`` arms the per-attempt HANG WATCHDOG (see
    :func:`_run_worker_ring`): silently wedged attempts are killed and
    restarted instead of burning the budgeted wall time forever.

    ``extra_env`` reaches every worker of every attempt (launcher-owned
    keys — DPT_ATTEMPT, ring coordinates, DPT_RUN_DIR_FILE — always win);
    ``worker_platform`` pins the workers' jax backend ("cpu", the
    historical dev-ring default; "" = inherit the environment — how the
    serving fleet runs TPU replicas, see :func:`_worker_env`);
    ``tag`` prefixes this supervisor's log lines, so N rings supervised
    concurrently from one process (the serving fleet runs one per
    replica, in threads) stay attributable. This function is
    thread-safe: all state is local, and the per-ring run-dir handshake
    file is a fresh tempfile per call.

    Reference equivalent: in-process ``torch.distributed.run.run``
    (dist_run.py:13-54). Returns the final attempt's max worker exit code.
    """
    cmd_base = [sys.executable, "-m", modname, *script_argv]
    # Pin the run timestamp ONCE for all attempts: run/train.py derives its
    # auto-generated run dir from DPT_RUN_TIMESTAMP when set, so a respawned
    # ring lands in the SAME directory and checkpoint auto-resume actually
    # resumes (without this, each attempt would mint a fresh timestamped dir
    # and silently restart from step 0). Also removes the latent race where
    # workers spawned across a second boundary disagree on the dir name.
    # Passed to the WORKERS' env only — mutating this process's environ
    # would leak the timestamp into a second launch from the same process,
    # silently resuming run 2 from run 1's checkpoints.
    run_timestamp = os.environ.get("DPT_RUN_TIMESTAMP") or time.strftime(
        "%Y%m%d-%H%M%S")
    # Compilation-cache propagation: an explicit cache_dir (or one already
    # exported by enable_persistent_compilation_cache in this process) is
    # shipped to every worker of every attempt, so ring restarts — the
    # elastic-recovery path — resume without paying the model compile again.
    # (Workers running run/train.py with the default '--compilation_cache_dir
    # auto' additionally converge on <run_dir>/compile_cache by themselves,
    # since DPT_RUN_TIMESTAMP pins one shared run dir.)
    if cache_dir is None:
        cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR", "")
    budget = _RestartBudget(max_restarts, restart_window_s)
    # Elastic capacity schedules (shrink/grow simulation): per-attempt
    # worker/device counts override the flags; parsed ONCE so a malformed
    # override fails the launch, not attempt 3.
    nprocs_sched = parse_capacity_schedule(
        os.environ.get(FORCE_NPROCS_ENV, ""))
    devices_sched = parse_capacity_schedule(
        os.environ.get(FORCE_DEVICES_ENV, ""))
    fd, run_dir_file = tempfile.mkstemp(prefix="dpt_run_dir_")
    os.close(fd)
    label = f"[launcher{' ' + tag if tag else ''}]"
    records: List[dict] = []
    attempt = 0
    consecutive_failures = 0
    prev_t_exit = 0.0
    prev_max_step: Optional[int] = None
    # Supervision trace (obs/, armed by DPT_TRACE): attempt spans, backoff
    # windows, and watchdog kills land in trace_launcher*.jsonl in the run
    # dir — created lazily once the worker handshake reveals the dir.
    tracer: Any = trace_lib.NULL
    try:
        while True:
            t_spawn = time.time()
            nprocs_a = _capacity_at(nprocs_sched, attempt, nprocs)
            devices_a = _capacity_at(devices_sched, attempt,
                                     devices_per_proc)
            if nprocs_a != nprocs or devices_a != devices_per_proc:
                print(f"{label} attempt {attempt}: capacity override "
                      f"-> {nprocs_a} worker(s) x {devices_a} device(s) "
                      f"(was {nprocs} x {devices_per_proc})")
            ring_status: dict = {}
            code = _run_worker_ring(
                cmd_base, nprocs_a, devices_a, monitor_interval,
                run_timestamp, log_dir=log_dir, log_tee=log_tee,
                cache_dir=cache_dir, attempt=attempt,
                extra_env={**(extra_env or {}),
                           "DPT_ATTEMPT": str(attempt),
                           "DPT_SPAWN_T": repr(t_spawn),
                           "DPT_RUN_DIR_FILE": run_dir_file},
                hang_timeout_s=hang_timeout_s,
                hang_startup_timeout_s=hang_startup_timeout_s,
                run_dir_file=run_dir_file,
                status=ring_status, tag=tag, platform=worker_platform)
            t_exit = time.time()
            record, run_dir = _harvest_attempt(
                run_dir_file, attempt, code, t_spawn, t_exit, prev_t_exit,
                prev_max_step, ring_status=ring_status,
                nprocs=nprocs_a, devices_per_proc=devices_a)
            records.append(record)
            if run_dir and os.path.isdir(run_dir):
                try:
                    goodput_lib.append_attempt(run_dir, record)
                except OSError as e:
                    print(f"{label} attempts.jsonl write failed: {e}")
                if tracer is trace_lib.NULL:
                    tracer = trace_lib.tracer_for(
                        run_dir, f"launcher_{tag}" if tag else "launcher")
            if tracer.enabled:
                tracer.complete(
                    f"attempt {attempt}", "supervise", t_spawn,
                    t_exit - t_spawn,
                    args={"rc": code, "steps": record["steps"],
                          "nprocs": nprocs_a,
                          "devices_per_proc": devices_a})
                if ring_status.get("hung"):
                    tracer.instant(
                        "watchdog_kill", "supervise", t=t_exit,
                        args={"hang_s": ring_status.get("hang_s"),
                              "kind": ring_status.get("hang_kind")})
            prev_t_exit = t_exit
            if record["end_step"] is not None:
                prev_max_step = max(prev_max_step or 0, record["end_step"])
            if code == 0:
                return 0
            # "Consecutive" failures reset when an attempt made real step
            # progress: a preemption after hours of healthy training is
            # not a tightening crash loop, and must not inherit the
            # accumulated backoff of unrelated failures days earlier.
            if (record["steps"] or 0) > 0:
                consecutive_failures = 1
            else:
                consecutive_failures += 1
            if _crash_looping(records):
                print(f"{label} crash loop: last 2 attempts made zero "
                      f"step progress (rc={code}); failing fast instead of "
                      f"burning {max_restarts - budget.spent()} more "
                      f"restart(s)")
                return code
            if not budget.allows_restart():
                window = (f"in the last {restart_window_s:.0f}s"
                          if restart_window_s > 0 else "total")
                print(f"{label} ring failed (rc={code}); restart budget "
                      f"exhausted ({budget.spent()}/{max_restarts} "
                      f"{window})")
                return code
            budget.charge()
            backoff = 0.0
            if restart_backoff_s > 0:
                backoff = min(restart_backoff_max_s,
                              restart_backoff_s
                              * (2.0 ** (consecutive_failures - 1)))
            attempt += 1
            print(f"{label} ring failed (rc={code}); restart "
                  f"{budget.spent()}/{max_restarts} (window "
                  f"{restart_window_s:.0f}s), backoff {backoff:.1f}s")
            if backoff > 0:
                if tracer.enabled:
                    # booked up front: the sleep below IS the window
                    tracer.complete("backoff", "supervise", time.time(),
                                    backoff,
                                    args={"consecutive_failures":
                                          consecutive_failures})
                time.sleep(backoff)
    finally:
        tracer.close()
        try:
            os.unlink(run_dir_file)
        except OSError:
            pass


def parse_and_autorun(
    parser: argparse.ArgumentParser,
    argv: Optional[Sequence[str]] = None,
) -> Optional[argparse.Namespace]:
    """Main launcher API (reference dist_run.py:285-327).

    * ``--distributed --nprocs N``: spawn N local CPU workers running this
      same module, wait, and return None (parent exits, dist_run.py:314).
    * ``--distributed`` on a pod: set jax.distributed env from launcher args
      and fall through to run in-process (one process per host).
    * plain run / spawned child: parse script args and return the namespace;
      children (env flag set) force ``is_available`` true
      (dist_run.py:316-318) and set a descriptive proctitle when available.
    """
    dist_ns, script_argv = parse_distributed_args(parser, argv)

    # --nprocs 1 is a real (supervised) ring too: one spawned worker under
    # the launcher's restart/backoff/crash-loop machinery — the elastic
    # recovery story without cross-process collectives (which this image's
    # jax cannot run on CPU; see CHANGES r6).
    if dist_ns.distributed and dist_ns.nprocs >= 1:
        modname = get_main_modname()
        if modname is None:
            raise RuntimeError(
                "--nprocs relaunch requires running as a module (python -m ...)")
        code = run_argv_as_distributed(
            modname, script_argv, dist_ns.nprocs,
            dist_ns.devices_per_proc,
            max_restarts=dist_ns.max_restarts,
            monitor_interval=dist_ns.monitor_interval,
            log_dir=dist_ns.log_dir,
            log_tee=dist_ns.log_tee,
            restart_window_s=dist_ns.restart_window_s,
            restart_backoff_s=dist_ns.restart_backoff_s,
            restart_backoff_max_s=dist_ns.restart_backoff_max_s,
            hang_timeout_s=dist_ns.hang_timeout_s,
            hang_startup_timeout_s=dist_ns.hang_startup_timeout_s)
        sys.exit(code)

    if dist_ns.distributed:
        # Multi-host in-process path: export coordinator settings for
        # dist.setup_dist, echo the command for the other hosts.
        if dist_ns.coordinator_address:
            os.environ["JAX_COORDINATOR_ADDRESS"] = dist_ns.coordinator_address
        elif (dist_ns.num_processes and dist_ns.num_processes > 1
              and "JAX_COORDINATOR_ADDRESS" not in os.environ):
            # No address given: default to this host (assumed process 0) on a
            # fixed port, so the echoed per-host command is actually runnable
            # (torchrun's master_addr/port defaults, dist_run.py:198-213).
            import socket
            os.environ["JAX_COORDINATOR_ADDRESS"] = f"{socket.gethostname()}:12321"
        if dist_ns.num_processes:
            os.environ["JAX_NUM_PROCESSES"] = str(dist_ns.num_processes)
        if dist_ns.process_id is not None:
            os.environ["JAX_PROCESS_INDEX"] = str(dist_ns.process_id)
        os.environ[AUTORUN_ENV_FLAG] = "1"
        is_available.cache = True  # type: ignore[attr-defined]
        if dist_ns.num_processes and dist_ns.num_processes > 1:
            # All hosts must agree on the auto-generated run dir; pin the
            # timestamp here and ship it in the echoed per-host command so
            # host clocks (and re-executions after a failure) can't diverge.
            # The COORDINATOR (process 0 / unset) mints a FRESH timestamp
            # every launch — inheriting a stale one from a previous run in
            # this environment would silently resume that run's checkpoints.
            # Workers (process_id > 0) inherit the value the coordinator's
            # echoed command gave them.
            if dist_ns.process_id in (None, 0):
                os.environ["DPT_RUN_TIMESTAMP"] = time.strftime(
                    "%Y%m%d-%H%M%S")
            else:
                os.environ.setdefault("DPT_RUN_TIMESTAMP",
                                      time.strftime("%Y%m%d-%H%M%S"))
            modname = get_main_modname() or "<module>"
            print(f"[launcher] per-host command (run with --process_id i): "
                  f"DPT_RUN_TIMESTAMP={os.environ['DPT_RUN_TIMESTAMP']} "
                  f"python -m {modname} --distributed "
                  f"--coordinator_address {os.environ['JAX_COORDINATOR_ADDRESS']} "
                  f"--num_processes {dist_ns.num_processes} "
                  f"{' '.join(script_argv)}")

    if os.environ.get(AUTORUN_ENV_FLAG):
        is_available.cache = True  # type: ignore[attr-defined]
        try:  # descriptive proctitle, like reference dist_run.py:319-323
            import setproctitle  # type: ignore[import-not-found]
            setproctitle.setproctitle(
                f"dpt-worker{os.environ.get('JAX_PROCESS_INDEX', '0')}: "
                + " ".join(sys.argv))
        except ImportError:
            pass

    ns = parser.parse_args(script_argv)
    # Record the exact argv this namespace came from so downstream checks
    # (TrainSettings' --config_json exclusivity) never have to guess from the
    # hosting process's sys.argv.
    ns._parsed_argv = list(script_argv)
    return ns
