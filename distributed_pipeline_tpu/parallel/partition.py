"""Regex-rule partition engine: param-tree paths -> PartitionSpecs.

The auto-sharding layer (ROADMAP item 2). Models used to get sharded by
flax logical-axis metadata hand-mapped in ``parallel/sharding.py``; new
models therefore meant editing the engine. Here sharding is DECLARED: a
model family ships a rule table — ordered ``(regex, PartitionSpec)``
pairs matched against each parameter's tree path (the
``match_partition_rules`` / ``make_shard_and_gather_fns`` pattern of the
big public JAX LLM trainers; SNIPPETS [2]) — and the engine materializes
NamedShardings from it. ``parallel/sharding.py`` remains as a thin compat
shim (flax-logical-metadata models resolve through the same
:func:`resolve_shardings` fixer).

On top of the engine sits ZeRO-1 (Xu et al. 2020, "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training"):
:func:`zero1_shardings` extends a params-layout sharding tree so each
optimizer-state/EMA leaf is additionally sharded across the ``data`` mesh
axis. Weight-update state is only ever consumed elementwise inside the
train step, so XLA's SPMD partitioner gathers it on use (all-gather of the
updates, not of the 2x-Adam + EMA state), and per-replica weight-update
memory drops by the data-parallel factor — the refactor that unlocks
larger-model bench legs (utils/trainer.py wires it behind
``--shard_optimizer``).

Three invariants the tests pin (tests/test_partition.py):

* scalar leaves (ndim 0 or size 1) never partition, whatever the rules;
* every leaf must match a rule — tables end with an explicit catch-all
  ``(r".*", P())`` so "replicate the rest" is a decision, not an accident;
* axes whose size a dim does not divide fall back to replication at
  materialization time (:func:`fix_spec` — tiny test models shard cleanly
  on any mesh, same contract as the old hand-wired path).

ELASTIC note (ISSUE 10): :func:`zero1_shardings` is a pure function of
the CURRENT mesh — on a shrink/grow resume the new run's dp may differ
from the one the checkpoint was written at (and the chosen shard dim may
even move when divisibility changes), which is fine by construction: the
trainer hands ``restore_resume_state`` abstract targets built from the
NEW layout and orbax reshards the stored state into it, in either
direction of a ``--shard_optimizer`` flip. dp == 1 degenerates to the
param layout, so shrinking all the way to one replica is just the
trivial case of the same path.
"""

from __future__ import annotations

import json
import re
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "Rules", "match_partition_rules", "named_tree_map", "tree_path_name",
    "fix_spec", "resolve_shardings", "make_shard_and_gather_fns",
    "zero1_spec", "zero1_shardings", "parse_partition_rules",
    "rules_from_json", "rules_to_json", "load_partition_artifact",
    "rules_for_workload", "MOE_RULES", "DIFFUSEQ_RULES", "GPT2_RULES",
]

# An ordered rule table: first regex (re.search) matching a leaf's
# '/'-joined tree path wins.
Rules = Tuple[Tuple[str, P], ...]


def tree_path_name(path: Sequence[Any]) -> str:
    """A tree_flatten_with_path key path -> '/'-joined name, e.g.
    ``params/backbone/block_0/attn/qkv``."""
    parts = []
    for k in path:
        for attr in ("key", "idx", "name"):
            if hasattr(k, attr):
                parts.append(str(getattr(k, attr)))
                break
        else:
            parts.append(str(k))
    return "/".join(parts)


def named_tree_map(fn: Callable[[str, Any], Any], tree: Any) -> Any:
    """``tree_map(fn, tree)`` where ``fn`` also receives the leaf's
    '/'-joined path (the engine's matching key)."""
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return jax.tree_util.tree_unflatten(
        treedef, [fn(tree_path_name(p), x) for p, x in leaves])


def match_partition_rules(rules: Sequence[Tuple[str, P]], tree: Any) -> Any:
    """PartitionSpec pytree for ``tree`` (live arrays, ShapeDtypeStructs —
    anything with ``.shape`` leaves) according to ``rules``.

    Scalar leaves (ndim 0 or one element) are never partitioned. Every
    other leaf must match some rule: a table without an explicit catch-all
    ``(r".*", P())`` raises on the first uncovered path instead of
    silently replicating it."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(name: str, leaf: Any) -> P:
        shape = tuple(getattr(leaf, "shape", ()))
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()  # scalars never partition (snippet [2] contract)
        for pat, spec in compiled:
            if pat.search(name):
                if len(tuple(spec)) > len(shape):
                    raise ValueError(
                        f"partition rule {pat.pattern!r} has "
                        f"{len(tuple(spec))} entries but {name!r} has rank "
                        f"{len(shape)} (shape {shape})")
                return spec
        raise ValueError(
            f"no partition rule matched {name!r} — rule tables must end "
            f"with an explicit catch-all (r'.*', PartitionSpec()) so "
            f"replication is declared, not accidental")

    return named_tree_map(spec_for, tree)


def _axes_size(mesh: Mesh, entry: Any) -> int:
    axes = entry if isinstance(entry, tuple) else (entry,) if entry else ()
    return int(np.prod([mesh.shape[a] for a in axes])) if axes else 1


def fix_spec(mesh: Mesh, spec: P, shape: Tuple[int, ...]) -> P:
    """Materialization fixer: pad the spec to the leaf's rank and drop
    axes whose size the dim does not divide (fall back to replication) —
    the same contract the hand-wired path always had, so tiny test models
    shard cleanly on any mesh."""
    entries = tuple(spec) + (None,) * (len(shape) - len(tuple(spec)))
    fixed = [ax if _axes_size(mesh, ax) > 1 and dim % _axes_size(mesh, ax) == 0
             else None
             for dim, ax in zip(shape, entries)]
    return P(*fixed)


def _shape_of(leaf: Any) -> Tuple[int, ...]:
    if isinstance(leaf, (tuple, list)):
        return tuple(leaf)
    return tuple(leaf.shape)


def resolve_shardings(mesh: Mesh, specs: Any, tree: Any) -> Any:
    """PartitionSpec tree + shape-carrying tree -> NamedSharding tree,
    divisibility-fixed per leaf. ``tree`` leaves may be arrays, abstract
    values, or bare shape tuples."""
    return jax.tree_util.tree_map(
        lambda s, l: NamedSharding(mesh, fix_spec(mesh, s, _shape_of(l))),
        specs, tree)


def make_shard_and_gather_fns(mesh: Mesh, specs: Any) -> Tuple[Any, Any]:
    """Per-leaf ``(shard_fns, gather_fns)`` pytrees from a PartitionSpec
    tree (snippet [2] surface).

    ``shard_fns[leaf](x)`` places ``x`` into its rule sharding (host numpy
    or an already-device array both work — ``device_put`` reshards);
    ``gather_fns[leaf](x)`` brings a sharded leaf back fully replicated,
    the gather-on-use primitive for host-side consumers (export tooling,
    eval code that wants the whole array). Both are explicit transfers,
    legal under the sanitizer's transfer guard."""

    def make_shard(spec: P):
        def fn(x: Any) -> jax.Array:
            return jax.device_put(
                x, NamedSharding(mesh, fix_spec(mesh, spec, np.shape(x))))
        return fn

    def make_gather(spec: P):
        del spec  # gathering is spec-independent: target is replicated

        def fn(x: Any) -> jax.Array:
            return jax.device_put(x, NamedSharding(mesh, P()))
        return fn

    shard_fns = jax.tree_util.tree_map(make_shard, specs,
                                       is_leaf=lambda x: isinstance(x, P))
    gather_fns = jax.tree_util.tree_map(make_gather, specs,
                                        is_leaf=lambda x: isinstance(x, P))
    return shard_fns, gather_fns


# ------------------------------------------------------------------- ZeRO-1


def zero1_spec(mesh: Mesh, spec: P, shape: Tuple[int, ...],
               axis: str = "data") -> P:
    """Extend a (materialized) param spec so the leaf is additionally
    sharded across ``axis`` — the ZeRO-1 layout for weight-update state.

    Placement policy: the first dim the axis divides — an unsharded dim
    first, else an already-sharded dim whose per-shard size still divides
    (mixed FSDP/TP meshes). Leaves nothing divides stay as they are
    (small odd-shaped params; replicating them costs ~nothing)."""
    dp = mesh.shape[axis]
    fixed = tuple(fix_spec(mesh, spec, shape))
    if dp <= 1 or not shape:
        return P(*fixed)
    used = {a for e in fixed if e
            for a in (e if isinstance(e, tuple) else (e,))}
    if axis in used:
        # the param layout already consumes the axis (a rule table that
        # shards some dim over 'data'): the leaf is dp-sharded as-is, and
        # adding it again would build an invalid duplicate-axis spec
        return P(*fixed)
    entries = list(fixed)
    for d, ax in enumerate(entries):
        if ax is None and shape[d] % dp == 0:
            entries[d] = axis
            return P(*entries)
    for d, ax in enumerate(entries):
        if ax is None:
            continue
        if shape[d] % (_axes_size(mesh, ax) * dp) == 0:
            axes = ax if isinstance(ax, tuple) else (ax,)
            entries[d] = tuple(axes) + (axis,)
            return P(*entries)
    return P(*entries)


def zero1_shardings(mesh: Mesh, shardings: Any, tree: Any,
                    axis: str = "data") -> Any:
    """Params-layout NamedSharding tree -> ZeRO-1 NamedSharding tree:
    every leaf additionally sharded across the ``axis`` mesh axis (see
    :func:`zero1_spec`). Applied to optimizer moments and EMA copies —
    state the train step only reads/writes elementwise, so GSPMD gathers
    on use and per-replica bytes drop by ~``mesh.shape[axis]``."""
    return jax.tree_util.tree_map(
        lambda ns, l: NamedSharding(
            mesh, zero1_spec(mesh, ns.spec, _shape_of(l), axis)),
        shardings, tree)


# ------------------------------------------------------- per-model tables
#
# These tables REPRODUCE the flax-logical-metadata shardings the models
# shipped with (tests/test_partition.py pins leaf-for-leaf equivalence
# across mesh shapes), expressed as path rules so the next model declares
# a table instead of threading metadata through every self.param call.
#
# Layout legend (parallel/mesh.py axes):
#   fsdp   — ZeRO-3-style parameter sharding (every weight's "embed" dim)
#   tensor — Megatron TP pairing (wi column-, wo row-parallel; heads split)
#   expert — MoE expert-weight leading dim (GShard)
#   pipe   — stacked-layer leading dim under scan_layers (GPipe stages)

# MoE expert weights — both the named-block layout (moe/...) and the
# scan-stacked layout (blocks/moe_... with a leading layer-group dim).
MOE_RULES: Rules = (
    (r"moe/router$", P("fsdp", None)),
    (r"moe/wi$", P("expert", "fsdp", "tensor")),
    (r"moe/wo$", P("expert", "tensor", "fsdp")),
    (r"blocks/moe_router$", P("pipe", "fsdp", None)),
    (r"blocks/moe_wi$", P("pipe", "expert", "fsdp", "tensor")),
    (r"blocks/moe_wo$", P("pipe", "expert", "tensor", "fsdp")),
)

# The shared transformer trunk: named blocks (block_N/...), the
# scan-stacked dense layout (blocks/...), and the MoE-scan group layout
# (blocks/dense_* carries an extra per-group dense-layer dim, blocks/moe_*
# the attention/LN halves of MoE groups).
_BACKBONE_RULES: Rules = (
    (r"attn/qkv$", P("fsdp", None, "tensor", None)),
    (r"attn/out$", P("tensor", None, "fsdp")),
    (r"mlp/wi$", P("fsdp", "tensor")),
    (r"mlp/wo$", P("tensor", "fsdp")),
    (r"blocks/dense_qkv$", P("pipe", None, "fsdp", None, "tensor", None)),
    (r"blocks/dense_out$", P("pipe", None, "tensor", None, "fsdp")),
    (r"blocks/dense_wi$", P("pipe", None, "fsdp", "tensor")),
    (r"blocks/dense_wo$", P("pipe", None, "tensor", "fsdp")),
    (r"blocks/dense_ln\d_(scale|bias)$", P("pipe", None, None)),
    (r"blocks/(moe_)?qkv$", P("pipe", "fsdp", None, "tensor", None)),
    (r"blocks/(moe_)?out$", P("pipe", "tensor", None, "fsdp")),
    (r"blocks/(moe_)?wi$", P("pipe", "fsdp", "tensor")),
    (r"blocks/(moe_)?wo$", P("pipe", "tensor", "fsdp")),
    (r"blocks/(moe_)?ln\d_(scale|bias)$", P("pipe", None)),
)

# The embedding table shards over vocab only: tensor (Megatron
# vocab-parallel logits) + fsdp (ZeRO for the big table). Its hidden dim
# stays replicated — an fsdp-sharded hidden dim would push fsdp onto every
# [B, L, hidden] activation the table produces and fight the batch
# sharding (see models/diffuseq.py's annotation rationale).
_EMBED_RULE = (r"word_emb/embedding$", P(("tensor", "fsdp"), None))

DIFFUSEQ_RULES: Rules = MOE_RULES + _BACKBONE_RULES + (
    _EMBED_RULE,
    (r"(^|/)pos_emb$", P(None, "fsdp")),
    (r"in_proj/kernel$", P(None, "fsdp")),
    (r"out_proj/kernel$", P("fsdp", None)),
    # LN scales/biases, Dense biases, the time-embedding MLP: replicated
    (r".*", P()),
)

GPT2_RULES: Rules = MOE_RULES + _BACKBONE_RULES + (
    _EMBED_RULE,
    # pos_emb replicated (it adds directly into the activation — sharding
    # its hidden dim would fight the batch sharding, gpt2.py rationale)
    (r".*", P()),
)

_FAMILY_RULES: Dict[str, Rules] = {
    "diffuseq": DIFFUSEQ_RULES,
    "gpt2": GPT2_RULES,
}


def rules_for_workload(workload: Any) -> Optional[Rules]:
    """The rule table a workload declares (``workload.partition_rules``),
    else its family's built-in table, else None (unknown families keep the
    flax logical-metadata compat path in parallel/sharding.py)."""
    declared = getattr(workload, "partition_rules", None)
    if declared:
        return tuple(declared)
    return _FAMILY_RULES.get(getattr(workload, "family", ""))


def rules_from_json(raw: Any) -> Rules:
    """Wire-format rule list -> Rules: an ordered list of
    ``[regex, spec]`` pairs where ``spec`` is a list of entries — ``null``
    (replicate the dim), a mesh-axis name, or a list of axis names
    (several axes on one dim)."""
    rules = []
    for entry in raw:
        if not (isinstance(entry, list) and len(entry) == 2
                and isinstance(entry[0], str) and isinstance(entry[1], list)):
            raise ValueError(
                f"partition rule entries must be [regex, [spec...]] pairs, "
                f"got {entry!r}")
        pat, spec = entry
        rules.append((pat, P(*(tuple(e) if isinstance(e, list) else e
                               for e in spec))))
    return tuple(rules)


def rules_to_json(rules: Rules) -> list:
    """Rules -> the wire format :func:`rules_from_json` reads (the tuner
    artifact writer; round-trips exactly)."""
    out = []
    for pat, spec in rules:
        out.append([pat, [list(e) if isinstance(e, tuple) else e
                          for e in tuple(spec)]])
    return out


def _read_rules_body(text: str) -> str:
    """Shared ``--partition_rules`` input resolution: inline JSON,
    ``@/path.json``, or a bare file path."""
    body = text.strip()
    if body.startswith("@"):
        with open(body[1:]) as f:
            return f.read()
    if not body.startswith(("[", "{")):
        with open(body) as f:
            return f.read()
    return body


def parse_partition_rules(text: str) -> Optional[Rules]:
    """``--partition_rules`` parser: inline JSON, ``@/path.json``, or a
    bare file path. The JSON is either the ordered ``[regex, spec]`` pair
    list (:func:`rules_from_json`), e.g.
    ``[["attn/qkv$", ["fsdp", null, "tensor", null]], [".*", []]]``, or a
    TUNER ARTIFACT object (tune/search.py) whose rules ride the
    ``partition_rules`` key — so the file the auto-tuner emits is loaded
    verbatim. Returns None for empty input."""
    if not text:
        return None
    raw = json.loads(_read_rules_body(text))
    if isinstance(raw, dict):
        if "partition_rules" not in raw:
            raise ValueError(
                "a --partition_rules JSON object must carry the rule "
                "list under 'partition_rules' (the tuner artifact shape)")
        raw = raw["partition_rules"]
    return rules_from_json(raw)


def load_partition_artifact(text: str) -> Optional[Dict[str, Any]]:
    """Full ``--partition_rules`` payload including the tuner's layout
    recommendations: ``{"rules": Rules, "mesh": dict|None,
    "shard_optimizer": bool|None}``. A plain rule list (the pre-tuner
    input shape) yields mesh/shard_optimizer None; empty input None."""
    if not text:
        return None
    raw = json.loads(_read_rules_body(text))
    if isinstance(raw, dict):
        if "partition_rules" not in raw:
            raise ValueError(
                "a --partition_rules JSON object must carry the rule "
                "list under 'partition_rules' (the tuner artifact shape)")
        mesh = raw.get("mesh")
        return {
            "rules": rules_from_json(raw["partition_rules"]),
            "mesh": dict(mesh) if isinstance(mesh, dict) else None,
            "shard_optimizer": (bool(raw["shard_optimizer"])
                                if raw.get("shard_optimizer") is not None
                                else None),
        }
    return {"rules": rules_from_json(raw), "mesh": None,
            "shard_optimizer": None}
