"""distributed_pipeline_tpu — a TPU-native (JAX/XLA/pjit/pallas) training framework
with the capabilities of the reference torch.distributed pipeline scaffold.

Layer map (mirrors SURVEY.md §1, rebuilt TPU-first):
  config/    typed pydantic<->argparse<->JSON settings
  parallel/  distributed substrate: jax.distributed init, device mesh,
             sharding specs, launcher, ring attention
  utils/     trainer (single jitted train_step), logger, checkpointing, perf
  data/      host-sharded infinite data pipeline with device prefetch
  models/    DiffuSeq seq2seq diffusion + GPT-2 causal LM (flax.linen)
  ops/       pallas TPU kernels for the hot ops
  run/       CLI entry points
"""

__version__ = "0.1.0"
