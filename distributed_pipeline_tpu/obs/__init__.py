"""Observability: span tracing + timeline export + live telemetry.

The robustness stack (PRs 8-11) made failures survivable; this package
makes them *explainable*. Three layers, all import-light (no jax — the
launcher, router, and status CLI run in processes that never pay a
backend import, the same discipline as :mod:`..chaos`):

* :mod:`.trace`  — nestable spans and instant events with explicit
  (never wall-clock-defaulted) span/trace IDs, appended to per-process
  ``trace_rank{k}.jsonl`` shards in the run dir. A zero-cost no-op path
  (:data:`~.trace.NULL`) makes tracing-off free: no span objects, no
  writes, no branches beyond one attribute check.
* :mod:`.export` — folds a run (or fleet) dir's trace shards + beacons +
  ``attempts.jsonl`` + the router ``journal.jsonl`` (+ the cost ledger
  as counter tracks) into ONE Chrome-trace-event / Perfetto-loadable
  timeline (one pid per process/replica, one track per category) plus a
  Prometheus-textfile metrics snapshot.
* :mod:`.ledger` — the per-compiled-program COST LEDGER (ISSUE 14):
  XLA ``cost_analysis``/``memory_analysis`` extraction, the HLO
  collective-bytes tally, and the roofline MFU-gap attribution
  (``mfu + gap_host + gap_comms + gap_memory_bound + gap_residual == 1``
  exactly), snapshotted to ``<run_dir>/perf_ledger.json`` behind
  ``--cost_ledger`` and rendered by ``run/perf_report.py``.
* :mod:`.regress` — the bench-history regression sentinel: newest
  recorded bench run vs a trailing baseline window, per-leg verdicts on
  tokens/s / MFU / peak bytes / steady recompiles, nonzero exit on a
  past-band regression (CI-gateable).
* ``run/status.py`` — the live, read-only fleet status CLI built on the
  same readers.

Arming: set ``DPT_TRACE=1`` (rides the launcher's worker env to every
attempt of every ring) or pass ``--trace true`` to run/train.py /
run/serve.py. The trace and the goodput ledger can never disagree:
instrumented code books each span from the SAME measured seconds it
hands to :class:`~..utils.perf.GoodputTracker` / StallBreakdown /
:class:`~..serving.fleet.ServingTracker`.
"""

from . import trace

__all__ = ["trace"]
