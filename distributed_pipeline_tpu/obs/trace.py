"""Span tracing: per-process append-only JSONL trace shards.

Event model (one JSON object per line, compact keys)::

    {"ph": "X", "name": "step", "cat": "train", "t": <epoch s>,
     "dur": <seconds>, "sid": "rank0:17", "parent": "rank0:16",
     "trace": "req00000003", "args": {...}}
    {"ph": "i", "name": "watchdog_kill", "cat": "attempt", "t": ..., ...}

* ``sid`` (span id) and ``trace`` (cross-process trace id) are EXPLICIT:
  a process label plus a monotonic counter, or a caller-minted request
  id — never derived from the wall clock, so two spans can never
  collide because two events landed in the same microsecond and a
  replayed request keeps ONE identity across processes. Timestamps (not
  identity) are wall-clock on purpose: they are what lets shards from
  different processes stitch into one timeline.
* Writes are single-line atomic appends (one buffered ``write`` +
  ``flush`` per event). A SIGKILL mid-write leaves at most one torn
  tail line, which :func:`read_trace` — the one-owner JSONL reader
  contract shared with ``chaos.goodput.read_journal`` — skips.
* The OFF path is free: :data:`NULL` is a singleton whose ``span()``
  returns a shared no-op context manager and whose ``complete``/
  ``instant`` are pass statements; hot paths guard the (tiny) argument
  construction behind ``tracer.enabled``, so a disabled trace allocates
  no span objects and takes no clock readings.

Import-light (stdlib + the chaos JSONL reader only): the launcher,
router, and status CLI trace without a jax import.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional, Union

from ..chaos.goodput import read_journal as read_trace  # one-owner reader

__all__ = ["TRACE_ENV", "NULL", "NullTracer", "Stopwatch", "Tracer",
           "enabled_by_env", "read_trace", "request_trace_id",
           "trace_path", "tracer_for"]

# Arming env var: rides the launcher's worker environment (dict(os.environ)
# at spawn), so exporting it on the supervisor traces every worker of
# every restart attempt — including --config_json rings that reject
# individual CLI flags (the DPT_PREFETCH_DEPTH channel).
TRACE_ENV = "DPT_TRACE"


def enabled_by_env() -> bool:
    return os.environ.get(TRACE_ENV, "") not in ("", "0")


def trace_path(run_dir: str, who: Union[int, str]) -> str:
    """Shard path for one process: an int rank -> ``trace_rank{k}.jsonl``
    (the trainer/worker spelling); a string label -> ``trace_{who}.jsonl``
    (launcher/router-side writers). Owned here so writers and the
    exporter's glob can never drift."""
    name = f"rank{who}" if isinstance(who, int) else str(who)
    return os.path.join(run_dir, f"trace_{name}.jsonl")


def request_trace_id(req_id: int) -> str:
    """THE cross-process trace identity for serving request ``req_id``
    — one owner for the spelling, so the router's mint, its journal
    recovery, and the exporter's rederivation (for pre-trace journals)
    can never drift apart and break the per-request timeline stitch."""
    return f"req{int(req_id):08d}"


def microbatch_trace_id(step: int, mb: int) -> str:
    """THE cross-process trace identity for one pipeline microbatch —
    the MPMD runtime's counterpart of :func:`request_trace_id`: every
    stage's fwd/bwd spans and every link send/recv frame for microbatch
    ``mb`` of step ``step`` carry this id, so one microbatch stitches
    into one timeline across stage processes in the Perfetto export."""
    return f"s{int(step):06d}.mb{int(mb):04d}"


class Stopwatch:
    """Monotonic interval timer — the sanctioned way to book wall time
    into a metric OUTSIDE utils/perf.py and obs/ (graftlint GL009 flags
    raw ``time.time()``/``perf_counter()`` deltas fed to metric sinks;
    keeping the subtraction here gives ad-hoc timing one owner)."""

    __slots__ = ("_t0",)

    def __init__(self) -> None:
        self._t0 = time.perf_counter()

    def lap_s(self) -> float:
        """Seconds since construction or the previous lap; resets."""
        now = time.perf_counter()
        dt = now - self._t0
        self._t0 = now
        return dt

    def peek_s(self) -> float:
        """Seconds since construction/last lap, without resetting."""
        return time.perf_counter() - self._t0


class _Span:
    """Live span context manager (only ever built by an ENABLED tracer)."""

    __slots__ = ("_tracer", "name", "cat", "trace_id", "args", "_t0",
                 "_watch", "sid")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 trace_id: Optional[str], args: Optional[dict]) -> None:
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.args = args
        self.sid = ""

    def __enter__(self) -> "_Span":
        self._t0 = time.time()
        self._watch = Stopwatch()
        self.sid = self._tracer._push()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._tracer._pop(self)


class _NullSpan:
    """Shared no-op context manager: ``NULL.span(...)`` returns THIS one
    object every time — the tracing-off path allocates nothing."""

    __slots__ = ()
    sid = ""

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> None:
        pass


_NULL_SPAN = _NullSpan()


class NullTracer:
    """The disabled tracer: every operation is a no-op; ``enabled`` is
    the one attribute hot paths check before building span arguments."""

    enabled = False

    def span(self, name: str, cat: str = "misc",
             trace_id: Optional[str] = None,
             args: Optional[dict] = None) -> _NullSpan:
        return _NULL_SPAN

    def complete(self, name: str, cat: str, t0: float, dur_s: float,
                 trace_id: Optional[str] = None,
                 args: Optional[dict] = None) -> str:
        return ""

    def instant(self, name: str, cat: str = "misc",
                t: Optional[float] = None,
                trace_id: Optional[str] = None,
                args: Optional[dict] = None) -> str:
        return ""

    def close(self) -> None:
        pass


NULL = NullTracer()


class Tracer:
    """Writes one process's trace shard; thread-safe, lazily opened.

    ``proc`` labels the process ("rank0", "launcher", ...) and prefixes
    every span id — IDs are ``{proc}:{counter}``, explicit and
    collision-free by construction (never wall-clock-derived). Spans
    opened with :meth:`span` nest: the innermost open span is the parent
    of anything booked while it is open (including after-the-fact
    :meth:`complete` bookings, which is how the goodput-aligned
    instrumentation reuses already-measured seconds)."""

    enabled = True

    def __init__(self, path: str, proc: str) -> None:
        self.path = path
        self.proc = proc
        self._n = 0
        self._f: Any = None
        self._stack: list = []
        self._lock = threading.Lock()

    # ------------------------------------------------------------- identity

    def _next_id(self) -> str:
        """Mint one span id. Callers must hold ``_lock`` (concurrent
        unlocked increments could mint the same id, breaking the
        collision-free contract)."""
        self._n += 1
        return f"{self.proc}:{self._n}"

    def _parent(self) -> Optional[str]:
        return self._stack[-1] if self._stack else None

    # --------------------------------------------------------------- events

    def span(self, name: str, cat: str = "misc",
             trace_id: Optional[str] = None,
             args: Optional[dict] = None) -> _Span:
        """Context manager measuring a live span (wall-clock anchor +
        monotonic duration, so a clock step mid-span cannot produce a
        negative or inflated ``dur``)."""
        return _Span(self, name, cat, trace_id, args)

    def _push(self) -> str:
        with self._lock:
            sid = self._next_id()
            self._stack.append(sid)
        return sid

    def _pop(self, span: _Span) -> None:
        with self._lock:
            if span.sid in self._stack:
                self._stack.remove(span.sid)
            parent = self._parent()
        self._emit({"ph": "X", "name": span.name, "cat": span.cat,
                    "t": span._t0, "dur": span._watch.peek_s(),
                    "sid": span.sid, "parent": parent,
                    "trace": span.trace_id, "args": span.args})

    def complete(self, name: str, cat: str, t0: float, dur_s: float,
                 trace_id: Optional[str] = None,
                 args: Optional[dict] = None) -> str:
        """Book an ALREADY-MEASURED span: ``t0`` is the wall-clock start,
        ``dur_s`` the caller's own measured seconds — pass the exact
        value handed to the goodput/stall tracker so the trace and the
        ledger can never disagree."""
        with self._lock:
            sid = self._next_id()
            parent = self._parent()
        self._emit({"ph": "X", "name": name, "cat": cat, "t": t0,
                    "dur": max(0.0, dur_s), "sid": sid,
                    "parent": parent, "trace": trace_id,
                    "args": args})
        return sid

    def instant(self, name: str, cat: str = "misc",
                t: Optional[float] = None,
                trace_id: Optional[str] = None,
                args: Optional[dict] = None) -> str:
        with self._lock:
            sid = self._next_id()
            parent = self._parent()
        self._emit({"ph": "i", "name": name, "cat": cat,
                    "t": time.time() if t is None else t, "sid": sid,
                    "parent": parent, "trace": trace_id,
                    "args": args})
        return sid

    # ---------------------------------------------------------------- sink

    def _emit(self, event: Dict[str, Any]) -> None:
        line = json.dumps({k: v for k, v in event.items() if v is not None},
                          separators=(",", ":"))
        try:
            with self._lock:
                if self._f is None:
                    self._f = open(self.path, "a")
                self._f.write(line + "\n")
                self._f.flush()
        except (OSError, ValueError):
            pass  # tracing is telemetry: never fail the traced work

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None


def tracer_for(run_dir: str, who: Union[int, str],
               armed: Optional[bool] = None,
               proc: Optional[str] = None) -> Union[Tracer, NullTracer]:
    """The one constructor call sites use: a live :class:`Tracer` when
    tracing is armed (``armed``; None defers to :func:`enabled_by_env`,
    False forces off regardless of the env) and a local run dir exists
    to write into, else :data:`NULL` — so every caller gets the
    zero-cost off path by default.

    ``proc`` overrides the process label (default ``rank{who}``/the
    label) WITHOUT changing the shard filename — a fleet's replica
    workers all write ``trace_rank0.jsonl`` in their own dirs but must
    label themselves distinctly (``r1.rank0``) or the merged timeline
    holds colliding span ids. Under launcher supervision
    (``DPT_ATTEMPT`` set) the label additionally carries the attempt
    index (``rank0.a2``): a respawned attempt appends to the SAME shard
    with its counter reset to 1, so without the qualifier the
    kill/restart runs this feature exists for would mint colliding
    ids."""
    if armed is None:
        armed = enabled_by_env()
    if not armed or not run_dir or "://" in run_dir:
        return NULL
    if proc is None:
        proc = f"rank{who}" if isinstance(who, int) else str(who)
    path = trace_path(run_dir, who)
    attempt = os.environ.get("DPT_ATTEMPT", "")
    if attempt:
        proc = f"{proc}.a{attempt}"
    else:
        try:
            appending = os.path.getsize(path) > 0
        except OSError:
            appending = False
        if appending:
            # unsupervised second session appending to an earlier
            # session's shard (manual checkpoint resume without the
            # launcher): qualify with the pid — explicit process
            # identity, not a clock — or both sessions would label
            # themselves identically with counters restarting at 1
            proc = f"{proc}.p{os.getpid()}"
    return Tracer(path, proc)
