"""Per-compiled-program cost ledger + roofline MFU-gap attribution.

Headline MFU is ONE number; this module decomposes it per compiled
program so "where do the missing FLOP-seconds go" has an answer a kernel
PR can be held to (ROADMAP item 3: every Pallas kernel must prove it
moves ``device_step_s``). Three layers:

* **extraction** — :func:`extract_cost` pulls XLA's own accounting off
  an already-AOT-compiled executable (``compiled.cost_analysis()`` /
  ``compiled.memory_analysis()``, duck-typed so this module never
  imports jax) and :func:`hlo_collective_tally` walks the executable's
  HLO text tallying collective ops (all-reduce / all-gather /
  reduce-scatter / collective-permute / all-to-all) with their shapes
  into bytes-moved per execution — the comms side of the roofline,
  measured off the real compiled program instead of estimated from the
  parallelism plan;
* **attribution** — :func:`roofline_attribution` folds the extracted
  FLOPs/bytes with the analytic ``flops_per_token``, the measured
  tokens/s, and the r8 stall gauges into one row per program::

      mfu + mfu_gap_host + mfu_gap_comms + mfu_gap_memory_bound
          + mfu_gap_residual == 1        (exactly, by construction)

  Each gap term is that component's estimated share of step wall time,
  capped so the cumulative sum can never exceed the gap; the residual
  absorbs what no modeled component explains (kernel inefficiency,
  padding inside the program, dispatch overlap) — the honest framing,
  since the components are roofline ESTIMATES while ``mfu`` itself is
  measured. Attribution order is trust order: host stalls (measured by
  the StallBreakdown) cap first, then comms (HLO-derived bytes over an
  interconnect roofline), then memory-boundedness (bytes-accessed over
  an HBM roofline, in excess of ideal compute time);
* **persistence** — :func:`write_ledger`/:func:`read_ledger` keep one
  ``perf_ledger.json`` per run dir (atomic replace, the beacon
  discipline) that ``run/perf_report.py``, ``run/status.py``, and
  ``obs/export.py`` (Perfetto counter tracks) all read.

The bandwidth/peak tables are public-spec roofline CONSTANTS (the same
posture as ``utils/perf._PEAK_FLOPS``): the attribution is a first-order
decomposition for steering optimization, not a simulator. This module
and ``utils/perf.py`` are the two sanctioned owners of FLOPs/MFU
arithmetic (graftlint GL010 flags figures computed from raw constants
anywhere else).

Import-light (stdlib only): the report/status/regress CLIs read ledgers
without paying a jax import.
"""

from __future__ import annotations

import json
import os
import re
import threading
from typing import Any, Dict, List, Optional

__all__ = [
    "LEDGER_FILENAME", "COLLECTIVE_OPS", "GAP_TERMS", "PaddingMeter",
    "attribution_columns", "device_bandwidths", "extract_cost",
    "gap_sum_identity", "hlo_collective_tally", "ledger_path",
    "read_ledger", "roofline_attribution", "write_ledger",
]

LEDGER_FILENAME = "perf_ledger.json"

# the attribution row's gap terms, in attribution (= trust) order
GAP_TERMS = ("mfu_gap_host", "mfu_gap_comms", "mfu_gap_memory_bound",
             "mfu_gap_residual")

# HLO collective ops tallied into bytes-moved (the async '-start' form
# counts; its '-done' twin moves nothing new and is skipped).
COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

# element sizes for HLO shape strings (f32[256,128]{1,0})
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# One typed shape inside an HLO line: dtype[dims]{layout?}. dims empty =
# scalar. Tuple results wrap several of these in parentheses.
_SHAPE_RE = re.compile(r"([a-z]+[0-9a-z]*)\[([0-9,]*)\](?:\{[^}]*\})?")

# `%name = <result type(s)> <collective-op>(' — the -start async variant
# included, the -done completion excluded (it moves no new bytes).
_COLLECTIVE_RE = re.compile(
    r"=\s+(\([^)]*\)|[a-z]+[0-9a-z]*\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(" + "|".join(re.escape(op) for op in COLLECTIVE_OPS) + r")"
    r"(-start)?\(")


def _shape_byte_list(typed: str) -> List[int]:
    """Byte size of EACH shape in a type string, in order (token/opaque
    types count 0 — they move no tallyable payload)."""
    out: List[int] = []
    for m in _SHAPE_RE.finditer(typed):
        dtype, dims = m.group(1), m.group(2)
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            out.append(0)
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append(n * size)
    return out


def _shape_bytes(typed: str) -> int:
    """Total bytes of one result-type string (single shape or tuple)."""
    return sum(_shape_byte_list(typed))


def hlo_collective_tally(hlo_text: str) -> Dict[str, Any]:
    """Tally the collective ops in one executable's HLO text.

    Returns ``{"counts": {op: n}, "bytes": {op: total}, "collective_bytes":
    sum}`` where bytes are the RESULT shapes' sizes per execution — the
    payload a step moves through the interconnect (all-gather results are
    the gathered size, reduce-scatter results the scattered shard; a
    first-order bytes-on-the-wire figure, not a ring-step simulation).

    Async ``-start`` forms return a TUPLE whose leading element(s) alias
    the input operand(s) (the XLA ``(operands..., results..., contexts
    ...)`` convention): only the result element(s) count, so the same
    collective tallies identical bytes whether XLA scheduled it sync or
    async — a scheduling flip must never read as a comms-bytes delta."""
    counts = {op: 0 for op in COLLECTIVE_OPS}
    bytes_ = {op: 0 for op in COLLECTIVE_OPS}
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        typed, op, started = m.group(1), m.group(2), m.group(3)
        counts[op] += 1
        elements = _shape_byte_list(typed)
        if started and typed.startswith("("):
            # operand shapes sit between the regex's trailing '(' and
            # the first ')' (shape layouts use {}, never parens)
            n_ops = len(_shape_byte_list(line[m.end():].split(")")[0]))
            if 0 < n_ops < len(elements):
                results = (elements[n_ops:2 * n_ops]
                           if len(elements) >= 2 * n_ops
                           else elements[n_ops:])
                elements = results
        bytes_[op] += sum(elements)
    return {
        "counts": {op: n for op, n in counts.items() if n},
        "bytes": {op: b for op, b in bytes_.items() if b},
        "collective_bytes": sum(bytes_.values()),
    }


def extract_cost(compiled: Any) -> Dict[str, Any]:
    """XLA's own per-execution accounting off a compiled executable
    (``jax.stages.Compiled`` duck-typed — any object with
    ``cost_analysis``/``memory_analysis``/``as_text`` works, so this
    module never imports jax). Every probe is guarded: a backend that
    reports nothing yields an absent/zero field, never an exception —
    extraction runs inside live trainers/servers."""
    out: Dict[str, Any] = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            out["flops_per_execution"] = float(ca.get("flops", 0.0))
            out["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            out["memory"] = {
                "argument_bytes": int(
                    getattr(ma, "argument_size_in_bytes", 0)),
                "output_bytes": int(getattr(ma, "output_size_in_bytes", 0)),
                "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
                "alias_bytes": int(getattr(ma, "alias_size_in_bytes", 0)),
                "generated_code_bytes": int(
                    getattr(ma, "generated_code_size_in_bytes", 0)),
            }
    except Exception:
        pass
    try:
        tally = hlo_collective_tally(compiled.as_text())
        out["collectives"] = tally
        out["collective_bytes_per_step"] = tally["collective_bytes"]
    except Exception:
        pass
    return out


# ------------------------------------------------------- roofline constants

# (device-kind substring, HBM bytes/s, interconnect bytes/s per chip) —
# public-spec roofline numbers, matched in order like perf._PEAK_FLOPS.
# The CPU entry keeps CPU test attributions finite and small.
_BANDWIDTHS = (
    ("v6 lite", 1.6e12, 2.0e11), ("v6e", 1.6e12, 2.0e11),
    ("v5 lite", 8.1e11, 1.6e11), ("v5e", 8.1e11, 1.6e11),
    ("v5p", 2.77e12, 6.0e11), ("v5", 2.77e12, 6.0e11),
    ("v4", 1.2e12, 2.4e11), ("v3", 9.0e11, 1.4e11),
    ("v2", 7.0e11, 1.0e11),
    ("cpu", 2.0e10, 1.0e10),
)


def device_bandwidths(device_kind: str = "cpu") -> Dict[str, float]:
    """(rough, public-spec) per-chip HBM and interconnect bytes/s for a
    jax ``device_kind`` string — the roofline denominators."""
    kind = (device_kind or "cpu").lower()
    for key, hbm, ici in _BANDWIDTHS:
        if key in kind:
            return {"hbm_bytes_per_s": hbm, "ici_bytes_per_s": ici}
    if "tpu" in kind:  # unknown TPU generation: assume v4-class
        return {"hbm_bytes_per_s": 1.2e12, "ici_bytes_per_s": 2.4e11}
    return {"hbm_bytes_per_s": 2.0e10, "ici_bytes_per_s": 1.0e10}


def roofline_attribution(*, tokens_per_s: float, flops_per_token: float,
                         peak_flops: float, n_devices: int,
                         steps_per_s: float = 0.0,
                         collective_bytes_per_step: float = 0.0,
                         bytes_accessed: float = 0.0,
                         host_stall_s_per_step: float = 0.0,
                         device_kind: str = "cpu",
                         padding_waste_frac: float = 0.0
                         ) -> Dict[str, float]:
    """The roofline MFU-gap decomposition for one program.

    ``mfu`` is MEASURED (achieved model FLOP/s over peak); each gap term
    is a component's estimated share of per-step wall time, capped in
    trust order (host -> comms -> memory) so the terms can never
    over-explain the gap; ``mfu_gap_residual`` is the exact remainder —
    ``mfu + sum(gaps) == 1`` to float precision, by construction. With
    no per-step wall clock (``steps_per_s`` 0) every modeled term is 0
    and the whole gap lands in the residual: an unattributed gap is
    reported as unattributed, never invented."""
    bw = device_bandwidths(device_kind)
    mfu = 0.0
    if peak_flops > 0 and n_devices > 0:
        mfu = tokens_per_s * flops_per_token / (peak_flops * n_devices)
    mfu = min(max(mfu, 0.0), 1.0)
    gap = 1.0 - mfu
    step_s = 1.0 / steps_per_s if steps_per_s > 0 else 0.0
    host_frac = comms_frac = mem_frac = 0.0
    if step_s > 0:
        # host: measured stall seconds per step (data/h2d/dispatch)
        host_frac = max(0.0, host_stall_s_per_step) / step_s
        # comms: HLO-tallied collective payload over the interconnect
        # roofline (per chip — the payload is per program execution)
        comms_frac = (max(0.0, collective_bytes_per_step)
                      / bw["ici_bytes_per_s"]) / step_s
        # memory-bound: HBM traffic time IN EXCESS of ideal compute time
        # (a compute-bound program's traffic hides under the MXU)
        ideal_s = 0.0
        if peak_flops > 0 and n_devices > 0 and tokens_per_s > 0:
            ideal_s = (tokens_per_s * flops_per_token * step_s
                       / (peak_flops * n_devices))
        mem_s = max(0.0, bytes_accessed / bw["hbm_bytes_per_s"] - ideal_s)
        mem_frac = mem_s / step_s
    gap_host = min(gap, host_frac)
    gap_comms = min(gap - gap_host, comms_frac)
    gap_mem = min(gap - gap_host - gap_comms, mem_frac)
    gap_residual = gap - gap_host - gap_comms - gap_mem
    return {
        "mfu": mfu,
        "mfu_gap_host": gap_host,
        "mfu_gap_comms": gap_comms,
        "mfu_gap_memory_bound": gap_mem,
        "mfu_gap_residual": gap_residual,
        "collective_bytes_per_step": float(
            max(0.0, collective_bytes_per_step)),
        "padding_waste_frac": min(max(float(padding_waste_frac), 0.0), 1.0),
    }


def attribution_columns(row: Dict[str, Any]) -> Dict[str, Any]:
    """The bench-row subset of a ledger program row: ``mfu`` (unrounded —
    the gap-sum identity must hold to 1e-6, which survives no 4-decimal
    rounding), the four gap terms, the collective payload, and the
    padding waste."""
    keys = ("mfu",) + GAP_TERMS + ("collective_bytes_per_step",
                                   "padding_waste_frac")
    return {k: row[k] for k in keys if k in row}


# ---------------------------------------------------------- padding meter

class PaddingMeter:
    """Active-vs-padded token accounting off the masks the data path
    already carries (``pad_mask``: 1 for real tokens). Thread-safe (the
    device-prefetch wrapper calls the trainer's ``_prepare`` from its
    own thread); ``frac`` is the cumulative padding-waste fraction —
    the share of step FLOPs spent on tokens that are pure padding."""

    def __init__(self) -> None:
        self._active = 0
        self._total = 0
        self._lock = threading.Lock()

    def add(self, active: int, total: int) -> None:
        with self._lock:
            self._active += int(active)
            self._total += int(total)

    @property
    def frac(self) -> float:
        with self._lock:
            if self._total <= 0:
                return 0.0
            return 1.0 - self._active / self._total


# ------------------------------------------------------------ persistence

def ledger_path(run_dir: str) -> str:
    return os.path.join(run_dir, LEDGER_FILENAME)


def write_ledger(run_dir: str, programs: Dict[str, Dict[str, Any]], *,
                 t: float, extra: Optional[Dict[str, Any]] = None) -> str:
    """Atomically replace the run dir's ``perf_ledger.json`` (the beacon
    discipline: a reader never sees a torn file). Telemetry: an OSError
    is swallowed — the ledger must never fail the run it describes."""
    path = ledger_path(run_dir)
    payload = {"t": t, "programs": programs, **(extra or {})}
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except OSError:
        pass
    return path


def read_ledger(run_dir: str) -> Optional[Dict[str, Any]]:
    """The run dir's ledger snapshot, or None (absent / torn / garbled
    — the readers are status CLIs that must not crash on a live dir)."""
    try:
        with open(ledger_path(run_dir)) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    return payload if isinstance(payload, dict) else None


def gap_sum_identity(row: Dict[str, Any]) -> float:
    """``mfu + sum(gap terms)`` — the acceptance identity (== 1.0 within
    float precision for any row this module produced). One owner so the
    tests and the report CLI check the same expression."""
    return float(row.get("mfu", 0.0)) + sum(
        float(row.get(k, 0.0)) for k in GAP_TERMS)
