"""Timeline export: run/fleet dir artifacts -> Perfetto + Prometheus.

One command turns everything a run (or serving fleet) left on disk into
a single Chrome-trace-event JSON that Perfetto / ``chrome://tracing``
loads directly::

    python -m distributed_pipeline_tpu.obs.export <run_or_fleet_dir>

Four artifact kinds fold into one timeline, each readable on its own
(an UNTRACED run still exports — attempts/beacons/journal carry real
timestamps regardless of ``DPT_TRACE``):

* ``trace_*.jsonl`` shards (:mod:`.trace`): the instrumented spans;
* ``attempts.jsonl``: launcher per-attempt records -> ``attempt``/
  ``downtime`` spans + ``watchdog_kill`` instants;
* ``.progress_rank*.json`` beacons: last-known state instants (a killed
  process's flight recorder, placed at its final beacon time);
* the router ``journal.jsonl`` (fleet dirs): per-request ``queue`` /
  ``service`` spans and ``replay`` wasted-work spans, each carrying the
  request's cross-process trace id — the same id the worker's ``serve``
  span carries, so submit -> assign -> prefill/decode -> complete ->
  replay -> swap stitches into ONE timeline per request.

Layout: one pid per process/replica (rank files and the supervising
launcher's attempt spans share the replica's pid), one track (tid) per
category. Timestamps are normalized to the earliest event.

:func:`prometheus_lines` renders the same artifacts as a Prometheus
textfile snapshot — including the per-replica beacon ``serving``
snapshots, so fleet health is visible LIVE (scrape or ``run/status.py``)
instead of only post-mortem via ``aggregate_serving``.

Import-light: stdlib + the chaos readers; never imports jax.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

from ..chaos import goodput
from . import ledger as ledger_lib
from .trace import read_trace, request_trace_id

__all__ = ["chrome_trace", "collect_sources", "is_fleet_dir",
           "journal_counts", "main", "percentile", "prometheus_lines",
           "write_outputs"]

_SHARD_RE = re.compile(r"trace_([A-Za-z0-9_.-]+)\.jsonl$")


def is_fleet_dir(d: str) -> bool:
    """A fleet dir holds replica_* run dirs and/or the router journal; a
    training run dir holds neither."""
    return bool(goodput.list_replica_dirs(d)) or os.path.exists(
        goodput.serving_journal_path(d))


def percentile(vals: List[float], q: float) -> float:
    """Nearest-rank percentile on a copy-sorted sample (the EventStats
    convention, kept jax/numpy-free for the status CLI); 0.0 when empty."""
    if not vals:
        return 0.0
    v = sorted(vals)
    n = len(v)
    return v[min(n - 1, max(0, -(-int(q * 100) * n // 100) - 1))]


def _fnum(x: Any, default: float = 0.0) -> float:
    try:
        if isinstance(x, bool) or x is None:
            return default
        return float(x)
    except (TypeError, ValueError):
        return default


# ----------------------------------------------------------- event sources

def _shard_events(d: str) -> List[Tuple[str, List[dict]]]:
    """(label, events) per trace shard in ONE directory (non-recursive)."""
    out = []
    for path in sorted(glob.glob(os.path.join(d, "trace_*.jsonl"))):
        m = _SHARD_RE.search(path)
        if m:
            out.append((m.group(1), read_trace(path)))
    return out


def _attempt_events(run_dir: str) -> List[dict]:
    """attempts.jsonl -> internal-format events: one ``attempt`` span per
    record (spawn -> exit), a ``downtime`` span for the gap before it,
    and a ``watchdog_kill`` instant for hang-killed attempts. Used only
    when the dir has no live launcher trace shard (an ARMED launcher
    books the same spans itself; an untraced run still gets its attempt
    timeline from the records)."""
    events: List[dict] = []
    for rec in goodput.read_attempts(run_dir):
        t_spawn = _fnum(rec.get("t_spawn"))
        t_exit = _fnum(rec.get("t_exit"))
        if t_spawn <= 0 or t_exit < t_spawn:
            continue  # torn/garbled record: skip, never raise
        a = rec.get("attempt")
        args = {k: rec.get(k) for k in
                ("rc", "steps", "start_step", "end_step", "nprocs",
                 "devices_per_proc", "resume_overhead_s")
                if rec.get(k) is not None}
        # cat matches the launcher's LIVE spans exactly ("supervise"):
        # one attempt must land on the same track whether the run was
        # traced or reconstructed from the records alone
        events.append({"ph": "X", "name": f"attempt {a}",
                       "cat": "supervise",
                       "t": t_spawn, "dur": t_exit - t_spawn, "args": args})
        down = _fnum(rec.get("downtime_s"))
        if down > 0:
            events.append({"ph": "X", "name": "downtime",
                           "cat": "supervise",
                           "t": t_spawn - down, "dur": down})
        if rec.get("hung"):
            events.append({"ph": "i", "name": "watchdog_kill",
                           "cat": "supervise", "t": t_exit,
                           "args": {"hang_s": rec.get("hang_s"),
                                    "kind": rec.get("hang_kind")}})
    return events


def _tune_trial_events(d: str) -> List[dict]:
    """``tune_trials.jsonl`` -> per-trial spans (ISSUE 13): the tuner's
    journal is its trace — every measured/pruned trial carries its wall
    stamp and duration, so an UNTRACED tune still exports a timeline
    (the attempts.jsonl pattern; an armed tune tracer books richer spans
    itself and wins — see the caller's shard check). The journal stamps
    ``t`` at trial END, so the span starts at ``t - dur_s``; static
    rejects (no duration) land as instants."""
    events: List[dict] = []
    for row in read_trace(os.path.join(d, "tune_trials.jsonl")):
        if not isinstance(row, dict) or row.get("kind") not in ("trial",
                                                                "final"):
            continue
        t = _fnum(row.get("t"))
        if t <= 0:
            continue
        dur = _fnum(row.get("dur_s"))
        args = {"cid": row.get("cid"), "rung": row.get("rung"),
                "status": row.get("status")}
        res = row.get("result")
        if isinstance(res, dict) and res.get("steps_per_s") is not None:
            args["steps_per_s"] = res.get("steps_per_s")
        if row.get("reason"):
            args["reason"] = row.get("reason")
        name = f"{row.get('kind')} {row.get('cid')}"
        if dur > 0:
            events.append({"ph": "X", "name": name, "cat": "tune",
                           "t": t - dur, "dur": dur, "args": args})
        else:
            events.append({"ph": "i", "name": name, "cat": "tune",
                           "t": t, "args": args})
    return events


def _ledger_events(run_dir: str) -> List[dict]:
    """``perf_ledger.json`` -> Perfetto COUNTER events (ph "C"): one
    counter track per program carrying mfu + the roofline gap terms,
    plus a bytes track for the collective payload — the attribution as
    a timeline series next to the spans it explains. The ledger is a
    snapshot (atomically replaced each log window), so each export
    carries one sample at the snapshot's wall stamp; Perfetto renders a
    counter with the value held from that point."""
    led = ledger_lib.read_ledger(run_dir)
    if not led:
        return []
    t = _fnum(led.get("t"))
    if t <= 0:
        return []
    events: List[dict] = []
    for name, row in sorted((led.get("programs") or {}).items()):
        if "mfu" not in row:
            continue
        series = {"mfu": row["mfu"],
                  **{k: row.get(k, 0.0) for k in ledger_lib.GAP_TERMS},
                  "padding_waste_frac": row.get("padding_waste_frac",
                                                0.0)}
        events.append({"ph": "C", "name": f"roofline {name}",
                       "cat": "ledger", "t": t,
                       "args": {k: round(_fnum(v), 6)
                                for k, v in series.items()}})
        if row.get("collective_bytes_per_step"):
            events.append({"ph": "C", "name": f"collective_bytes {name}",
                           "cat": "ledger", "t": t,
                           "args": {"bytes_per_step": _fnum(
                               row["collective_bytes_per_step"])}})
    return events


def _beacon_events(run_dir: str) -> Dict[int, dict]:
    """rank -> one ``beacon`` instant at the rank's LAST beacon time (a
    killed attempt's flight-recorder position on the timeline)."""
    out: Dict[int, dict] = {}
    for rank, b in goodput.read_beacons(run_dir).items():
        t = _fnum(b.get("t"))
        if t <= 0:
            continue
        args = {k: b.get(k) for k in
                ("step", "attempt", "steady_recompile_count")
                if b.get(k) is not None}
        snap = b.get("serving") or b.get("goodput")
        if isinstance(snap, dict):
            args.update({k: v for k, v in snap.items()
                         if isinstance(v, (int, float))})
        out[rank] = {"ph": "i", "name": "last_beacon", "cat": "beacon",
                     "t": t, "args": args}
    return out


def journal_counts(events: List[dict]) -> dict:
    """Request-state machine over the router journal, shared by the
    Prometheus snapshot and the status CLI (one owner: the two live
    views of the same fleet dir must never disagree): submitted/
    completed/in-flight/replayed totals, per-replica assigned-in-flight,
    and TTFT percentiles from the completion events."""
    subs: set = set()
    done: set = set()
    where: Dict[int, int] = {}  # req id -> replica currently assigned
    replays = 0
    ttfts: List[float] = []
    affinity_hits = 0
    scale_ups = scale_downs = 0
    paid_idle_s = 0.0
    for ev in events:
        kind = ev.get("ev")
        try:
            rid = int(ev.get("id")) if ev.get("id") is not None else None
        except (TypeError, ValueError):
            rid = None
        if kind == "submit" and rid is not None:
            subs.add(rid)
        elif kind == "assign" and rid is not None:
            if _fnum(ev.get("affinity")) > 0:
                affinity_hits += 1
            try:
                where[rid] = int(ev.get("replica"))
            except (TypeError, ValueError):
                pass
        elif kind == "scale":
            if ev.get("dir") == "up":
                scale_ups += 1
            elif ev.get("dir") == "down":
                scale_downs += 1
        elif kind == "paid_idle":
            paid_idle_s += _fnum(ev.get("idle_s"))
        elif kind == "complete" and rid is not None:
            done.add(rid)
            where.pop(rid, None)
            if ev.get("ttft_s") is not None:
                ttfts.append(_fnum(ev.get("ttft_s")))
        elif kind == "replay":
            replays += 1
            if rid is not None:
                where.pop(rid, None)
    per_replica: Dict[int, int] = {}
    for rep in where.values():
        per_replica[rep] = per_replica.get(rep, 0) + 1
    return {
        "submitted": len(subs),
        "completed": len(done),
        "in_flight": len(subs - done),
        "replayed": replays,
        "assigned": per_replica,
        "ttfts": ttfts,
        "ttft_p50_s": (round(percentile(ttfts, 0.5), 4)
                       if ttfts else None),
        "ttft_p95_s": (round(percentile(ttfts, 0.95), 4)
                       if ttfts else None),
        "affinity_hits": affinity_hits,
        "scale_ups": scale_ups,
        "scale_downs": scale_downs,
        "paid_idle_s": round(paid_idle_s, 4),
    }


def _request_trace_id(ev: dict) -> Optional[str]:
    tid = ev.get("trace")
    if tid:
        return str(tid)
    rid = ev.get("id")  # pre-trace journal: rederive the minted id
    try:
        return request_trace_id(int(rid)) if rid is not None else None
    except (TypeError, ValueError):
        return None


def _journal_events(fleet_dir: str) -> List[dict]:
    """Router journal -> per-request lifecycle spans. The journal is the
    router's trace: every event carries request identity (and, since
    tracing landed, the explicit trace id), so queue/service/replay
    spans need no separate shard."""
    events: List[dict] = []
    pending_since: Dict[int, float] = {}
    assigned: Dict[int, Tuple[float, Any]] = {}
    for ev in read_trace(goodput.serving_journal_path(fleet_dir)):
        kind = ev.get("ev")
        t = _fnum(ev.get("t"))
        tid = _request_trace_id(ev)
        try:
            rid = int(ev.get("id")) if ev.get("id") is not None else None
        except (TypeError, ValueError):
            rid = None
        if kind == "submit" and rid is not None:
            pending_since[rid] = t
            events.append({"ph": "i", "name": "submit", "cat": "request",
                           "t": t, "trace": tid,
                           "args": {"id": rid,
                                    "max_new_tokens":
                                        ev.get("max_new_tokens")}})
        elif kind == "assign" and rid is not None:
            t0 = pending_since.pop(rid, t)
            events.append({"ph": "X", "name": "queue", "cat": "request",
                           "t": t0, "dur": max(0.0, t - t0), "trace": tid,
                           "args": {"id": rid,
                                    "replica": ev.get("replica")}})
            assigned[rid] = (t, ev.get("replica"))
        elif kind == "complete" and rid is not None:
            t0, replica = assigned.pop(rid, (t, ev.get("replica")))
            events.append({"ph": "X", "name": "service", "cat": "request",
                           "t": t0, "dur": max(0.0, t - t0), "trace": tid,
                           "args": {"id": rid, "replica": replica,
                                    "n_tokens": ev.get("n_tokens"),
                                    "ttft_s": ev.get("ttft_s")}})
        elif kind == "replay" and rid is not None:
            t0, replica = assigned.pop(rid, (t, ev.get("from")))
            pending_since[rid] = t
            events.append({"ph": "X", "name": "replayed_work",
                           "cat": "replay", "t": t0,
                           "dur": max(0.0, t - t0), "trace": tid,
                           "args": {"id": rid, "from": ev.get("from"),
                                    "reason": ev.get("reason"),
                                    "wasted_s": ev.get("wasted_s")}})
        elif kind == "replica_down":
            events.append({"ph": "i", "name": "replica_down",
                           "cat": "replay", "t": t,
                           "args": {"replica": ev.get("replica")}})
        elif kind == "scale":
            events.append({"ph": "i",
                           "name": f"scale_{ev.get('dir')}",
                           "cat": "autoscale", "t": t,
                           "args": {"replica": ev.get("replica"),
                                    "reason": ev.get("reason"),
                                    "n_active": ev.get("n_active")}})
    return events


def collect_sources(d: str) -> List[Tuple[int, str, List[dict]]]:
    """(pid, process_name, internal events) per process/replica.

    Training run dir: pid 1 = launcher (its trace shard + the
    attempts.jsonl conversion), pid 10+k per rank shard (+ its beacon).
    Fleet dir: pid 1 = router (journal + any fleet-root shards), pid
    10+i per replica — the replica's worker shard, its supervising
    ring's attempt spans, and its beacon share the replica's pid (one
    pid per process/replica; categories separate the tracks)."""
    sources: List[Tuple[int, str, List[dict]]] = []
    if is_fleet_dir(d):
        router_events = _journal_events(d)
        for label, events in _shard_events(d):
            router_events.extend(events)
        sources.append((1, "router", router_events))
        for rd in goodput.list_replica_dirs(d):
            rid = goodput.replica_id(rd)
            shards = _shard_events(rd)
            events = [ev for _, shard in shards for ev in shard]
            if not any(label.startswith("launcher") for label, _ in shards):
                events.extend(_attempt_events(rd))
            events.extend(_beacon_events(rd).values())
            # per-replica roofline counter tracks (--cost_ledger workers)
            events.extend(_ledger_events(rd))
            sources.append((10 + rid, f"replica_{rid}", events))
        return sources
    stage_dirs = goodput.list_stage_dirs(d)
    if stage_dirs:
        # MPMD pipeline run (ISSUE 16): pid 1 = the jax-free host driver
        # (its own shard lives in the run-dir root), pid 10+k per stage —
        # the stage worker's shard, its supervising ring's attempt spans,
        # and its beacon (the replica pattern). Cross-process microbatch
        # stitching rides the trace ids the links carry in frame meta.
        driver_events = [ev for _, events in _shard_events(d)
                         for ev in events]
        sources.append((1, "driver", driver_events))
        for sd in stage_dirs:
            sid = goodput.stage_id(sd)
            shards = _shard_events(sd)
            events = [ev for _, shard in shards for ev in shard]
            if not any(label.startswith("launcher")
                       for label, _ in shards):
                events.extend(_attempt_events(sd))
            events.extend(_beacon_events(sd).values())
            sources.append((10 + sid, f"stage_{sid}", events))
        return sources
    rank_shards: Dict[int, List[dict]] = {}
    launcher_events: List[dict] = []
    have_launcher_shard = False
    have_tune_shard = False
    for label, events in _shard_events(d):
        m = re.fullmatch(r"rank(\d+)", label)
        if m:
            rank_shards.setdefault(int(m.group(1)), []).extend(events)
        else:
            have_launcher_shard = (have_launcher_shard
                                   or label.startswith("launcher"))
            have_tune_shard = have_tune_shard or label.startswith("tune")
            launcher_events.extend(events)
    if not have_launcher_shard:
        launcher_events.extend(_attempt_events(d))
    if not have_tune_shard:
        # untraced tune runs: the trial journal is the span source (the
        # attempts.jsonl pattern; an armed tune tracer wins)
        launcher_events.extend(_tune_trial_events(d))
    # cost-ledger counter tracks (--cost_ledger runs) ride the launcher
    # pid: one roofline series per program
    launcher_events.extend(_ledger_events(d))
    beacons = _beacon_events(d)
    for rank, ev in beacons.items():
        rank_shards.setdefault(rank, []).append(ev)
    sources.append((1, "launcher", launcher_events))
    for rank in sorted(rank_shards):
        sources.append((10 + rank, f"rank{rank}", rank_shards[rank]))
    return sources


# ------------------------------------------------------------ chrome trace

def chrome_trace(d: str) -> dict:
    """Fold one run/fleet dir into a Chrome-trace-event dict (load the
    written file directly in Perfetto / chrome://tracing)."""
    sources = [(pid, name, evs) for pid, name, evs in collect_sources(d)
               if evs]
    base = min((_fnum(ev.get("t"))
                for _, _, evs in sources for ev in evs
                if _fnum(ev.get("t")) > 0), default=0.0)
    trace_events: List[dict] = []
    for pid, pname, events in sources:
        trace_events.append({"ph": "M", "name": "process_name", "pid": pid,
                             "tid": 0, "args": {"name": pname}})
        cats = sorted({str(ev.get("cat", "misc")) for ev in events})
        tid_of = {c: i + 1 for i, c in enumerate(cats)}
        for cat, tid in tid_of.items():
            trace_events.append({"ph": "M", "name": "thread_name",
                                 "pid": pid, "tid": tid,
                                 "args": {"name": cat}})
        for ev in events:
            t = _fnum(ev.get("t"))
            if t <= 0:
                continue
            cat = str(ev.get("cat", "misc"))
            args = dict(ev.get("args") or {})
            for key, out_key in (("trace", "trace_id"), ("sid", "span_id"),
                                 ("parent", "parent_id")):
                if ev.get(key):
                    args[out_key] = ev[key]
            if ev.get("ph") == "C":
                # counter sample: args ARE the series values (numeric
                # only — Perfetto draws one line per key)
                trace_events.append({
                    "name": str(ev.get("name", "?")), "cat": cat,
                    "ph": "C", "pid": pid, "tid": tid_of[cat],
                    "ts": round((t - base) * 1e6, 1),
                    "args": {k: _fnum(v) for k, v in args.items()}})
                continue
            ch = {"name": str(ev.get("name", "?")), "cat": cat,
                  "ph": "i" if ev.get("ph") == "i" else "X",
                  "pid": pid, "tid": tid_of[cat],
                  "ts": round((t - base) * 1e6, 1), "args": args}
            if ch["ph"] == "X":
                ch["dur"] = round(max(0.0, _fnum(ev.get("dur"))) * 1e6, 1)
            else:
                ch["s"] = "t"
            trace_events.append(ch)
    return {"traceEvents": trace_events, "displayTimeUnit": "ms",
            "otherData": {"source_dir": os.path.abspath(d),
                          "base_wall_clock_s": base}}


# -------------------------------------------------------------- prometheus

class _Prom:
    """Tiny metric-family accumulator -> textfile lines."""

    def __init__(self) -> None:
        self._fams: Dict[str, Tuple[str, List[Tuple[str, float]]]] = {}

    def add(self, name: str, value: Any, labels: Optional[dict] = None,
            help_: str = "") -> None:
        v = _fnum(value, default=float("nan"))
        if v != v:  # non-numeric: skip rather than emit NaN
            return
        lab = ""
        if labels:
            inner = ",".join(f'{k}="{v2}"' for k, v2 in sorted(
                labels.items()))
            lab = "{" + inner + "}"
        fam = self._fams.setdefault(name, (help_, []))
        fam[1].append((lab, v))

    def lines(self) -> List[str]:
        out: List[str] = []
        for name, (help_, samples) in self._fams.items():
            if help_:
                out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} gauge")
            for lab, v in samples:
                out.append(f"{name}{lab} {v:g}")
        return out


def _prom_run(p: _Prom, run_dir: str, now: float,
              labels: Optional[dict] = None) -> None:
    for rank, b in sorted(goodput.read_beacons(run_dir).items()):
        lab = {**(labels or {}), "rank": rank}
        p.add("dpt_beacon_step", b.get("step"), lab,
              help_="last step any beacon reported")
        p.add("dpt_beacon_age_seconds", now - _fnum(b.get("t")), lab,
              help_="seconds since the rank's last beacon write")
        p.add("dpt_beacon_attempt", b.get("attempt"), lab)
    attempts = goodput.read_attempts(run_dir)
    if attempts:
        p.add("dpt_attempts_total", len(attempts), labels,
              help_="launcher attempts recorded")
        p.add("dpt_last_attempt_rc", attempts[-1].get("rc"), labels)
    agg = goodput.aggregate_run(run_dir)
    if agg["attempts"]:
        p.add("dpt_goodput", agg["goodput"], labels,
              help_="useful-step share of accounted wall time")
        p.add("dpt_accounted_frac", agg["accounted_frac"], labels)
        for cat in ("useful_step_s", "startup_s", "setup_s", "restore_s",
                    "compile_s", "save_s", "data_stall_s", "link_wait_s",
                    "recompute_s", "hang_s", "lost_s", "downtime_s"):
            p.add("dpt_goodput_seconds", agg[cat],
                  {**(labels or {}), "category": cat[:-2]},
                  help_="goodput ledger decomposition (seconds)")
    _prom_ledger(p, run_dir, labels)


def _prom_ledger(p: _Prom, run_dir: str,
                 labels: Optional[dict] = None) -> None:
    """perf_ledger.json -> dpt_mfu/gap gauges. One owner shared by the
    training-run and per-replica fleet snapshots (a replica worker with
    --cost_ledger writes the same file into its replica dir)."""
    led = ledger_lib.read_ledger(run_dir)
    for name, row in sorted(((led or {}).get("programs") or {}).items()):
        if "mfu" not in row:
            continue
        lab = {**(labels or {}), "program": name}
        p.add("dpt_mfu", row["mfu"], lab,
              help_="measured model-FLOPs utilization per program "
                    "(perf_ledger.json)")
        for term in ledger_lib.GAP_TERMS:
            p.add("dpt_mfu_gap", row.get(term),
                  {**lab, "component": term.replace("mfu_gap_", "")},
                  help_="roofline MFU-gap decomposition "
                        "(sums with dpt_mfu to 1)")
        p.add("dpt_collective_bytes_per_step",
              row.get("collective_bytes_per_step"), lab,
              help_="HLO-tallied collective payload per step")
        p.add("dpt_padding_waste_frac", row.get("padding_waste_frac"),
              lab, help_="share of step tokens that are padding")
        if row.get("accept_rate") is not None:
            # speculative-decoding gauges (ISSUE 20): only the decode
            # program of a --spec_tokens replica/run carries them
            p.add("dpt_accept_rate", row.get("accept_rate"), lab,
                  help_="draft-token acceptance rate under speculative "
                        "decoding (perf_ledger.json)")
            p.add("dpt_accepted_tokens_per_s",
                  row.get("accepted_tokens_per_s"), lab,
                  help_="target-verified tokens per second under "
                        "speculative decoding")


def _prom_fleet(p: _Prom, fleet_dir: str, now: float) -> None:
    from ..serving.fleet import ReplicaPaths, read_json_file

    for rd in goodput.list_replica_dirs(fleet_dir):
        rid = goodput.replica_id(rd)
        lab = {"replica": rid}
        paths = ReplicaPaths.at(rd, rid)
        ready = read_json_file(paths.ready_path)
        p.add("dpt_replica_ready", 1 if ready else 0, lab,
              help_="replica announced ready (current attempt)")
        if ready:
            p.add("dpt_replica_params_step", ready.get("params_step"), lab,
                  help_="checkpoint step the replica serves")
        beacons = goodput.read_beacons(rd)
        b = beacons.get(0)
        if b:
            p.add("dpt_replica_tick", b.get("step"), lab)
            p.add("dpt_replica_beacon_age_seconds",
                  now - _fnum(b.get("t")), lab,
                  help_="staleness of the replica's liveness beacon")
            p.add("dpt_replica_attempt", b.get("attempt"), lab)
            snap = b.get("serving")
            if isinstance(snap, dict):
                # the LIVE serving-time decomposition (satellite: fleet
                # health visible now, not only post-mortem)
                for cat in ("wall_s", "serving_s", "drain_s", "swap_s"):
                    p.add("dpt_replica_serving_seconds", snap.get(cat),
                          {**lab, "category": cat[:-2]},
                          help_="in-attempt serving-time decomposition "
                                "from the replica's beacon")
            if b.get("accept_rate") is not None:
                # live speculative gauges off the beacon (no --cost_ledger
                # needed): same names the ledger path emits per program
                p.add("dpt_accept_rate", b.get("accept_rate"), lab,
                      help_="draft-token acceptance rate under "
                            "speculative decoding (perf_ledger.json)")
                p.add("dpt_accepted_tokens_per_s",
                      b.get("accepted_tokens_per_s"), lab,
                      help_="target-verified tokens per second under "
                            "speculative decoding")
            if b.get("prefix_hits") is not None:
                p.add("dpt_replica_prefix_cache_total",
                      b.get("prefix_hits"), {**lab, "kind": "hit"},
                      help_="prefix-cache hits/misses advertised on the "
                            "replica's beacon")
                p.add("dpt_replica_prefix_cache_total",
                      b.get("prefix_misses"), {**lab, "kind": "miss"})
        attempts = goodput.read_attempts(rd)
        if attempts:
            p.add("dpt_replica_attempts_total", len(attempts), lab)
        # per-replica roofline: a --cost_ledger replica worker snapshots
        # perf_ledger.json into its replica dir (ISSUE 15 satellite)
        _prom_ledger(p, rd, lab)
    events = read_trace(goodput.serving_journal_path(fleet_dir))
    if events:
        counts = journal_counts(events)
        p.add("dpt_requests_total", counts["submitted"],
              {"state": "submitted"},
              help_="router journal request counts")
        p.add("dpt_requests_total", counts["completed"],
              {"state": "completed"})
        p.add("dpt_requests_total", counts["replayed"],
              {"state": "replayed"})
        p.add("dpt_requests_in_flight", counts["in_flight"],
              help_="submitted but not yet completed")
        if counts["ttfts"]:
            p.add("dpt_ttft_seconds", counts["ttft_p50_s"],
                  {"quantile": "0.5"},
                  help_="time-to-first-token from journal completions")
            p.add("dpt_ttft_seconds", counts["ttft_p95_s"],
                  {"quantile": "0.95"})
        p.add("dpt_affinity_hits_total", counts["affinity_hits"],
              help_="placements won by a warm advertised prefix")
        p.add("dpt_scale_events_total", counts["scale_ups"],
              {"dir": "up"},
              help_="autoscaler structural changes from the journal")
        p.add("dpt_scale_events_total", counts["scale_downs"],
              {"dir": "down"})
    agg = goodput.aggregate_serving(fleet_dir)
    if agg["attempts"]:
        p.add("dpt_serving_accounted_frac", agg["accounted_frac"])
        for cat in ("serving_s", "drain_s", "replay_s", "paid_idle_s",
                    "swap_s", "downtime_s", "lost_s"):
            p.add("dpt_serving_seconds", agg[cat],
                  {"category": cat[:-2]},
                  help_="fleet serving ledger decomposition (seconds)")


def prometheus_lines(d: str, now: Optional[float] = None) -> List[str]:
    """Prometheus-textfile snapshot of a run or fleet dir (node_exporter
    textfile-collector format; every metric is a point-in-time gauge)."""
    now = time.time() if now is None else now
    p = _Prom()
    if is_fleet_dir(d):
        _prom_fleet(p, d, now)
    else:
        _prom_run(p, d, now)
    return p.lines()


# --------------------------------------------------------------------- CLI

def write_outputs(d: str, out: str = "", prom: str = "") -> dict:
    """Write the Perfetto JSON (and optionally the Prometheus snapshot);
    returns a summary dict (also the CLI's stdout line)."""
    out = out or os.path.join(d, "trace.json")
    payload = chrome_trace(d)
    with open(out, "w") as f:
        json.dump(payload, f)
    summary = {"dir": os.path.abspath(d),
               "kind": "fleet" if is_fleet_dir(d) else "run",
               "trace_json": os.path.abspath(out),
               "events": len(payload["traceEvents"])}
    if prom:
        lines = prometheus_lines(d)
        with open(prom, "w") as f:
            f.write("\n".join(lines) + "\n")
        summary["prometheus"] = os.path.abspath(prom)
        summary["metrics"] = len(lines)
    return summary


def main(argv: Optional[List[str]] = None) -> dict:
    ap = argparse.ArgumentParser(
        description="Export a run/fleet dir's artifacts as one "
                    "Perfetto-loadable timeline (+ optional Prometheus "
                    "textfile snapshot). Load the JSON at "
                    "https://ui.perfetto.dev or chrome://tracing.")
    ap.add_argument("dir", help="run dir (training) or fleet dir (serving)")
    ap.add_argument("--out", default="",
                    help="output JSON path (default <dir>/trace.json)")
    ap.add_argument("--prom", default="",
                    help="also write a Prometheus textfile snapshot here")
    ns = ap.parse_args(argv)
    summary = write_outputs(ns.dir, ns.out, ns.prom)
    print(json.dumps(summary))
    return summary


if __name__ == "__main__":
    main()
