"""Bench-history regression sentinel: newest run vs a trailing baseline.

``bench.py`` appends every leg row to a persistent ``bench_history.jsonl``
(stamped with a per-run ``run_id``), so the bench trajectory is a time
series instead of a pile of disconnected artifacts. This CLI compares the
NEWEST recorded run against the mean of a trailing baseline window of
prior runs, per leg, on the metrics that define the perf contract::

    python -m distributed_pipeline_tpu.obs.regress                  # table
    python -m distributed_pipeline_tpu.obs.regress --json           # machine
    python -m distributed_pipeline_tpu.obs.regress --band_pct 3 \
        --baseline_runs 3 --history bench_history.jsonl

Per leg, per metric, the verdict is ``improved`` / ``flat`` /
``regressed`` against the established ±3% noise band (the same band every
paired-A/B acceptance in this repo uses; direction-aware — ``mfu`` up is
good, ``peak_live_bytes`` up is bad, and ``recompile_count`` regresses on
ANY increase: steady recompiles are a 0-contract, not a noisy rate). A
leg that ERRORED in the newest run but carried data in the baseline is a
regression too — a leg silently dying must not read as "no data, no
problem" — while a budget/sigterm ``skipped`` marker is the bench's
documented normal mode and simply yields no comparison. Exit code 1 when anything regressed (the CI wiring: a
lint-marked test pins this), 0 otherwise — including the not-enough-
history case, which reports itself honestly instead of blocking a young
repo's CI.

Output: one machine-readable JSON line on stdout, the human table on
stderr (the bench stdout contract). Reads through the shared torn-tail
``chaos.goodput.read_journal`` owner; never writes. Import-light: no
jax.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

from ..chaos.goodput import read_journal

__all__ = ["METRICS", "compare_runs", "group_runs", "main", "render_table"]

# (metric key aliases in priority order, higher_is_better, zero_band)
# zero_band metrics regress on ANY adverse move (steady recompiles are a
# 0-contract); banded metrics use the ±band noise tolerance.
METRICS: Tuple[Tuple[str, Tuple[str, ...], bool, bool], ...] = (
    ("tokens_per_s", ("tokens_per_sec_per_chip",
                      "decode_tokens_per_s_per_chip"), True, False),
    ("mfu", ("mfu",), True, False),
    ("peak_live_bytes", ("peak_live_bytes",), False, False),
    ("recompile_count", ("recompile_count",
                         "steady_recompile_count"), False, True),
)


def _metric_value(row: Dict[str, Any], aliases: Tuple[str, ...]
                  ) -> Optional[float]:
    for k in aliases:
        v = row.get(k)
        if isinstance(v, bool) or v is None:
            continue
        try:
            return float(v)
        except (TypeError, ValueError):
            continue
    return None


def group_runs(rows: List[dict]) -> List[Tuple[str, Dict[str, dict]]]:
    """History rows -> ordered (run_id, {leg name: row}) groups. File
    order IS run order (append-only history); rows without a run_id
    (pre-sentinel histories) group under "unstamped" so old files still
    parse. Within a run the last row per leg wins (a re-run leg)."""
    runs: List[Tuple[str, Dict[str, dict]]] = []
    for row in rows:
        if not isinstance(row, dict) or not row.get("name"):
            continue
        rid = str(row.get("run_id") or "unstamped")
        if not runs or runs[-1][0] != rid:
            runs.append((rid, {}))
        runs[-1][1][str(row["name"])] = row
    return runs


def _usable(row: dict) -> bool:
    return "error" not in row and "skipped" not in row


def compare_runs(runs: List[Tuple[str, Dict[str, dict]]], *,
                 band_pct: float = 3.0,
                 baseline_runs: int = 3) -> Dict[str, Any]:
    """Newest run vs the mean of up to ``baseline_runs`` trailing prior
    runs. Returns the summary dict (per-leg per-metric verdicts + the
    overall verdict); see the module docstring for verdict semantics."""
    if len(runs) < 2:
        return {"verdict": "insufficient-history", "runs": len(runs),
                "needed": 2, "legs": {}}
    newest_id, newest = runs[-1]
    window = runs[-(baseline_runs + 1):-1]
    legs: Dict[str, Any] = {}
    band = band_pct / 100.0
    for name, row in newest.items():
        base_rows = [r[name] for _, r in window
                     if name in r and _usable(r[name])]
        if not base_rows:
            continue  # a brand-new leg has no baseline yet
        if "skipped" in row:
            # budget/sigterm skips are the bench's documented NORMAL
            # mode under BENCH_BUDGET_S — no data is no comparison, not
            # a regression (a gate that reddens on routine budget skips
            # would flap on every boundary leg)
            continue
        if not _usable(row):
            legs[name] = {"verdict": "regressed",
                          "reason": "leg errored in the newest run but "
                                    "has baseline data",
                          "metrics": {}}
            continue
        metrics: Dict[str, Any] = {}
        worst = "flat"
        any_improved = False
        for label, aliases, higher, zero_band in METRICS:
            new_v = _metric_value(row, aliases)
            base_vals = [v for v in
                         (_metric_value(r, aliases) for r in base_rows)
                         if v is not None]
            if new_v is None or not base_vals:
                continue
            base = sum(base_vals) / len(base_vals)
            delta = new_v - base
            delta_pct = (100.0 * delta / abs(base)) if base else None
            adverse = (delta < 0) if higher else (delta > 0)
            if zero_band:
                verdict = ("regressed" if adverse and delta != 0 else
                           "improved" if delta != 0 else "flat")
            elif base == 0:
                verdict = ("regressed" if adverse and abs(delta) > 0 else
                           "flat")
            else:
                frac = abs(delta) / abs(base)
                verdict = ("flat" if frac <= band else
                           "regressed" if adverse else "improved")
            metrics[label] = {"new": new_v, "baseline": base,
                              "delta_pct": (round(delta_pct, 2)
                                            if delta_pct is not None
                                            else None),
                              "verdict": verdict}
            if verdict == "regressed":
                worst = "regressed"
            elif verdict == "improved":
                any_improved = True
        legs[name] = {
            "verdict": ("regressed" if worst == "regressed" else
                        "improved" if any_improved else "flat"),
            "metrics": metrics,
        }
    n_reg = sum(1 for l in legs.values() if l["verdict"] == "regressed")
    return {
        "verdict": ("regressed" if n_reg else
                    "ok" if legs else "no-comparable-legs"),
        "newest_run": newest_id,
        "baseline_window": [rid for rid, _ in window],
        "band_pct": band_pct,
        "runs": len(runs),
        "regressed": n_reg,
        "legs": legs,
    }


def render_table(summary: Dict[str, Any]) -> str:
    """The human view: one line per leg-metric, verdicts spelled out."""
    if summary["verdict"] == "insufficient-history":
        return (f"bench history holds {summary['runs']} run(s); the "
                f"sentinel needs >= 2 to compare")
    lines = [f"newest run {summary['newest_run']} vs baseline window "
             f"{summary['baseline_window']} (band ±{summary['band_pct']}%)"]
    header = f"{'leg':<34} {'metric':<16} {'new':>14} {'baseline':>14} " \
             f"{'delta%':>8}  verdict"
    lines += [header, "-" * len(header)]
    for name, leg in sorted(summary["legs"].items()):
        if not leg["metrics"]:
            lines.append(f"{name:<34} {'-':<16} {'-':>14} {'-':>14} "
                         f"{'-':>8}  {leg['verdict']}"
                         + (f" ({leg.get('reason')})"
                            if leg.get("reason") else ""))
        for label, m in leg["metrics"].items():
            d = "-" if m["delta_pct"] is None else f"{m['delta_pct']:+.2f}"
            lines.append(
                f"{name:<34} {label:<16} {m['new']:>14.4g} "
                f"{m['baseline']:>14.4g} {d:>8}  {m['verdict']}")
        lines.append(f"{name:<34} {'=> ' + leg['verdict']}")
    lines.append(f"overall: {summary['verdict']} "
                 f"({summary['regressed']} leg(s) regressed)")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> Tuple[Dict[str, Any], int]:
    ap = argparse.ArgumentParser(
        description="Compare the newest bench run in bench_history.jsonl "
                    "against a trailing baseline window; exit 1 when any "
                    "leg regressed beyond the noise band.")
    ap.add_argument("--history", default="bench_history.jsonl",
                    help="append-only per-leg history bench.py writes")
    ap.add_argument("--band_pct", type=float, default=3.0,
                    help="noise band (±%%) for rate/bytes metrics")
    ap.add_argument("--baseline_runs", type=int, default=3,
                    help="trailing prior runs averaged into the baseline")
    ap.add_argument("--json", action="store_true", dest="json_only",
                    help="suppress the human table (JSON line only)")
    ns = ap.parse_args(argv)
    rows = read_journal(ns.history)
    summary = compare_runs(group_runs(rows), band_pct=ns.band_pct,
                           baseline_runs=ns.baseline_runs)
    summary["history"] = ns.history
    if not ns.json_only:
        print(render_table(summary), file=sys.stderr, flush=True)
    print(json.dumps(summary), flush=True)
    return summary, (1 if summary["verdict"] == "regressed" else 0)


if __name__ == "__main__":
    sys.exit(main()[1])
