"""Replica transport contract: one ``ReplicaClient`` interface, two wires.

The r13 fleet hard-coded its transport: FILES in the replica dir (atomic-
rename mailboxes, beacon mtimes for liveness). That transport is proven —
a request that only ever lived in a socket buffer dies with the process,
while the journal + inbox/outbox survive any kill — but it pins every
replica to one host. This module extracts the router-side protocol behind
an interface so replicas can live anywhere a socket reaches:

* :class:`FileReplicaClient` — the r13 transport, verbatim semantics.
  Stays the tier-1 default; every durability invariant the fleet tests
  pin (consume-completions-first, replay-on-epoch-bump, atomic results)
  is this class.
* :class:`SocketReplicaClient` — length-prefixed JSON frames over TCP to
  a :class:`WorkerSocketEndpoint` the worker advertises in
  ``ctrl/endpoint.json``. Liveness is HEARTBEAT-based (the worker's main
  loop stamps each tick; a wedged loop answers heartbeats with a stale
  stamp, so ``beacon_age_s`` grows exactly like a stale beacon mtime).
  Torn frames and half-open connections degrade to the same path as a
  kill: the client drops the connection, the age grows past the router's
  ``stale_beacon_s`` gate, and the journaled request replays on a
  sibling once the attempt bumps.

Only the DATA plane moves over the socket (submit / drain / heartbeat).
The CONTROL plane — ``ready.json``, swap command/ack, ``current.json``
pins, stop flags, launcher beacons and attempt records — stays file-based
for BOTH transports, so the hot-swap state machine, the launcher's hang
watchdog, and ``chaos.goodput.aggregate_serving`` run unchanged.

Durability difference, documented not hidden: file results are deleted
only by the router, so a kill between "computed" and "consumed" loses
nothing; socket results drained but not yet ACKed are re-sent on the next
drain (the client acks batch N in the drain call for batch N+1), and
results still in a killed worker's memory are REPLAYED on a sibling —
token-identical under greedy decoding, the same guarantee replay always
had.

Import-light (stdlib only): the router/fleet process never pays for jax.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..chaos import goodput as goodput_lib

__all__ = [
    "ReplicaPaths", "ReplicaClient", "FileReplicaClient",
    "SocketReplicaClient", "WorkerSocketEndpoint", "TransportError",
    "write_json_atomic", "read_json_file", "send_frame", "recv_frame",
    "prefix_block_hashes",
]


# --------------------------------------------------------------- file layer

def write_json_atomic(path: str, payload: dict) -> None:
    """tmp-write + rename: a reader never sees a torn JSON file, and a
    writer killed mid-write leaves only a ``.tmp`` corpse behind."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(payload))
    os.replace(tmp, path)


def read_json_file(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    return payload if isinstance(payload, dict) else None


class ReplicaPaths:
    """Canonical file locations for one replica (root doubles as the
    launcher run dir, so beacons/attempts land next to the mailboxes)."""

    def __init__(self, fleet_dir: str, rid: int,
                 root: Optional[str] = None) -> None:
        self.rid = rid
        self.root = root or goodput_lib.replica_dir(fleet_dir, rid)
        self.inbox = os.path.join(self.root, "inbox")
        self.outbox = os.path.join(self.root, "outbox")
        self.ctrl = os.path.join(self.root, "ctrl")
        self.log_dir = os.path.join(self.root, "logs")
        self.ready_path = os.path.join(self.ctrl, "ready.json")
        self.stop_path = os.path.join(self.ctrl, "stop")
        self.swap_path = os.path.join(self.ctrl, "swap.json")
        self.swap_ack_path = os.path.join(self.ctrl, "swap_ack.json")
        self.current_path = os.path.join(self.ctrl, "current.json")
        # socket transport: the worker advertises its data-plane endpoint
        # here (host+port+attempt); the ctrl plane stays in these files
        self.endpoint_path = os.path.join(self.ctrl, "endpoint.json")

    @classmethod
    def at(cls, root: str, rid: int = 0) -> "ReplicaPaths":
        """Build from an existing replica root (the worker side only
        knows its own ``--fleet_worker_dir``, not the fleet dir)."""
        return cls("", rid, root=root)

    def ensure(self) -> "ReplicaPaths":
        for d in (self.root, self.inbox, self.outbox, self.ctrl):
            os.makedirs(d, exist_ok=True)
        return self

    def req_path(self, req_id: int) -> str:
        return os.path.join(self.inbox, f"req_{req_id:08d}.json")

    def result_path(self, req_id: int) -> str:
        return os.path.join(self.outbox, f"req_{req_id:08d}.json")


# ------------------------------------------------------------ prefix hashes

def prefix_block_hashes(tokens: Sequence[int], page_size: int,
                        max_blocks: int = 32) -> Tuple[int, ...]:
    """Cumulative CRC32 hashes of the page-aligned prefix blocks of a
    prompt — the routing-side twin of the paged-KV prefix cache's page
    granularity. ``hashes[i]`` identifies the first ``(i+1)*page_size``
    tokens, so two prompts share exactly ``k`` leading hashes iff they
    share ``k`` full cache pages. CRC32 (not ``hash()``) so the values
    are identical across processes regardless of PYTHONHASHSEED: the
    worker advertises them, the router compares them."""
    page = max(1, int(page_size))
    toks = [int(t) for t in tokens]
    out: List[int] = []
    h = 0
    for b in range(min(len(toks) // page, max_blocks)):
        block = toks[b * page:(b + 1) * page]
        h = zlib.crc32(",".join(map(str, block)).encode(), h)
        out.append(h)
    return tuple(out)


# ----------------------------------------------------------------- framing

class TransportError(ConnectionError):
    """Any data-plane failure: torn frame, half-open peer, refused
    connect, oversized frame. The client maps ALL of these to the same
    observable — a growing heartbeat age — so the router's health gate
    and replay path never need to know which wire failed how."""


MAX_FRAME_BYTES = 16 * 1024 * 1024  # a prompt is a few KB; 16MB is absurd
_HDR = struct.Struct(">I")          # 4-byte big-endian payload length


def send_frame(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode("utf-8")
    if len(data) > MAX_FRAME_BYTES:
        raise TransportError(f"frame too large: {len(data)} bytes")
    try:
        sock.sendall(_HDR.pack(len(data)) + data)
    except OSError as e:
        raise TransportError(f"send failed: {e}") from e


def _recv_exact(sock: socket.socket, n: int, *,
                header: bool = False) -> bytes:
    """Read exactly n bytes. A clean EOF before the FIRST header byte is
    a normal close (raises TransportError with ``clean=True`` flavor via
    empty message); anything torn mid-frame is a TransportError. An idle
    timeout with zero bytes read propagates as ``socket.timeout`` so a
    server loop can keep a quiet connection open."""
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            if header and not buf:
                raise  # idle, not torn: caller may retry
            raise TransportError(
                f"torn frame: timed out with {len(buf)}/{n} bytes")
        except OSError as e:
            raise TransportError(f"recv failed: {e}") from e
        if not chunk:
            if header and not buf:
                raise TransportError("peer closed")
            raise TransportError(f"torn frame: EOF at {len(buf)}/{n} bytes")
        buf += chunk
    return buf


def recv_frame(sock: socket.socket) -> dict:
    (length,) = _HDR.unpack(_recv_exact(sock, _HDR.size, header=True))
    if length > MAX_FRAME_BYTES:
        raise TransportError(f"frame too large: {length} bytes")
    try:
        payload = json.loads(_recv_exact(sock, length).decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise TransportError(f"bad frame payload: {e}") from e
    if not isinstance(payload, dict):
        raise TransportError("frame payload is not an object")
    return payload


# ------------------------------------------------------- client interface

class ReplicaClient:
    """Router-side view of one replica — the transport contract.

    Implementations must provide::

        alive()             -> bool        is anything still supervising it
        ready()             -> dict|None   worker's ready.json announcement
        beacon_age_s(now)   -> float|None  liveness age (None = not born)
        submit(payload)     -> None        deliver one request (may raise
                                           TransportError; the router
                                           reverts the placement)
        consume_results()   -> [dict]      drain finished results, at-least-
                                           once (the router dedups by id)
        prefix_index()      -> seq[int]    advertised prefix-cache hashes
        close()             -> None        release any wire state

    ``ready()`` is ALWAYS the ctrl-plane file: the attempt epoch it
    carries is what keys replay, and it must survive any data-plane
    outage."""

    def __init__(self, paths: ReplicaPaths,
                 alive_fn: Callable[[], bool] = lambda: True) -> None:
        self.paths = paths.ensure()
        self.rid = paths.rid
        self._alive_fn = alive_fn

    def alive(self) -> bool:
        """Whether anything still supervises this replica (a dead
        supervisor means no more restarts: the replica is gone for good)."""
        return bool(self._alive_fn())

    def ready(self) -> Optional[dict]:
        return read_json_file(self.paths.ready_path)

    def beacon_age_s(self, now: Optional[float] = None) -> Optional[float]:
        raise NotImplementedError

    def submit(self, payload: dict) -> None:
        raise NotImplementedError

    def consume_results(self) -> List[dict]:
        raise NotImplementedError

    def prefix_index(self) -> Sequence[int]:
        return ()

    def close(self) -> None:
        pass


class FileReplicaClient(ReplicaClient):
    """The r13 file transport: submit into the replica's inbox, consume
    its outbox, liveness from beacon mtimes. Results are deleted only by
    this reader, so a worker kill between "computed" and "consumed" loses
    nothing."""

    def beacon_age_s(self, now: Optional[float] = None) -> Optional[float]:
        mtimes = goodput_lib.beacon_mtimes(self.paths.root)
        if not mtimes:
            return None
        return max(0.0, (now if now is not None else time.time())
                   - max(mtimes.values()))

    def submit(self, payload: dict) -> None:
        write_json_atomic(self.paths.req_path(int(payload["id"])), payload)

    def consume_results(self) -> List[dict]:
        import glob
        out = []
        for path in sorted(glob.glob(
                os.path.join(self.paths.outbox, "req_*.json"))):
            payload = read_json_file(path)
            if payload is None:
                continue  # torn writes impossible (atomic rename); a
                # vanished file was consumed by a competing reader
            out.append(payload)
            try:
                os.unlink(path)
            except OSError:
                pass
        return out

    def prefix_index(self) -> Sequence[int]:
        beacon = read_json_file(goodput_lib.beacon_path(self.paths.root, 0))
        if beacon is None:
            return ()
        return beacon.get("prefix_index") or ()


class SocketReplicaClient(ReplicaClient):
    """TCP data plane to a :class:`WorkerSocketEndpoint`.

    One persistent connection, reconnected on any error. Heartbeats carry
    the worker's last main-loop tick stamp, so ``beacon_age_s`` measures
    the same thing beacon mtimes do — loop liveness, not just process
    liveness (a wedged worker's endpoint thread still answers, with a
    stale stamp). Heartbeat replies are cached for ``hb_cache_s`` because
    the router's placement gate runs per pending request per poll.

    Drain is at-least-once: the reply keeps results buffered worker-side
    until the NEXT drain acks their ids, so a reply torn mid-frame is
    re-sent rather than lost; the router's duplicate-result accounting
    absorbs any re-delivery."""

    def __init__(self, paths: ReplicaPaths,
                 alive_fn: Callable[[], bool] = lambda: True, *,
                 connect_timeout_s: float = 0.5,
                 io_timeout_s: float = 5.0,
                 hb_cache_s: float = 0.05) -> None:
        super().__init__(paths, alive_fn)
        self.connect_timeout_s = connect_timeout_s
        self.io_timeout_s = io_timeout_s
        self.hb_cache_s = hb_cache_s
        self._sock: Optional[socket.socket] = None
        self._pending_ack: List[int] = []
        self._hb_cache: Optional[Tuple[float, Optional[dict]]] = None
        self._last_tick: Optional[float] = None  # newest worker tick stamp
        self._first_fail_t: Optional[float] = None

    # wire plumbing -----------------------------------------------------

    def _drop_conn(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _conn(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        ep = read_json_file(self.paths.endpoint_path)
        if ep is None or "port" not in ep:
            raise TransportError("no endpoint advertised")
        try:
            s = socket.create_connection(
                (ep.get("host", "127.0.0.1"), int(ep["port"])),
                timeout=self.connect_timeout_s)
        except OSError as e:
            raise TransportError(f"connect failed: {e}") from e
        s.settimeout(self.io_timeout_s)
        self._sock = s
        return s

    def _call(self, msg: dict) -> dict:
        try:
            s = self._conn()
            send_frame(s, msg)
            reply = recv_frame(s)
        except socket.timeout as e:
            self._drop_conn()
            raise TransportError(f"timed out: {e}") from e
        except TransportError:
            self._drop_conn()
            raise
        if not reply.get("ok"):
            raise TransportError(
                f"replica refused {msg.get('op')!r}: {reply.get('error')}")
        return reply

    # contract ----------------------------------------------------------

    def _heartbeat(self) -> Optional[dict]:
        mono = time.monotonic()
        if (self._hb_cache is not None
                and mono - self._hb_cache[0] < self.hb_cache_s):
            return self._hb_cache[1]
        try:
            reply = self._call({"op": "hb"})
        except TransportError:
            if self._first_fail_t is None:
                self._first_fail_t = time.time()
            self._hb_cache = (mono, None)
            return None
        self._first_fail_t = None
        self._last_tick = float(reply.get("t_tick") or 0.0) or None
        self._hb_cache = (mono, reply)
        return reply

    def beacon_age_s(self, now: Optional[float] = None) -> Optional[float]:
        wall = now if now is not None else time.time()
        hb = self._heartbeat()
        if hb is not None and self._last_tick is not None:
            return max(0.0, wall - self._last_tick)
        # unreachable: age from the last good tick stamp, else from the
        # advertised endpoint's birth, else from the first failure we
        # observed — None ("not born yet") only before any endpoint exists
        if self._last_tick is not None:
            return max(0.0, wall - self._last_tick)
        ep = read_json_file(self.paths.endpoint_path)
        if ep is not None:
            return max(0.0, wall - float(ep.get("t") or wall))
        if self._first_fail_t is not None:
            return max(0.0, wall - self._first_fail_t)
        return None

    def submit(self, payload: dict) -> None:
        self._call({"op": "submit", "req": payload})

    def consume_results(self) -> List[dict]:
        try:
            reply = self._call({"op": "drain", "ack": self._pending_ack})
        except TransportError:
            return []  # un-acked results stay buffered worker-side
        results = [r for r in reply.get("results", [])
                   if isinstance(r, dict)]
        self._pending_ack = [int(r.get("id", -1)) for r in results]
        return results

    def prefix_index(self) -> Sequence[int]:
        hb = self._heartbeat()
        if hb is None:
            return ()
        return hb.get("prefix_index") or ()

    def close(self) -> None:
        self._drop_conn()


# ------------------------------------------------------- worker endpoint

class WorkerSocketEndpoint:
    """Worker-side data plane for :class:`SocketReplicaClient`: a
    background thread serving submit/drain/hb frames on a loopback-bound
    ephemeral port, advertised atomically in ``ctrl/endpoint.json``.

    The worker's MAIN loop stays the owner of all work: it calls
    :meth:`take_submits` / :meth:`queue_result` / :meth:`tick` exactly
    where the file transport polled its mailboxes. The endpoint thread
    only buffers — so a wedged main loop stops calling ``tick`` and every
    heartbeat reply carries the stale stamp that health-gates the replica
    out (and eventually trips the file-beacon hang watchdog, which kills
    the process and triggers journal replay: identical fault path)."""

    def __init__(self, paths: ReplicaPaths, replica_id: int,
                 attempt: int, host: str = "127.0.0.1") -> None:
        self.paths = paths
        self.replica_id = replica_id
        self.attempt = attempt
        self._lock = threading.Lock()
        self._submits: List[dict] = []
        self._results: Dict[int, dict] = {}  # popped only on client ack
        self._t_tick = time.time()
        self._hb_extra: dict = {}
        self._stop = False
        self._srv = socket.create_server((host, 0))
        self._srv.settimeout(0.25)
        self.port = self._srv.getsockname()[1]
        write_json_atomic(paths.endpoint_path, {
            "host": host, "port": self.port, "attempt": attempt,
            "replica": replica_id, "t": time.time()})
        self._thread = threading.Thread(
            target=self._serve, daemon=True,
            name=f"replica{replica_id}-endpoint")
        self._thread.start()

    # main-loop side ----------------------------------------------------

    def take_submits(self) -> List[dict]:
        with self._lock:
            out, self._submits = self._submits, []
        return out

    def queue_result(self, payload: dict) -> None:
        with self._lock:
            self._results[int(payload["id"])] = payload

    def tick(self, t: Optional[float] = None,
             extra: Optional[dict] = None) -> None:
        with self._lock:
            self._t_tick = t if t is not None else time.time()
            if extra:
                self._hb_extra.update(extra)

    def close(self) -> None:
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass
        try:
            os.unlink(self.paths.endpoint_path)
        except OSError:
            pass
        self._thread.join(timeout=2.0)

    # endpoint-thread side ----------------------------------------------

    def _serve(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True,
                             name=f"replica{self.replica_id}-conn").start()

    def _handle(self, conn: socket.socket) -> None:
        conn.settimeout(0.5)
        try:
            while not self._stop:
                try:
                    msg = recv_frame(conn)
                except socket.timeout:
                    continue  # idle connection: keep it open
                except TransportError:
                    return  # torn/closed: drop the connection, keep state
                try:
                    send_frame(conn, self._reply(msg))
                except TransportError:
                    return  # un-acked results survive for the next drain
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _reply(self, msg: dict) -> dict:
        op = msg.get("op")
        if op == "submit":
            req = msg.get("req")
            if not isinstance(req, dict) or "id" not in req:
                return {"ok": False, "error": "malformed submit"}
            with self._lock:
                self._submits.append(req)
            return {"ok": True}
        if op == "drain":
            with self._lock:
                for rid in msg.get("ack") or []:
                    try:
                        self._results.pop(int(rid), None)
                    except (TypeError, ValueError):
                        pass
                results = [self._results[k]
                           for k in sorted(self._results)]
            return {"ok": True, "results": results}
        if op == "hb":
            with self._lock:
                return {"ok": True, "t_tick": self._t_tick,
                        "attempt": self.attempt,
                        "replica": self.replica_id, **self._hb_extra}
        return {"ok": False, "error": f"unknown op {op!r}"}
