"""Serving subsystem: prefill/decode AOT split, paged KV cache, a
continuous-batching scheduler, and the multi-replica resilience layer
(ROADMAP open item 2 — the "millions of users, heavy traffic" direction).

Layers, bottom up:

* :mod:`.paged_kv`   — pure-XLA page ops (scatter/gather against a shared
  page pool + block tables), the host-side :class:`PageManager`, and the
  refcounted :class:`PrefixCache` (requests sharing a prompt prefix reuse
  paged-KV pages);
* :mod:`.engine`     — :class:`DecodeEngine`: ``prefill`` and
  ``decode_step`` as two separately AOT-compiled executables with pinned
  shardings and per-slot positions, over the paged cache;
* :mod:`.scheduler`  — :class:`DecodeServer`: continuous batching (admit
  into free slots every step, decode always at the compiled slot count),
  count-based completion, lagged token fetch so host bookkeeping overlaps
  device steps, and TTFT/throughput gauges;
* :mod:`.traffic`    — seeded, deterministic arrival-process generators
  (Poisson / bursty / diurnal) for SLO-under-load benches;
* :mod:`.fleet`      — replica file protocol + :class:`ServingFleet`:
  N replicas, each its own supervised launcher ring (restart budget,
  backoff, beacon-mtime hang watchdog), plus zero-downtime checkpoint
  hot-swap;
* :mod:`.router`     — :class:`Router`: health-gated, load-aware
  placement with a durable request journal; in-flight requests on a dead
  or wedged replica replay on a sibling.

Entry points: ``run/serve.py`` serves a prompt stream (single replica or
``--replicas N`` fleet); ``run/sample.py`` routes one-shot GPT-2 decoding
through :func:`one_shot_decode` — one code path for one-shot and served
decode.

This ``__init__`` is LAZY (PEP 562): ``traffic``/``fleet``/``router`` are
jax-free on purpose — the fleet supervisor and router run in a process
that never imports jax (only replica workers pay it) — so the package
must not import the jax-heavy engine/scheduler until someone asks for
those names.
"""

_LAZY = {
    "DecodeEngine": ".engine",
    "TRASH_PAGE": ".paged_kv",
    "PageManager": ".paged_kv",
    "PrefixCache": ".paged_kv",
    "gather_kv": ".paged_kv",
    "write_prompt_kv": ".paged_kv",
    "write_token_kv": ".paged_kv",
    "DecodeServer": ".scheduler",
    "Request": ".scheduler",
    "one_shot_decode": ".scheduler",
    "TrafficGenerator": ".traffic",
    "ServingFleet": ".fleet",
    "Router": ".router",
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(_LAZY[name], __name__)
        value = getattr(mod, name)
        globals()[name] = value  # cache: next access skips this hook
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
