"""Serving subsystem: prefill/decode AOT split, paged KV cache, and a
continuous-batching scheduler (ROADMAP open item 1 — the "millions of
users, heavy traffic" direction).

Layers, bottom up:

* :mod:`.paged_kv`   — pure-XLA page ops (scatter/gather against a shared
  page pool + block tables) and the host-side :class:`PageManager`;
* :mod:`.engine`     — :class:`DecodeEngine`: ``prefill`` and
  ``decode_step`` as two separately AOT-compiled executables with pinned
  shardings and per-slot positions, over the paged cache;
* :mod:`.scheduler`  — :class:`DecodeServer`: continuous batching (admit
  into free slots every step, decode always at the compiled slot count),
  count-based completion, lagged token fetch so host bookkeeping overlaps
  device steps, and TTFT/throughput gauges.

Entry points: ``run/serve.py`` serves a prompt stream; ``run/sample.py``
routes one-shot GPT-2 decoding through :func:`one_shot_decode` — one code
path for one-shot and served decode.
"""

from .engine import DecodeEngine
from .paged_kv import TRASH_PAGE, PageManager, gather_kv, write_prompt_kv, \
    write_token_kv
from .scheduler import DecodeServer, Request, one_shot_decode

__all__ = [
    "DecodeEngine", "DecodeServer", "Request", "PageManager", "TRASH_PAGE",
    "gather_kv", "write_prompt_kv", "write_token_kv", "one_shot_decode",
]
