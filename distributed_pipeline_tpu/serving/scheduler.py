"""DecodeServer: continuous batching over the prefill/decode engine.

``run/sample.py`` (pre-serving) ran generation in LOCKSTEP batches: every
prompt starts together, the batch ends when the longest generation ends,
and a new request waits for the whole batch to drain. A serving loop keeps
the compiled decode batch FULL instead: every step, queued requests are
admitted into whatever slots are free (prefill batched opportunistically),
decode always runs at the compiled slot count with an active mask, and a
finished request frees its slot and pages immediately for the next one.

Host/device split (the async-dispatch pattern from the trainer's lagged
metrics, PR 5): the host dispatches decode step N, then fetches step N-1's
token vector — blocking on N-1 while N executes, so scheduler bookkeeping
(admission, page accounting, output assembly) overlaps device time instead
of serializing behind it. Completion is COUNT-based (each request's
generation budget is known at admission), so the host never has to sync on
content to schedule; an optional EOS id finishes a request early, observed
one lagged step late by construction.

Invariants the tests pin (tests/test_serving.py):

* no slot or page leaks — after drain, every slot is free and the page
  pool is back to full;
* bounded completion — pages for a request's WORST CASE (prompt + budget)
  are reserved at admission, so an admitted request can always run to
  completion without preempting anyone;
* late arrivals preempt nothing — an admission only ever touches free
  slots/pages, so in-flight requests' outputs are unchanged (greedy
  decode: token-for-token).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import time
from typing import Any, Deque, List, Optional

import jax
import numpy as np

from ..utils.perf import EventStats, RecompileMonitor, SanitizeReport, \
    device_peak_flops
from ..utils.perf import transformer_decode_flops_per_token \
    as decode_flops_per_token
from .engine import DecodeEngine
from .paged_kv import TRASH_PAGE, PageManager, PrefixCache
from .spec import DRAFT_KINDS, ngram_propose, truncated_draft

__all__ = ["Request", "DecodeServer", "one_shot_decode"]


@dataclasses.dataclass
class Request:
    """One generation request and its lifecycle bookkeeping."""

    id: int
    prompt: np.ndarray              # int32 [prompt_len]
    max_new_tokens: int
    g_max: int = 0                  # tokens this request WILL generate
    # (min(max_new_tokens, max_len - prompt_len), fixed at submit — the
    # single cap admission, release, and fetch-truncation all share)
    eos_id: Optional[int] = None
    submit_t: float = 0.0
    tokens: List[int] = dataclasses.field(default_factory=list)
    ttft_s: Optional[float] = None  # submit -> first token FETCHED
    finished: bool = False          # output collection complete

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])


@dataclasses.dataclass
class _SlotState:
    """Host mirror of one decode slot (no device fetch needed to
    schedule): dispatch-side generation count and position."""

    req: Request
    pages: np.ndarray               # page ids reserved for this request
    generated: int = 1              # prefill produced token #1
    position: int = 0               # index of the token currently in state


class DecodeServer:
    """Continuous-batching decode service over a :class:`DecodeEngine`.

    ``submit()`` enqueues requests; ``step()`` advances the world by one
    decode step (admitting first, fetching last); ``drain()`` runs until
    everything submitted has completed. ``sanitize=True`` mirrors the
    trainer's runtime sanitizer: every XLA compile counts into
    ``recompile_count`` (steady state must freeze it — the two phase
    executables compile exactly once) and dispatches run under
    ``jax.transfer_guard("disallow")``.

    ``spec_tokens = K > 0`` turns on SPECULATIVE decoding: each round a
    draft (``spec_draft``: host-side "ngram" prompt-lookup, or "model" — an
    early-exit engine over the target's first ``draft_layers`` blocks)
    proposes K tokens per slot and ONE verify dispatch yields the target's
    pick at every link (serving/spec.py for the acceptance contract —
    greedy output is token-identical to the non-speculative path). Spec
    rounds are synchronous (the verify result IS next round's input), so
    ``dispatch_lag`` overlap doesn't apply; the win is K+1 target steps
    per dispatch, paid back at the accept rate.
    """

    def __init__(self, workload, params, *, decode_slots: int = 8,
                 page_size: int = 16, max_pages: int = 0,
                 max_prompt_len: int = 0, max_len: int = 0,
                 prefill_batch: int = 0, decode_span: int = 1,
                 temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0, seed: int = 0,
                 rng: Optional[jax.Array] = None, eos_id: Optional[int] = None,
                 mesh=None, sanitize: bool = False,
                 dispatch_lag: int = 1,
                 prefix_cache: bool = False,
                 decode_impl: str = "auto", kv_quant: str = "fp",
                 spec_tokens: int = 0, spec_draft: str = "ngram",
                 draft_layers: int = 2) -> None:
        max_len = max_len or workload.seq_len
        max_prompt_len = max_prompt_len or max(2, max_len // 2)
        pages_per_slot = -(-max_len // page_size)
        if max_pages <= 0:
            # full residency default: every slot can hold max_len — the
            # pool-smaller-than-worst-case regime is opt-in via max_pages
            max_pages = 1 + decode_slots * pages_per_slot
        self.sanitize = sanitize
        self._recompiles = RecompileMonitor(capture_sites=sanitize)
        # Evidence sidecar (ISSUE 19 runtime bridge): guard trips and
        # steady-state recompiles accumulate here; run/serve.py finalizes
        # with write_sanitize_report() so the static pass can
        # cross-reference (analysis --runtime-evidence, GL013).
        self.sanitize_report = SanitizeReport()
        self._recompiles_at_first_token: Optional[int] = None
        self._sanitizer_reported = False
        if sanitize:
            self._recompiles.install()
        if spec_tokens > 0 and spec_draft not in DRAFT_KINDS:
            raise ValueError(f"spec_draft must be one of {DRAFT_KINDS}, "
                             f"got {spec_draft!r}")
        self.spec_tokens = spec_tokens
        self.spec_draft = spec_draft
        self._draft_layers = draft_layers
        try:
            self.engine = DecodeEngine(
                workload, params, decode_slots=decode_slots,
                page_size=page_size, max_pages=max_pages,
                max_prompt_len=max_prompt_len, max_len=max_len,
                prefill_batch=prefill_batch, decode_span=decode_span,
                temperature=temperature,
                top_k=top_k, top_p=top_p, rng=rng, seed=seed, mesh=mesh,
                transfer_guard=sanitize, decode_impl=decode_impl,
                kv_quant=kv_quant, spec_tokens=spec_tokens)
            self._draft_engine: Optional[DecodeEngine] = None
            self._draft_fpt = 0.0
            if spec_tokens > 0 and spec_draft == "model":
                # Early-exit draft over the target's first draft_layers
                # blocks, on a STATIC full-residency pool: slot s owns
                # pages [1 + s*pps, 1 + (s+1)*pps) forever, so the draft
                # needs no allocator and rollback is just the host state
                # push each round (accepted draft K/V is valid by the
                # acceptance rule: d_j == g_{j-1}).
                dwl, dparams = truncated_draft(workload, params,
                                               draft_layers)
                pps = self.engine.pages_per_slot
                self._draft_engine = DecodeEngine(
                    dwl, dparams, decode_slots=decode_slots,
                    page_size=page_size,
                    max_pages=1 + decode_slots * pps,
                    max_prompt_len=max_prompt_len, max_len=max_len,
                    prefill_batch=prefill_batch, decode_span=1,
                    temperature=0.0, seed=seed, mesh=mesh,
                    transfer_guard=sanitize, decode_impl=decode_impl,
                    kv_quant=kv_quant)
                self._draft_tables = np.arange(
                    1, 1 + decode_slots * pps,
                    dtype=np.int32).reshape(decode_slots, pps)
                self._draft_engine.set_block_tables(self._draft_tables)
                self._draft_fpt = decode_flops_per_token(
                    dwl.param_count(dparams))
        except BaseException:
            self._recompiles.uninstall()  # failed build must not leak the
            raise                         # process-global 'jax' log handler
        self.mgr = PageManager(max_pages, page_size)
        # Shared-prefix page reuse (ISSUE 11 satellite): requests whose
        # prompts open with the same token run share the pages holding
        # that prefix's K/V (refcounted — see PrefixCache for why replay/
        # eviction can never free a page a live slot still reads).
        self.prefix = PrefixCache(self.mgr) if prefix_cache else None
        s = decode_slots
        self.block_tables = np.zeros((s, self.engine.pages_per_slot),
                                     np.int32)  # all TRASH_PAGE
        self.active = np.zeros((s,), np.int32)
        self.slots: List[Optional[_SlotState]] = [None] * s
        self.queue: Deque[Request] = collections.deque()
        self.default_eos_id = eos_id
        self.dispatch_lag = max(0, dispatch_lag)
        # lagged fetch ring: (device tokens handle, [(slot, Request)] whose
        # token in that vector is NEW)
        self._ring: Deque[Any] = collections.deque()
        self._dirty = False     # block tables / active changed since put
        self._needs_sweep = False  # a fetch EOS-finished a request whose
        # slot is still held (count-based completions release inline)
        self._req_counter = 0
        self.ttft = EventStats()
        self.decode_steps = 0
        self.prefill_steps = 0
        self.tokens_fetched = 0
        # Cost-ledger occupancy/padding counters: actual vs padded
        # prompt tokens per prefill dispatch, and active vs compiled
        # slot-steps per decode dispatch — the serving-side
        # padding_waste_frac inputs (obs/ledger.py).
        self.workload = workload
        self.prompt_tokens_prefilled = 0
        self.prefill_token_slots = 0
        self.slot_steps_active = 0
        self.slot_steps_total = 0
        # Speculative gauges: per-round draft proposals vs matches (the
        # fleet accept_rate surface) — every FETCHED token still counts
        # through tokens_fetched, which in spec mode is by definition the
        # accepted-token count.
        self.spec_rounds = 0
        self.draft_proposed = 0
        self.draft_accepted = 0

    # ----------------------------------------------------------- gauges etc.

    @property
    def compile_time_s(self) -> float:
        return self.engine.compile_time_s

    @property
    def recompile_count(self) -> int:
        return self._recompiles.count

    def stop_sanitizer(self) -> int:
        """Detach the process-global sanitizer hooks; returns the final
        compile count. Idempotent; no-op when sanitize was off. Compiles
        observed after the first fetched token (the serving steady-state
        boundary — both phase executables exist by then) become
        ``steady_recompile`` violations in the evidence report."""
        self._recompiles.uninstall()
        if self.sanitize and not self._sanitizer_reported:
            self._sanitizer_reported = True
            if self._recompiles_at_first_token is not None:
                self.sanitize_report.note_recompiles(
                    self._recompiles, self._recompiles_at_first_token)
        return self._recompiles.count

    def write_sanitize_report(self, out_dir: str) -> str:
        """Finalize the evidence (stop_sanitizer, folding steady
        recompiles in) and drop the sanitize_report.json sidecar in
        ``out_dir``. Returns the written path, "" when sanitize was off
        or the write failed (best-effort by design)."""
        if not self.sanitize:
            return ""
        self.stop_sanitizer()
        return self.sanitize_report.write(out_dir)

    def set_params(self, params) -> None:
        """Hot-swap surface: replace the target's weights AND rebuild the
        model draft's early-exit views from the swapped tree (the draft
        leaves are references into ``params``, so this is re-indexing,
        not a second restore). Callers that poke ``engine.params``
        directly would leave a model draft proposing from stale weights —
        harmless for correctness (every token is target-verified) but a
        silent accept-rate regression."""
        self.engine.params = params
        if self._draft_engine is not None:
            _, dparams = truncated_draft(self.workload, params,
                                         self._draft_layers)
            self._draft_engine.params = dparams

    @property
    def free_slots(self) -> int:
        return sum(1 for s in self.slots if s is None)

    @property
    def busy(self) -> bool:
        """Anything queued, in flight, or awaiting fetch."""
        return bool(self.queue or any(s is not None for s in self.slots)
                    or self._ring)

    @property
    def time_to_first_token_s(self) -> float:
        """Mean submit->first-token latency over completed TTFTs."""
        return self.ttft.summary()["mean"]

    def reset_stats(self) -> None:
        """Zero the serving gauges (bench: warmup vs timed window)."""
        self.ttft = EventStats()
        self.decode_steps = 0
        self.prefill_steps = 0
        self.tokens_fetched = 0
        self.prompt_tokens_prefilled = 0
        self.prefill_token_slots = 0
        self.slot_steps_active = 0
        self.slot_steps_total = 0
        self.spec_rounds = 0
        self.draft_proposed = 0
        self.draft_accepted = 0

    @property
    def accept_rate(self) -> float:
        """Fraction of proposed draft tokens the target accepted."""
        return (self.draft_accepted / self.draft_proposed
                if self.draft_proposed > 0 else 0.0)

    def prefix_stats(self) -> dict:
        """Prefix-cache gauges (empty dict when the cache is off)."""
        return self.prefix.stats() if self.prefix is not None else {}

    def cost_ledger(self, *, wall_s: float, n_devices: int = 1) -> dict:
        """Per-executable cost-ledger rows (obs/ledger.py) for the two
        serving phases. The DECODE row carries the full roofline MFU-gap
        attribution — tokens/s over ``wall_s`` against the forward-only
        2N FLOPs/token roofline, slot-occupancy waste as its padding
        term — while the PREFILL row carries the extraction plus the
        prompt-padding waste (prefill runs at the compiled
        [prefill_batch, max_prompt_len] shape regardless of actual
        prompt lengths). ``n_devices`` defaults to 1: decode state is
        replicated, so the service rate IS the per-chip rate (the
        measure_decode rationale)."""
        from ..obs import ledger as ledger_lib

        n_params = self.workload.param_count(self.engine.params)
        fpt = decode_flops_per_token(n_params)
        device_kind = getattr(jax.devices()[0], "device_kind", "cpu")
        rows: dict = {}
        for name, aot in self.engine.executables().items():
            if aot.compiled is None:
                continue
            row = {"program": f"serve_{name}",
                   **ledger_lib.extract_cost(aot.compiled)}
            if name == "decode":
                tokens_per_s = (self.tokens_fetched / wall_s
                                if wall_s > 0 else 0.0)
                steps_per_s = (self.decode_steps / wall_s
                               if wall_s > 0 else 0.0)
                occupancy_waste = (
                    1.0 - self.slot_steps_active / self.slot_steps_total
                    if self.slot_steps_total > 0 else 0.0)
                row.update({
                    "flops_per_token": fpt,
                    "n_params": n_params,
                    "tokens_per_s": tokens_per_s,
                    "steps_per_s": steps_per_s,
                    "decode_span": self.engine.decode_span,
                    # page-pool residency gauge: the int8 KV criterion is
                    # ledger-verified (int8 arm <= 0.55x fp at equal
                    # geometry)
                    "kv_pool_bytes": self.engine.kv_pool_bytes(),
                    "kv_quant": self.engine.kv_quant,
                })
                if self.spec_tokens > 0:
                    tf = max(1, self.tokens_fetched)
                    # draft-flops accounting: what the device ACTUALLY
                    # spent per fetched (= accepted) token — verify runs
                    # K+1 target steps per round and the draft its own
                    # model (0 flops for ngram) — so the roofline stays
                    # honest about speculative overhead
                    row.update({
                        "spec_tokens": self.spec_tokens,
                        "spec_draft": self.spec_draft,
                        "accept_rate": self.accept_rate,
                        "accepted_tokens_per_s": tokens_per_s,
                        "accepted_tokens_per_s_per_chip":
                            tokens_per_s / max(1, n_devices),
                        "draft_flops_per_token": self._draft_fpt,
                        "spec_flops_per_fetched_token":
                            fpt * self.slot_steps_active / tf
                            + self._draft_fpt * self.draft_proposed / tf,
                    })
                row.update(ledger_lib.roofline_attribution(
                    tokens_per_s=tokens_per_s, flops_per_token=fpt,
                    peak_flops=device_peak_flops(), n_devices=n_devices,
                    steps_per_s=steps_per_s,
                    collective_bytes_per_step=row.get(
                        "collective_bytes_per_step", 0.0),
                    bytes_accessed=row.get("bytes_accessed", 0.0),
                    device_kind=device_kind,
                    padding_waste_frac=occupancy_waste))
            else:
                row["padding_waste_frac"] = (
                    1.0 - self.prompt_tokens_prefilled
                    / self.prefill_token_slots
                    if self.prefill_token_slots > 0 else 0.0)
            rows[f"serve_{name}"] = row
        return rows

    # ------------------------------------------------------------ lifecycle

    def set_rng(self, key: jax.Array) -> None:
        self.engine.set_rng(key)

    def submit(self, prompt: np.ndarray, max_new_tokens: int,
               eos_id: Optional[int] = None) -> Request:
        prompt = np.ascontiguousarray(prompt, np.int32).ravel()
        if not 1 <= prompt.shape[0] <= self.engine.max_prompt_len:
            raise ValueError(
                f"prompt length {prompt.shape[0]} outside [1, "
                f"max_prompt_len={self.engine.max_prompt_len}]")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        g_max = min(max_new_tokens,
                    self.engine.max_len - int(prompt.shape[0]))
        if g_max < 1:
            raise ValueError(
                f"prompt of {prompt.shape[0]} tokens leaves no room to "
                f"generate under max_len={self.engine.max_len}")
        total = prompt.shape[0] + g_max
        if self.mgr.pages_for(total) > self.mgr.capacity:
            raise ValueError(
                f"request needs {self.mgr.pages_for(total)} pages but the "
                f"pool holds {self.mgr.capacity}; raise max_pages or lower "
                f"max_new_tokens")
        self._req_counter += 1
        req = Request(id=self._req_counter, prompt=prompt,
                      max_new_tokens=max_new_tokens, g_max=g_max,
                      eos_id=self.default_eos_id if eos_id is None else eos_id,
                      submit_t=time.perf_counter())
        self.queue.append(req)
        return req

    def submit_prefilled(self, prompt: np.ndarray, max_new_tokens: int, *,
                         first_token: int, kv_pages: dict,
                         eos_id: Optional[int] = None,
                         submit_t: Optional[float] = None
                         ) -> Optional[Request]:
        """Admit a request whose prefill ran on ANOTHER engine (the
        disaggregated serving path, mpmd/disagg.py): ``kv_pages`` is an
        ``DecodeEngine.extract_pages`` payload covering the prompt's
        ``pages_for(prompt_len)`` pages, ``first_token`` the token the
        prefill worker already picked at ``position = prompt_len``.

        Unlike :meth:`submit` this admits IMMEDIATELY (no queue): the KV
        payload is only valid against the page ids allocated here, so
        deferring admission would mean holding the payload host-side
        anyway — returning None (no free slot / pool exhausted) pushes
        the backpressure onto the caller's StageLink instead, which is
        the flow-control channel the transfer already has. Pages come
        straight from the PageManager (never the prefix cache: the
        transferred pages hold remote state the local prefill executable
        never wrote, so publishing them as a shareable prefix would hand
        sharers pages this server cannot reproduce)."""
        prompt = np.ascontiguousarray(prompt, np.int32).ravel()
        if not 1 <= prompt.shape[0] <= self.engine.max_prompt_len:
            raise ValueError(
                f"prompt length {prompt.shape[0]} outside [1, "
                f"max_prompt_len={self.engine.max_prompt_len}]")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        g_max = min(max_new_tokens,
                    self.engine.max_len - int(prompt.shape[0]))
        if g_max < 1:
            raise ValueError(
                f"prompt of {prompt.shape[0]} tokens leaves no room to "
                f"generate under max_len={self.engine.max_len}")
        total = prompt.shape[0] + g_max
        if self.mgr.pages_for(total) > self.mgr.capacity:
            raise ValueError(
                f"request needs {self.mgr.pages_for(total)} pages but the "
                f"pool holds {self.mgr.capacity}; raise max_pages or lower "
                f"max_new_tokens")
        n_filled = self.mgr.pages_for(prompt.shape[0])
        got = {k: v.shape[0] for k, v in kv_pages.items()}
        if any(n != n_filled for n in got.values()):
            raise ValueError(f"kv_pages rows {got} != pages_for(prompt_len)"
                             f"={n_filled}")
        free = [s for s in range(len(self.slots)) if self.slots[s] is None]
        if not free:
            return None
        pages = self.mgr.alloc(self.mgr.pages_for(total))
        if pages is None:
            return None
        slot = free[0]
        self._req_counter += 1
        req = Request(id=self._req_counter, prompt=prompt,
                      max_new_tokens=max_new_tokens, g_max=g_max,
                      eos_id=self.default_eos_id if eos_id is None else eos_id,
                      submit_t=(time.perf_counter() if submit_t is None
                                else submit_t))
        self.engine.ingest_pages(pages[:n_filled], kv_pages)
        self.engine.set_slot_state(slot, first_token, req.prompt_len)
        self.block_tables[slot, :] = TRASH_PAGE
        self.block_tables[slot, :len(pages)] = pages
        self.active[slot] = 1
        self.slots[slot] = _SlotState(req=req, pages=pages,
                                      position=req.prompt_len)
        self._dirty = True
        # the transferred first token is this request's first FETCHED
        # token too (the colocated path attributes it from the prefill
        # ring; there is no local prefill dispatch to ride here)
        now = time.perf_counter()
        req.tokens.append(int(first_token))
        self.tokens_fetched += 1
        req.ttft_s = max(0.0, now - req.submit_t)
        self.ttft.add(req.ttft_s)
        if req.eos_id is not None and int(first_token) == req.eos_id:
            req.finished = True
        elif len(req.tokens) >= req.g_max:
            req.finished = True
        if req.finished or req.g_max <= 1:
            self._release(slot)
        return req

    def _release(self, slot: int) -> None:
        st = self.slots[slot]
        if st is None:
            return
        if self.prefix is not None:
            # shared prefix pages stay cache-resident for the next
            # sharer; only the slot's private tail frees now
            to_free = self.prefix.release(st.req.prompt, st.pages)
            if to_free.size:
                self.mgr.free(to_free)
        else:
            self.mgr.free(st.pages)
        self.block_tables[slot, :] = TRASH_PAGE
        self.active[slot] = 0
        self.slots[slot] = None
        self._dirty = True

    def _reserve_pages(self, req: Request) -> Optional[np.ndarray]:
        """All-or-nothing worst-case page reservation for one admission.
        With the prefix cache on, the cached full-page prompt prefix is
        slot-ref'd (not re-allocated) and only the remainder comes from
        the pool — evicting idle cache entries under pressure before
        giving up."""
        total = req.prompt_len + req.g_max
        n_total = self.mgr.pages_for(total)
        if self.prefix is None:
            return self.mgr.alloc(n_total)
        shared, covered = self.prefix.acquire(req.prompt)
        need = n_total - len(shared)
        fresh = (self.mgr.alloc(need) if need > 0
                 else np.zeros((0,), np.int32))
        if fresh is None:
            self.prefix.evict_for(need)
            fresh = self.mgr.alloc(need)
        if fresh is None:
            if shared:
                # roll the acquire back. If the evict_for above dropped
                # the shared pages' entries, ours were the last refs and
                # release hands the orphans back — free them, or the
                # pool shrinks a page per failed admission
                back = self.prefix.release(req.prompt[:covered],
                                           np.asarray(shared, np.int32))
                if back.size:
                    self.mgr.free(back)
            return None
        pages = np.concatenate(
            [np.asarray(shared, np.int32), fresh]) if shared else fresh
        self.prefix.publish(req.prompt, pages, n_acquired=len(shared))
        return pages

    def _admit(self) -> bool:
        """Admit queued requests into free slots, up to one prefill batch.
        All-or-nothing page reservation per request (worst case: prompt +
        budget), head-of-line: a request that doesn't fit WAITS — it never
        preempts pages or slots from in-flight requests."""
        if not self.queue:
            return False  # hot path: nothing to admit, skip the slot scan
        free = [s for s in range(len(self.slots)) if self.slots[s] is None]
        batch: List[tuple] = []
        while (self.queue and free
               and len(batch) < self.engine.prefill_batch):
            req = self.queue[0]
            pages = self._reserve_pages(req)
            if pages is None:
                break  # pool exhausted: wait for completions to free pages
            slot = free.pop(0)
            self.queue.popleft()
            self.block_tables[slot, :] = TRASH_PAGE
            self.block_tables[slot, :len(pages)] = pages
            self.active[slot] = 1
            self.slots[slot] = _SlotState(req=req, pages=pages,
                                          position=req.prompt_len)
            self._dirty = True
            batch.append((slot, req))
        if not batch:
            return False
        bp, lp = self.engine.prefill_batch, self.engine.max_prompt_len
        ids = np.zeros((bp, lp), np.int32)
        lens = np.zeros((bp,), np.int32)
        smap = np.full((bp,), -1, np.int32)
        stables = np.zeros((bp, self.engine.pages_per_slot), np.int32)
        for i, (slot, req) in enumerate(batch):
            ids[i, :req.prompt_len] = req.prompt
            lens[i] = req.prompt_len
            smap[i] = slot
            stables[i] = self.block_tables[slot]
        toks = self.engine.prefill(ids, lens, smap, stables)
        if self._draft_engine is not None:
            # mirror the admission into the draft pool (its own static
            # tables); the draft's first-token pick is irrelevant — every
            # spec round pushes the authoritative host state first
            dstables = np.zeros_like(stables)
            for i, (slot, _) in enumerate(batch):
                dstables[i] = self._draft_tables[slot]
            self._draft_engine.prefill(ids, lens, smap, dstables)
        self.prefill_steps += 1
        # padding accounting: actual prompt tokens vs the padded
        # [prefill_batch, max_prompt_len] shape the executable ran at
        self.prompt_tokens_prefilled += int(lens.sum())
        self.prefill_token_slots += bp * lp
        self._ring.append((toks, list(batch)))
        # a budget-1 request is already complete at dispatch level
        for slot, _ in batch:
            st = self.slots[slot]
            if st is not None and st.generated >= st.req.g_max:
                self._release(slot)
        return True

    def step(self) -> bool:
        """One scheduler tick: sweep EOS completions -> admit -> dispatch
        decode -> lagged fetch. Returns False when nothing advanced (idle:
        no queue, no active slots, no pending fetches). Under sanitize the
        tick runs inside the evidence watcher: the engine's own transfer
        guard still raises on an implicit transfer, but the trip's site
        lands in the report on the way out."""
        with (self.sanitize_report.watch() if self.sanitize
              else contextlib.nullcontext()):
            return self._step_inner()

    def _step_inner(self) -> bool:
        # EOS sweep: requests finished by content (observed at fetch, one
        # step late) release their slot before new work is admitted. Only
        # when a fetch actually flagged one — count-based completions
        # release inline at dispatch time.
        if self._needs_sweep:
            for slot, st in enumerate(self.slots):
                if st is not None and st.req.finished:
                    self._release(slot)
            self._needs_sweep = False
        # admit until the queue, the free slots, or the page pool runs out
        # (several prefill batches per tick when a burst arrives): decode
        # windows then run at full occupancy instead of ramping one
        # prefill batch per window
        dispatched = False
        while self._admit():
            dispatched = True
        if self.spec_tokens > 0:
            # speculative path: synchronous rounds (the verify result IS
            # next round's input), so drain the prefill ring first — the
            # round needs every slot's current token host-side — and
            # sweep any EOS the fetch flagged before dispatching
            if self._ring:
                self._fetch(0)
            if self._needs_sweep:
                for slot, st in enumerate(self.slots):
                    if st is not None and st.req.finished:
                        self._release(slot)
                self._needs_sweep = False
            if self.active.any():
                self._spec_round()
                dispatched = True
            if self.sanitize and self._recompiles_at_first_token is None \
                    and self.tokens_fetched > 0:
                self._recompiles_at_first_token = self._recompiles.count
            return dispatched
        if self.active.any():
            if self._dirty:
                self.engine.set_block_tables(self.block_tables)
                self.engine.set_active(self.active)
                self._dirty = False
            snap = [(s, st.req) for s, st in enumerate(self.slots)
                    if st is not None and self.active[s]]
            toks = self.engine.decode()
            span = self.engine.decode_span
            self.decode_steps += 1
            # occupancy accounting: active vs compiled slot-steps this
            # dispatch (inactive slots run anyway, writing to trash —
            # the decode-side padding waste)
            self.slot_steps_active += int(self.active.sum()) * span
            self.slot_steps_total += len(self.slots) * span
            self._ring.append((toks, snap))
            for s, _ in snap:
                st = self.slots[s]
                # mirrors advance by the full span (the device does,
                # unconditionally, while the slot is active); a budget hit
                # mid-span overshoots harmlessly — see DecodeEngine
                st.generated += span
                st.position += span
                if st.generated >= st.req.g_max:  # budget spent:
                    self._release(s)          # completion, no fetch needed
            dispatched = True
        # Lagged on busy ticks (the overlap); full drain on idle ticks —
        # with nothing left to dispatch there is no step to hide the
        # fetch behind, and drain() must be able to terminate.
        self._fetch(self.dispatch_lag if dispatched else 0)
        if self.sanitize and self._recompiles_at_first_token is None \
                and self.tokens_fetched > 0:
            # serving steady-state boundary: everything compiled so far
            # was warmup; growth beyond this snapshot is a violation
            self._recompiles_at_first_token = self._recompiles.count
        return dispatched or bool(self._ring)

    def _spec_round(self) -> None:
        """One speculative round: propose K -> verify in one dispatch ->
        walk acceptance -> roll back host mirrors. Page/slot bookkeeping
        is untouched relative to the sequential path: pages were reserved
        worst-case at admission, rejected links only wrote rows past the
        live position inside those reserved pages (or trash), and the
        rolled-back position masks them until they are overwritten — the
        decode-span overshoot contract, so no leak is possible (tested:
        tests/test_spec_decode.py)."""
        if self._dirty:
            self.engine.set_block_tables(self.block_tables)
            self.engine.set_active(self.active)
            if self._draft_engine is not None:
                self._draft_engine.set_active(self.active)
            self._dirty = False
        S = len(self.slots)
        K = self.spec_tokens
        cur_tok = np.zeros((S,), np.int32)
        cur_pos = np.zeros((S,), np.int32)
        snap: List[tuple] = []
        for s, st in enumerate(self.slots):
            if st is None or not self.active[s]:
                continue
            cur_tok[s] = st.req.tokens[-1]   # last fetched = current state
            cur_pos[s] = st.position
            snap.append((s, st))
        draft = np.zeros((K, S), np.int32)
        if self._draft_engine is not None:
            # chain K greedy draft steps: the draft engine feeds its own
            # picks (decode_fn advances its state), exactly the chain the
            # target will verify
            self._draft_engine.set_decode_state(cur_tok, cur_pos)
            handles = [self._draft_engine.decode() for _ in range(K)]
            for j, h in enumerate(handles):
                draft[j] = np.asarray(jax.device_get(h))
        else:
            for s, st in snap:
                hist = np.concatenate(
                    [st.req.prompt, np.asarray(st.req.tokens, np.int32)])
                draft[:, s] = ngram_propose(hist, K)
        seq = np.asarray(jax.device_get(
            self.engine.verify(draft, cur_tok, cur_pos)))
        self.decode_steps += 1
        self.spec_rounds += 1
        self.slot_steps_active += len(snap) * (K + 1)
        self.slot_steps_total += S * (K + 1)
        for s, st in snap:
            req = st.req
            kept = 0
            matched = 0
            for j in range(K + 1):
                tok = int(seq[j, s])
                # row j is valid only while every earlier draft link
                # matched; the walk below never reaches an invalid row
                req.tokens.append(tok)
                self.tokens_fetched += 1
                kept += 1
                if req.eos_id is not None and tok == req.eos_id:
                    req.finished = True     # EOS inside an accepted
                elif len(req.tokens) >= req.g_max:
                    req.finished = True     # prefix wins over the draft
                if req.finished:
                    break
                if j < K and int(draft[j, s]) == tok:
                    matched += 1
                    continue
                break                        # first mismatch: reject suffix
            st.generated += kept
            st.position += kept
            self.draft_proposed += K
            self.draft_accepted += matched
            if req.finished:
                self._release(s)

    def _fetch(self, lag: int) -> None:
        """Drain the fetch ring down to ``lag`` entries, attributing each
        fetched token vector to its snapshot's requests. The device_get here
        is the only host<->device sync in the loop — and it blocks on step
        N-lag while step N executes (the PR 5 overlap)."""
        while len(self._ring) > lag:
            toks_dev, snap = self._ring.popleft()
            arr = np.asarray(jax.device_get(toks_dev))
            rows = arr if arr.ndim == 2 else arr[None]  # [span|1, S]
            now = time.perf_counter()
            for slot, req in snap:
                if req.finished:
                    continue
                for row in rows:
                    tok = int(row[slot])
                    req.tokens.append(tok)
                    self.tokens_fetched += 1
                    if req.ttft_s is None:
                        req.ttft_s = now - req.submit_t
                        self.ttft.add(req.ttft_s)
                    if req.eos_id is not None and tok == req.eos_id:
                        req.finished = True
                        self._needs_sweep = True  # slot may still be held
                    elif len(req.tokens) >= req.g_max:
                        req.finished = True  # overshoot rows are discarded
                    if req.finished:
                        break

    def drain(self) -> None:
        """Run until every submitted request has completed and every token
        has been fetched. Bounded by construction: admitted requests hold
        reserved pages, so each completes in ``g_max`` steps, freeing
        capacity for the queue."""
        while self.busy:
            if not self.step():
                break
        self._fetch(0)


def one_shot_decode(workload, params, ids: np.ndarray, prompt_len: int, *,
                    temperature: float = 0.0, top_k: int = 0,
                    top_p: float = 0.0, rng: Optional[jax.Array] = None,
                    seed: int = 0, page_size: int = 0, mesh=None,
                    server: Optional[DecodeServer] = None) -> np.ndarray:
    """Batch continuation through the SERVING path: the same prefill/decode
    executables that serve traffic, driven as one lockstep batch — one code
    path for one-shot (run/sample.py) and served decode.

    ``ids`` int [B, L]: positions < ``prompt_len`` are the prompts; the
    suffix is regenerated out to L. Greedy output is token-for-token
    identical to ``models.sampling.gpt2_decode`` (tested); stochastic
    decoding folds the key per slot position (the serving convention).
    Pass ``server`` to reuse compiled executables across calls (the
    engine's state fully recycles between drained batches); by default one
    is built with a single page per slot (``page_size = L``)."""
    ids = np.ascontiguousarray(ids, np.int32)
    b, l = ids.shape
    if not 1 <= prompt_len < l:
        if prompt_len == l:
            return ids.copy()
        raise ValueError(f"prompt_len {prompt_len} outside [1, {l}]")
    if server is None:
        # max_prompt_len = L: the prefill runs at the same padded length as
        # gpt2_decode's full-ids prefill, so the masked-softmax reductions
        # have identical shapes and greedy outputs match token for token
        server = DecodeServer(
            workload, params, decode_slots=b, page_size=page_size or l,
            max_prompt_len=l, max_len=l, prefill_batch=b,
            temperature=temperature, top_k=top_k, top_p=top_p,
            rng=rng, seed=seed, mesh=mesh)
    elif rng is not None:
        server.set_rng(rng)
    reqs = [server.submit(ids[i, :prompt_len],
                          max_new_tokens=l - prompt_len) for i in range(b)]
    server.drain()
    out = ids.copy()
    for i, req in enumerate(reqs):
        gen = np.asarray(req.tokens, np.int32)
        out[i, prompt_len:prompt_len + len(gen)] = gen
    return out
