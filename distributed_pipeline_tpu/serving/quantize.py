"""Quantized serving weights: int8 storage with a roll-out safety guard.

Replica weights are QUANTIZED AT LOAD (and at every hot-swap restore):
each param leaf is rounded to symmetric int8 — per-output-channel scales
for matrices, per-tensor for vectors — and immediately dequantized back to
its original dtype. Storage/wire quantization, not compute quantization:
the tree that reaches the engine has the exact dtypes/shapes the AOT
signatures were pinned against, so no recompile and no sharding churn; the
serving forward just runs on weights that have lost sub-scale precision
(the production pattern for shipping checkpoints to replicas at half/quarter
size — PAPERS: Gemma on Cloud TPU).

THE GUARD is the point (ISSUE 20c): :func:`quantize_params` measures the
round-trip error of every leaf and raises :class:`QuantizationError` when
any leaf is non-finite or its relative error exceeds ``max_rel_err`` — a
corrupt or pathological checkpoint fails INSIDE the worker's load/restore
path. Under the r13 hot-swap canary that exception makes the canary
replica's ack fail, the fleet keeps the old weights, and a bad quantization
can never take more than the canary.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["QuantizationError", "quantize_params", "quantize_leaf"]

Q8_MAX = 127.0


class QuantizationError(RuntimeError):
    """A leaf failed the round-trip guard: abort the load/swap."""


def quantize_leaf(x: jnp.ndarray, max_rel_err: float) -> jnp.ndarray:
    """int8 round-trip one leaf, guarded. Channel scales along the LAST
    axis for ndim >= 2 (the output-feature axis of this repo's kernels),
    per-tensor for vectors/scalars; all-zero channels pass through."""
    arr = np.asarray(x)
    if not np.issubdtype(arr.dtype, np.floating):
        return x  # int tables (none today) ship verbatim
    f = arr.astype(np.float32)
    if not np.all(np.isfinite(f)):
        raise QuantizationError(f"non-finite leaf {arr.shape} {arr.dtype}")
    axes = tuple(range(arr.ndim - 1)) if arr.ndim >= 2 else None
    amax = np.max(np.abs(f), axis=axes, keepdims=arr.ndim >= 2)
    scale = amax / Q8_MAX
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(np.round(f / safe), -Q8_MAX, Q8_MAX)
    deq = (q * safe).astype(np.float32)
    denom = max(float(np.max(np.abs(f))), 1e-12)
    err = float(np.max(np.abs(deq - f))) / denom
    # int8 symmetric round-trip error is <= scale/2 per element, i.e.
    # ~0.4% of the channel max — anything past the bound means the leaf's
    # distribution (or the checkpoint bytes) is broken, not borderline
    if err > max_rel_err:
        raise QuantizationError(
            f"leaf {arr.shape} round-trip rel err {err:.4f} > "
            f"{max_rel_err:.4f}")
    return jnp.asarray(deq.astype(arr.dtype))


def quantize_params(params: Any, max_rel_err: float = 0.02) -> Any:
    """Quantize every float leaf of a param tree (see module docstring).
    Raises :class:`QuantizationError` on the first failing leaf."""
    return jax.tree_util.tree_map(
        lambda x: quantize_leaf(x, max_rel_err), params)
