"""SLO-driven autoscaler: replica capacity that tracks live traffic.

The control loop reads two live signals every :meth:`AutoScaler.step` —
the router's pending backlog and the p95 TTFT over a trailing window of
COMPLETED requests — and compares them against the SLO target:

* SCALE UP when the fleet is visibly behind (backlog per ready replica
  above ``up_backlog``, or windowed p95 TTFT above ``slo_ttft_s``), via
  :meth:`~.fleet.ServingFleet.add_replica` — the existing elastic
  supervision + warmup-before-ready machinery means the new replica
  takes zero traffic until its compile/warmup is done, so a scale-up can
  never make latency WORSE while it boots.
* SCALE DOWN when the fleet is idle (zero backlog AND p95 below
  ``down_frac * slo_ttft_s`` — the hysteresis band: the down threshold
  sits strictly below the up threshold so bursty traffic can't flap the
  fleet), via the hot-swap DRAIN path: stop placement on the victim,
  wait for its outstanding work to finish, then stop flag + retire. The
  victim must already be IDLE (zero outstanding) — a fleet whose every
  ready replica holds in-flight work is busy, not cold, no matter what
  the completion window says — so the drain is normally instant and
  scale-down never triggers a replay.

Both directions share a ``cooldown_s`` clamp (one structural change per
cooldown) and are journaled (``{"ev": "scale", ...}``) + span-traced, so
the decision trail survives in the same durable artifact as every
request.

``paid_idle`` accounting: replica-seconds that were UP but UNNEEDED —
ready replicas beyond ``min_replicas`` sitting with zero outstanding
work while the queue is empty. Accrued here (the only component that
knows "unneeded"), journaled as ``{"ev": "paid_idle", ...}`` deltas, and
re-booked out of ``serving`` by ``chaos.goodput.aggregate_serving`` the
same way replay is — ``accounted_frac`` stays 1.0 by construction. It is
the autoscaler's own report card: a perfect scaler drives it to ~0.

Import-light (stdlib only): runs in the jax-free fleet process, beside
the router, driven from the same poll loop that steps hot-swaps.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

from ..obs import trace as trace_lib

__all__ = ["AutoScaler"]


def _p95(values: List[float]) -> Optional[float]:
    if not values:
        return None
    vals = sorted(values)
    return vals[min(len(vals) - 1, int(0.95 * len(vals)))]


class AutoScaler:
    """Drive with :meth:`step` from the fleet poll loop; call
    :meth:`close` before the final goodput fold so accrued-but-unflushed
    ``paid_idle`` reaches the journal."""

    def __init__(self, fleet, router, *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 slo_ttft_s: float = 10.0,
                 up_backlog: float = 2.0,
                 down_frac: float = 0.5,
                 cooldown_s: float = 5.0,
                 window_s: float = 30.0,
                 drain_timeout_s: float = 60.0,
                 journal_path: Optional[str] = None,
                 tracer=None) -> None:
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min ({min_replicas}) <= max ({max_replicas})")
        if not 0.0 <= down_frac < 1.0:
            raise ValueError(f"down_frac must be in [0, 1), got {down_frac}"
                             " — the hysteresis band would invert")
        self.fleet = fleet
        self.router = router
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.slo_ttft_s = slo_ttft_s
        self.up_backlog = up_backlog
        self.down_frac = down_frac
        self.cooldown_s = cooldown_s
        self.window_s = window_s
        self.drain_timeout_s = drain_timeout_s
        self.journal_path = (journal_path if journal_path is not None
                             else router.journal_path)
        self.tracer = tracer if tracer is not None else trace_lib.NULL
        self.scale_ups = 0
        self.scale_downs = 0
        self.paid_idle_s = 0.0          # journaled total
        self._unflushed: Dict[int, float] = {}   # rid -> accrued idle s
        self._last_scale_mono: Optional[float] = None
        self._last_step_mono: Optional[float] = None
        self._last_flush_mono = time.monotonic()
        self._draining_rid: Optional[int] = None
        self._drain_t0: Optional[float] = None

    # ------------------------------------------------------------- journal

    def _journal(self, event: dict) -> None:
        try:
            with open(self.journal_path, "a") as f:
                f.write(json.dumps(event) + "\n")
        except OSError:
            pass  # telemetry degrades, scaling still works

    def _flush_idle(self, now: float) -> None:
        for rid, idle in sorted(self._unflushed.items()):
            if idle > 0.0:
                self._journal({"ev": "paid_idle", "replica": rid,
                               "idle_s": round(idle, 6), "t": now})
                self.paid_idle_s += idle
        self._unflushed.clear()
        self._last_flush_mono = time.monotonic()

    # ---------------------------------------------------------------- step

    def _active(self) -> List[int]:
        return [rid for rid in self.router.clients
                if not self.router.down(rid)]

    def _capacity(self) -> int:
        """Replicas that count against ``max_replicas``: everything not
        down, PLUS down replicas whose supervising ring is still alive
        (crash-looping — the restart budget may bring them back). A
        retired or budget-exhausted replica is down with a dead ring and
        stops counting, so a drain never eats scale-up headroom and a
        permanently dead replica can be replaced."""
        return sum(1 for rid in self.router.clients
                   if not self.router.down(rid) or self.fleet.alive(rid))

    def _ready_active(self) -> List[int]:
        ready = set(self.fleet.ready_replicas())
        return [rid for rid in self._active() if rid in ready]

    def _cooled(self, mono: float) -> bool:
        return (self._last_scale_mono is None
                or mono - self._last_scale_mono >= self.cooldown_s)

    def step(self, now: Optional[float] = None) -> None:
        """One control decision (at most one structural change per call,
        and none while a hot-swap roll owns the drain machinery)."""
        now = time.time() if now is None else now
        mono = time.monotonic()
        dt = (0.0 if self._last_step_mono is None
              else max(0.0, mono - self._last_step_mono))
        self._last_step_mono = mono

        ready = self._ready_active()
        backlog = self.router.backlog

        # paid_idle accrual: ready replicas beyond the floor, idle, with
        # nothing queued — capacity nobody needed this interval. Charged
        # to the highest rids (the ones a scale-down would pick).
        if dt > 0.0 and backlog == 0:
            idle = sorted((r for r in ready
                           if self.router.outstanding(r) == 0),
                          reverse=True)
            for rid in idle[:max(0, len(ready) - self.min_replicas)]:
                self._unflushed[rid] = self._unflushed.get(rid, 0.0) + dt
        if self._unflushed and mono - self._last_flush_mono >= 2.0:
            self._flush_idle(now)

        if getattr(self.fleet, "swap_active", False):
            return

        # finish an in-progress drain-down before any new decision
        if self._draining_rid is not None:
            rid = self._draining_rid
            timed_out = (self._drain_t0 is not None
                         and mono - self._drain_t0 > self.drain_timeout_s)
            if (not self.fleet.alive(rid) or self.router.down(rid)
                    or self.router.outstanding(rid) == 0 or timed_out):
                self.fleet.stop_replica(rid)
                self.router.retire(rid)
                self.scale_downs += 1
                self._draining_rid = None
                self._drain_t0 = None
                self._last_scale_mono = mono
                self._journal({"ev": "scale", "dir": "down",
                               "replica": rid, "t": now,
                               "drained": not timed_out,
                               "n_active": len(self._active())})
                if self.tracer.enabled:
                    self.tracer.instant("scale_down", "autoscale",
                                        args={"replica": rid,
                                              "drained": not timed_out})
            return

        n_active = len(self._active())
        p95 = _p95(self.router.recent_ttfts(self.window_s, now))
        n_ready = max(1, len(ready))

        hot = (backlog > self.up_backlog * n_ready
               or (p95 is not None and p95 > self.slo_ttft_s))
        # the ceiling counts supervised capacity (``_capacity``), not
        # just healthy replicas: a crash-looping fleet is hot (backlog
        # grows, nothing ready) but its down replicas still own restart
        # budget — gating on healthy-only spawned a fresh ring every
        # cooldown for as long as an outage lasted (caught live: 13
        # scale-ups, 14 replica dirs, with max_replicas=2)
        if hot and self._capacity() < self.max_replicas and self._cooled(mono):
            rid = self.fleet.add_replica()
            self.router.add_client(rid, self.fleet.client(rid))
            self.scale_ups += 1
            self._last_scale_mono = mono
            reason = ("backlog" if backlog > self.up_backlog * n_ready
                      else "ttft_p95")
            self._journal({"ev": "scale", "dir": "up", "replica": rid,
                           "t": now, "reason": reason,
                           "backlog": backlog,
                           "ttft_p95_s": p95,
                           "n_active": n_active + 1})
            if self.tracer.enabled:
                self.tracer.instant("scale_up", "autoscale",
                                    args={"replica": rid, "reason": reason,
                                          "backlog": backlog})
            return

        cold = (backlog == 0
                and (p95 is None or p95 < self.down_frac * self.slo_ttft_s))
        if cold and n_active > self.min_replicas and self._cooled(mono):
            # victim: the highest-rid IDLE ready replica. Requiring an
            # idle victim also keeps a warming fleet honest — right
            # after startup p95 is None with everything in flight, and
            # busy replicas must not drain on that empty signal. The
            # drain-first machinery stays as the guard for work placed
            # in the same poll round (draining gates placement at once).
            victims = [r for r in ready
                       if not self.router.draining(r)
                       and self.router.outstanding(r) == 0]
            if not victims:
                return
            victim = max(victims)
            self.router.set_draining(victim, True)
            self._draining_rid = victim
            self._drain_t0 = mono

    # --------------------------------------------------------------- close

    def close(self, now: Optional[float] = None) -> None:
        """Flush accrued paid_idle and un-drain any half-finished victim
        (shutdown interrupts the drain; the fleet-wide stop takes over)."""
        if self._draining_rid is not None:
            self.router.set_draining(self._draining_rid, False)
            self._draining_rid = None
        self._flush_idle(time.time() if now is None else now)

    def summary(self) -> dict:
        return {
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "paid_idle_s": round(
                self.paid_idle_s + sum(self._unflushed.values()), 4),
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "n_active": len(self._active()),
        }
